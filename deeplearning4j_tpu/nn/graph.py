"""ComputationGraph — DAG network runtime.

TPU-native equivalent of deeplearning4j-nn/.../nn/graph/ComputationGraph.java
(3363 LoC): topologicalSortOrder :1190, fit :837, feedForward :1361 (topo-order
vertex loop), calcBackpropGradients :1629 (replaced by jax.grad), output :1532.

The whole DAG forward compiles into one XLA program under jit; the reference's
LOOP_* workspaces (:100-126) are replaced by XLA buffer assignment + donation.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.nn.conf.graph_conf import LayerVertex
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    STREAM_STATE_KEYS, BaseOutputLayerConf, CenterLossOutputLayer,
    stream_capacity)
from deeplearning4j_tpu.nn.conf.network import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.score import LazyScore
from deeplearning4j_tpu.nn.updater import normalize_gradients
from deeplearning4j_tpu.monitoring import ensure_started
from deeplearning4j_tpu.monitoring.listener import (
    finalize_fit_telemetry, maybe_record_fit_iteration)
from deeplearning4j_tpu.monitoring.tracing import phase_detail, span
from deeplearning4j_tpu.nn.multilayer import _strip_stream_state, _tree_sub
from deeplearning4j_tpu.optimize.listeners import close_listeners
from deeplearning4j_tpu.pipeline.padding import (
    group_signature, num_real_examples, pad_batch, with_example_weights)
from deeplearning4j_tpu.resilience.durable import (
    capture_cursor_pass, consume_restored_cursor, dispatch_boundary)
from deeplearning4j_tpu.resilience.sentinel import (
    apply_step, effective_policy, guard_updates, tree_finite)

log = logging.getLogger(__name__)


from deeplearning4j_tpu.nn.compute import f32_head as _f32_head  # noqa: E402


class ComputationGraph(LazyScore):
    """DAG network with fit/output/evaluate (ref: ComputationGraph.java)."""

    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.params: Dict[str, Any] = {}
        self.state: Dict[str, Any] = {}
        self.updater_state: Dict[str, Any] = {}
        self.listeners: List = []
        self.iteration_count = 0
        self.epoch_count = 0
        self.score_value = float("nan")
        self._rng = None
        self._jit_cache: Dict[Any, Any] = {}
        self._initialized = False
        self._topo = conf.topological_order()
        self._vertex_input_types: Dict[str, List[InputType]] = {}
        self.fuse_bn_act_conv = False
        self._fusion_cache = None
        # execution-plan refinements (tuning/plan.py): restrict the
        # bottleneck plan to a chosen block subset and/or engage the
        # fused space-to-depth stem (nn/layers/stem.py)
        self._fusion_only = None
        self._fuse_stem = False
        # the matchers' VMEM gates consult conf.dtype, so both plan
        # caches are dtype-stamped: flipping dtype after construction
        # (the bench builds at f32 then sets bf16) recomputes them
        self._fusion_dtype = None
        self._candidates_cache = None
        # listener capability flags, hoisted to fit-loop setup (None =
        # not inside fit(): _fit_batch recomputes for direct callers)
        self._stash_features: Optional[bool] = None
        # non-finite sentinel policy override (None = process default;
        # see resilience/sentinel.py)
        self.nonfinite_policy: Optional[str] = None
        # durable-state plumbing (resilience/durable.py) — see
        # MultiLayerNetwork.__init__
        self._dispatched_in_epoch = 0
        self._canon_in_epoch: Optional[int] = None
        self._restored_pipeline_state: Optional[Dict[str, Any]] = None
        self._cursor_pass: Optional[int] = None  # pass index mid-fit
        self._preemption_guard = None

    # ------------------------------------------------------------------
    # bn→act→conv1x1 fusion (execution-plan optimization, see
    # nn/layers/fused.py — params/state stay keyed by the original vertex
    # names, so serialization/import/transfer are unaffected)
    # ------------------------------------------------------------------
    def set_fusion(self, enabled=True, *, stem=False, only=None):
        """Select the fused execution plan: False (unfused — the
        measured-best default), True (bn→act→1×1-conv groups,
        nn/layers/fused.py), or "bottleneck" (whole identity-bottleneck
        chains through the Pallas kernel cascade,
        nn/layers/bottleneck.py). Changes how eligible chains execute,
        not what they compute (equivalence is test-pinned); jitted steps
        are rebuilt only when the resolved plan actually changes, so
        re-resolving the same plan per fit() call never retraces.

        ``only`` (bottleneck level) restricts fusion to the named block
        output vertices — the per-shape "auto" resolution seam
        (tuning/plan.py): the crossover store decides block by block and
        passes the winners here. ``stem`` additionally engages the fused
        space-to-depth stem (nn/layers/stem.py) on a matching
        pad→7×7/2-conv→BN→relu→3×3/2-maxpool chain."""
        if enabled not in (False, True, "bottleneck"):
            raise ValueError(
                f"unknown fusion level {enabled!r}: expected False, True "
                "or 'bottleneck'")
        if stem and enabled != "bottleneck":
            raise ValueError(
                "stem=True rides the 'bottleneck' fusion level (the "
                "fused-kernel execution plan)")
        only = None if only is None else frozenset(only)
        sig = (enabled, bool(stem), only)
        if sig != (self.fuse_bn_act_conv, self._fuse_stem,
                   self._fusion_only):
            self.fuse_bn_act_conv = enabled
            self._fuse_stem = bool(stem)
            self._fusion_only = only
            self._jit_cache.clear()
            self._fusion_cache = None
        return self

    def _fusion(self):
        """(plan, skip): plan maps a 1×1-conv vertex name to the fused
        group executing (bn → activation → conv) in one op; skip maps the
        absorbed bn/activation vertex names to their consuming conv.

        Eligibility (conservative — anything else runs unfused): a
        BatchNormalization vertex, optionally followed by an
        ActivationLayer (or its own activation), feeding a kernel-1×1 /
        stride-1 / pad-0 / dilation-1 ConvolutionLayer; every
        intermediate has a single consumer, no preprocessors/dropout, is
        not a network output, and the prologue activation is relu or
        identity (the Pallas kernel's fast set)."""
        if not self.fuse_bn_act_conv:
            return {}, {}, {}
        if self._fusion_cache is not None and \
                self._fusion_dtype == self.conf.dtype:
            return self._fusion_cache[:3]
        self._fusion_dtype = self.conf.dtype
        if self.fuse_bn_act_conv == "bottleneck":
            skip, bplan = self._bottleneck_fusion(self._fusion_only)
            splan = self._stem_fusion() if self._fuse_stem else {}
            for out_name, group in splan.items():
                for m in group["members"]:
                    skip[m] = out_name
            self._fusion_cache = ({}, skip, bplan, splan)
            return self._fusion_cache[:3]
        from deeplearning4j_tpu.nn.conf.layers import (
            ActivationLayer, BatchNormalization, ConvolutionLayer)
        consumers, layer_of = self._fusion_graph_view()
        plan: Dict[str, Tuple[str, str, str]] = {}
        skip: Dict[str, str] = {}
        for bn_name in self._topo:
            bn = layer_of(bn_name, BatchNormalization)
            if bn is None:
                continue
            if len(self.conf.vertex_inputs.get(bn_name, [])) != 1:
                continue
            if self._vertex_input_types[bn_name][0].kind != "cnn":
                continue
            cons = consumers.get(bn_name, [])
            if len(cons) != 1:
                continue
            nxt, act_vertex = cons[0], None
            act = bn.activation or "identity"
            al = layer_of(nxt, ActivationLayer)
            if al is not None:
                if act != "identity":
                    continue
                acons = consumers.get(nxt, [])
                if len(acons) != 1:
                    continue
                act_vertex, act, nxt = nxt, al.activation, acons[0]
            conv = layer_of(nxt, ConvolutionLayer)
            if (conv is None or act not in ("relu", "identity")
                    or tuple(conv.kernel) != (1, 1)
                    or tuple(conv.stride) != (1, 1)
                    or tuple(conv.padding) != (0, 0)
                    or tuple(conv.dilation) != (1, 1)
                    or conv.convolution_mode not in ("truncate", "same")
                    or conv.data_format != bn.data_format):
                continue
            if self.conf.vertex_inputs.get(nxt) != [act_vertex or bn_name]:
                continue
            src = self.conf.vertex_inputs[bn_name][0]
            plan[nxt] = (bn_name, act, src)
            skip[bn_name] = nxt
            if act_vertex is not None:
                skip[act_vertex] = nxt
        self._fusion_cache = (plan, skip, {}, {})
        return self._fusion_cache[:3]

    def _fusion_graph_view(self):
        """Shared matcher scaffolding for the fusion plans: the
        (consumers map, layer_of helper) both pattern matchers walk.
        layer_of(n, cls) returns the vertex n's layer iff it is a plain
        LayerVertex of exactly `cls` with no preprocessor/dropout and is
        not a network output — anything else is ineligible for fusion."""
        self._infer_types()
        consumers: Dict[str, List[str]] = {}
        for cname, srcs in self.conf.vertex_inputs.items():
            for s in srcs:
                consumers.setdefault(s, []).append(cname)
        outputs = set(self.conf.network_outputs)

        def layer_of(n, cls):
            v = self.conf.vertices.get(n)
            if (not isinstance(v, LayerVertex) or v.preprocessor is not None
                    or n in outputs):
                return None
            l = v.layer
            return l if type(l) is cls and not l.dropout else None

        return consumers, layer_of

    def _stem_plan(self):
        """splan for the fused space-to-depth stem: output (pool) vertex
        name → group. Populated only at level "bottleneck" with
        stem=True (set_fusion)."""
        self._fusion()          # populate the cache
        return self._fusion_cache[3] if self._fusion_cache else {}

    def _bottleneck_fusion(self, only=None):
        """(skip, bplan) for fuse level "bottleneck": bplan maps the
        final relu vertex of each IDENTITY bottleneck (conv1x1→bn→relu→
        conv3x3→bn→relu→conv1x1→bn→add(x)→relu, all stride 1, identity
        skip, NHWC) to its vertex group; skip maps every absorbed
        intermediate to that output vertex. Anything unmatched — entry
        blocks, other strides/layouts — runs unfused
        (nn/layers/bottleneck.py holds the kernels + eligibility
        rationale). ``only`` (a set of output-vertex names) keeps just
        the named blocks — the per-shape "auto" plan resolution."""
        from deeplearning4j_tpu.nn.conf.graph_conf import ElementWiseVertex
        from deeplearning4j_tpu.nn.conf.layers import (
            ActivationLayer, BatchNormalization, ConvolutionLayer)
        from deeplearning4j_tpu.nn.layers.bottleneck import (
            fused_bottleneck_supported)
        consumers, layer_of = self._fusion_graph_view()
        outputs = set(self.conf.network_outputs)

        def sole_consumer(n):
            c = consumers.get(n, [])
            return c[0] if len(c) == 1 else None

        def chain_next(n):
            """The one consumer of n, which must also have n as its ONE
            input (a second input would make the unfused vertex read a
            different xs[0] than the fused chain convolves). The residual
            add is the only legitimately multi-input consumer and is
            checked explicitly below."""
            c = sole_consumer(n)
            if c is None or self.conf.vertex_inputs.get(c, []) != [n]:
                return None
            return c

        def conv_ok(l, kernel, padding, stride=(1, 1)):
            return (l is not None and tuple(l.kernel) == kernel
                    and tuple(l.stride) == stride
                    and tuple(l.padding) == padding
                    and tuple(l.dilation) == (1, 1)
                    and not l.has_bias
                    and l.activation in (None, "identity")
                    and l.data_format == "NHWC")

        def walk_bn_act(name):
            """name is a conv; its single consumer must be bn (+ relu
            act vertex or bn relu activation). Returns (bn, act_vertex,
            following vertex) or None."""
            bn_name = chain_next(name)
            bn = bn_name and layer_of(bn_name, BatchNormalization)
            if bn is None or \
                    len(self.conf.vertex_inputs.get(bn_name, [])) != 1:
                return None
            nxt = chain_next(bn_name)
            if nxt is None:
                return None
            act = bn.activation or "identity"
            act_vertex = None
            al = layer_of(nxt, ActivationLayer)
            if al is not None and act == "identity":
                act_vertex, act = nxt, al.activation
                nxt = chain_next(act_vertex)
            if act != "relu" or nxt is None:
                return None
            return bn_name, act_vertex, nxt

        bplan: Dict[str, Dict[str, str]] = {}
        skip: Dict[str, str] = {}
        for ca_name in self._topo:
            conv_a = layer_of(ca_name, ConvolutionLayer)
            if conv_a is None:
                continue
            stride = tuple(conv_a.stride)
            if stride not in ((1, 1), (2, 2)) or \
                    not conv_ok(conv_a, (1, 1), (0, 0), stride):
                continue
            srcs = self.conf.vertex_inputs.get(ca_name, [])
            if len(srcs) != 1:
                continue
            src = srcs[0]
            it = self._vertex_input_types[ca_name][0]
            if it.kind != "cnn":
                continue
            w1 = walk_bn_act(ca_name)
            if w1 is None:
                continue
            bn_a, act_a, cb_name = w1
            conv_b = layer_of(cb_name, ConvolutionLayer)
            if not conv_ok(conv_b, (3, 3), (1, 1)):
                continue
            w2 = walk_bn_act(cb_name)
            if w2 is None:
                continue
            bn_b, act_b, cc_name = w2
            conv_c = layer_of(cc_name, ConvolutionLayer)
            if not conv_ok(conv_c, (1, 1), (0, 0)):
                continue
            bn_c_name = chain_next(cc_name)
            bn_c = bn_c_name and layer_of(bn_c_name, BatchNormalization)
            if bn_c is None or (bn_c.activation or "identity") != "identity":
                continue
            add_name = sole_consumer(bn_c_name)
            addv = add_name and self.conf.vertices.get(add_name)
            if (not isinstance(addv, ElementWiseVertex)
                    or addv.op.lower() != "add" or add_name in outputs):
                continue
            add_ins = self.conf.vertex_inputs.get(add_name, [])
            skip_group = {}
            if sorted(add_ins) == sorted([bn_c_name, src]):
                if stride != (1, 1):
                    continue          # strided main path needs a conv skip
            else:
                # downsample form: the other add input is src -> conv_skip
                # (1x1, same stride) -> bn_skip (identity activation)
                others = [i for i in add_ins if i != bn_c_name]
                if len(add_ins) != 2 or len(others) != 1:
                    continue
                bn_s_name = others[0]
                bn_s = layer_of(bn_s_name, BatchNormalization)
                if bn_s is None or \
                        (bn_s.activation or "identity") != "identity" or \
                        sole_consumer(bn_s_name) != add_name:
                    continue
                cs_in = self.conf.vertex_inputs.get(bn_s_name, [])
                if len(cs_in) != 1:
                    continue
                cs_name = cs_in[0]
                conv_s = layer_of(cs_name, ConvolutionLayer)
                if not conv_ok(conv_s, (1, 1), (0, 0), stride) or \
                        chain_next(cs_name) != bn_s_name or \
                        self.conf.vertex_inputs.get(cs_name, []) != [src]:
                    continue
                skip_group = {"conv_skip": cs_name, "bn_skip": bn_s_name}
            out_name = chain_next(add_name)
            out_act = out_name and layer_of(out_name, ActivationLayer)
            if out_act is None or out_act.activation != "relu":
                continue
            bns = [self.conf.vertices[n].layer
                   for n in ((bn_a, bn_b, bn_c_name)
                             + ((skip_group["bn_skip"],)
                                if skip_group else ()))]
            if len({(b.eps, b.decay) for b in bns}) != 1:
                continue
            if len({b.data_format for b in bns} | {"NHWC"}) != 1:
                continue
            # runtime-shape VMEM gate from the statically inferred types
            if not fused_bottleneck_supported(
                    (1, it.height, it.width, it.channels),
                    conv_a.n_out, conv_c.n_out,
                    self.conf.dtype or "float32",
                    stride=stride[0], has_skip=bool(skip_group)):
                continue
            if only is not None and out_name not in only:
                continue
            group = {"src": src, "conv_a": ca_name, "bn_a": bn_a,
                     "conv_b": cb_name, "bn_b": bn_b, "conv_c": cc_name,
                     "bn_c": bn_c_name, "add": add_name,
                     "stride": stride[0],
                     # shape metadata for the crossover fingerprint
                     # (tuning/plan.py) — unused by the apply path
                     "h": it.height, "w": it.width, "cin": it.channels,
                     "cmid": conv_a.n_out, "cout": conv_c.n_out,
                     **skip_group}
            members = [ca_name, bn_a, cb_name, bn_b, cc_name, bn_c_name,
                       add_name] + list(skip_group.values())
            if act_a:
                members.append(act_a)
            if act_b:
                members.append(act_b)
            if any(m in skip for m in members):
                continue
            bplan[out_name] = group
            for m in members:
                skip[m] = out_name
        return skip, bplan

    def _stem_fusion(self):
        """splan for the fused space-to-depth stem (nn/layers/stem.py):
        maps the maxpool vertex closing a
        [ZeroPadding(3,3,3,3) →] 7×7/2 pad-3 conv → BN → relu →
        3×3/2 pad-1 max-pool chain (NHWC, no bias, single consumers) to
        its vertex group. At most one chain matches (the stem consumes
        a network input resolution); everything else runs unfused."""
        from deeplearning4j_tpu.nn.conf.layers import (
            ActivationLayer, BatchNormalization, ConvolutionLayer,
            SubsamplingLayer, ZeroPaddingLayer)
        from deeplearning4j_tpu.nn.layers.stem import fused_stem_supported
        consumers, layer_of = self._fusion_graph_view()

        def sole_consumer(n):
            c = consumers.get(n, [])
            return c[0] if len(c) == 1 else None

        def chain_next(n):
            c = sole_consumer(n)
            if c is None or self.conf.vertex_inputs.get(c, []) != [n]:
                return None
            return c

        splan: Dict[str, Dict[str, Any]] = {}
        for cv_name in self._topo:
            conv = layer_of(cv_name, ConvolutionLayer)
            if (conv is None or tuple(conv.kernel) != (7, 7)
                    or tuple(conv.stride) != (2, 2)
                    or tuple(conv.dilation) != (1, 1)
                    or conv.has_bias
                    or conv.activation not in (None, "identity")
                    or conv.data_format != "NHWC"
                    or conv.convolution_mode != "truncate"):
                continue
            srcs = self.conf.vertex_inputs.get(cv_name, [])
            if len(srcs) != 1:
                continue
            members = [cv_name]
            pad_name = pre_vertex = None
            outputs = set(self.conf.network_outputs)
            if tuple(conv.padding) == (0, 0):
                # ZeroPadding(3,3,3,3) form (the zoo ResNet50 layout).
                # Matched by hand rather than layer_of: the pad vertex
                # legitimately carries the graph's input preprocessor
                # (FeedForwardToCnn), which the fused group absorbs.
                pad_name = srcs[0]
                pv = self.conf.vertices.get(pad_name)
                padl = pv.layer if (
                    isinstance(pv, LayerVertex)
                    and type(pv.layer) is ZeroPaddingLayer
                    and pad_name not in outputs
                    and not pv.layer.dropout) else None
                if (padl is None or tuple(padl._pads()) != (3, 3, 3, 3)
                        or padl.data_format != "NHWC"
                        or chain_next(pad_name) != cv_name):
                    continue
                if pv.preprocessor is not None:
                    pre_vertex = pad_name
                pin = self.conf.vertex_inputs.get(pad_name, [])
                if len(pin) != 1:
                    continue
                src = pin[0]
                it = self._vertex_input_types[pad_name][0]
                members.append(pad_name)
            elif tuple(conv.padding) == (3, 3):
                src = srcs[0]
                it = self._vertex_input_types[cv_name][0]
            else:
                continue
            if it.kind != "cnn":
                continue
            bn_name = chain_next(cv_name)
            bn = bn_name and layer_of(bn_name, BatchNormalization)
            if bn is None or \
                    len(self.conf.vertex_inputs.get(bn_name, [])) != 1:
                continue
            members.append(bn_name)
            nxt = chain_next(bn_name)
            act = bn.activation or "identity"
            if nxt is not None:
                al = layer_of(nxt, ActivationLayer)
                if al is not None and act == "identity":
                    members.append(nxt)
                    act = al.activation
                    nxt = chain_next(nxt)
            if act != "relu" or nxt is None:
                continue
            pool = layer_of(nxt, SubsamplingLayer)
            if (pool is None or pool.pooling_type.lower() != "max"
                    or tuple(pool.kernel) != (3, 3)
                    or tuple(pool.stride) != (2, 2)
                    or tuple(pool.padding) != (1, 1)
                    or pool.convolution_mode != "truncate"
                    or pool.data_format != "NHWC"):
                continue
            if not fused_stem_supported(
                    (1, it.height, it.width, it.channels), conv.n_out,
                    self.conf.dtype or "float32"):
                continue
            splan[nxt] = {"src": src, "conv": cv_name, "bn": bn_name,
                          "pre_vertex": pre_vertex,
                          "h": it.height, "w": it.width,
                          "cin": it.channels, "cout": conv.n_out,
                          "members": members}
        return splan

    def fusion_candidates(self):
        """Everything the fused execution plans COULD engage on this
        graph, independent of the currently selected plan: (bottleneck
        block groups, stem groups), each with the shape metadata the
        crossover fingerprints need (tuning/plan.py resolves
        ``execution_plan="auto"`` per candidate from the store). Pure
        read — no plan state is touched and no jitted step rebuilt;
        memoised per conf.dtype (the graph is fixed after construction
        but the VMEM gates are dtype-dependent), so per-fit plan
        re-resolution never re-walks the matchers."""
        cache = getattr(self, "_candidates_cache", None)
        if cache is None or cache[0] != self.conf.dtype:
            _, bplan = self._bottleneck_fusion(None)
            self._candidates_cache = (self.conf.dtype, bplan,
                                      self._stem_fusion())
        return self._candidates_cache[1:]

    # ------------------------------------------------------------------
    def _infer_types(self) -> Dict[str, InputType]:
        """Output InputType of every vertex, walking topo order.
        Memoised — the graph is fixed after construction, and per-token
        decode loops call this host-side."""
        if getattr(self, "_out_types_cache", None) is not None:
            return self._out_types_cache
        out_types: Dict[str, InputType] = {}
        for name, it in self.conf.input_types.items():
            out_types[name] = it
        for name in self._topo:
            ins = self.conf.vertex_inputs.get(name, [])
            its = [out_types[i] for i in ins if i in out_types]
            if len(its) != len(ins):
                missing = [i for i in ins if i not in out_types]
                raise ValueError(f"vertex {name}: missing input types for {missing} "
                                 "(call set_input_types on the builder)")
            self._vertex_input_types[name] = its
            out_types[name] = self.conf.vertices[name].output_type(its)
        self._out_types_cache = out_types
        return out_types

    def init(self):
        self._infer_types()
        key = jax.random.PRNGKey(self.conf.seed)
        self._rng = jax.random.PRNGKey(self.conf.seed + 1)
        keys = jax.random.split(key, max(2, len(self._topo)))
        self.params, self.state = {}, {}
        for i, name in enumerate(self._topo):
            v = self.conf.vertices[name]
            p, s = v.init(keys[i], self._vertex_input_types[name])
            self.params[name] = p
            self.state[name] = s
        self.updater_state = self.conf.updater.init_state(self.params)
        self._initialized = True
        return self

    def add_listener(self, listener):
        """Append a training listener (parity with MultiLayerNetwork)."""
        self.listeners.append(listener)
        return self

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def num_params(self) -> int:
        return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(self.params))

    # ------------------------------------------------------------------
    # forward
    # ------------------------------------------------------------------
    def _forward(self, params, state, inputs: Dict[str, Any], *, train, rng,
                 fmasks: Optional[Dict[str, Any]] = None, carry_rnn=False,
                 stream=False, pad=None, preout_of=None):
        """Topo-order forward (ref: feedForward :1361). Returns
        (vertex_activations dict, new_state, masks dict). `preout_of` is a
        vertex name or a collection of names whose output layers should
        yield pre-activation outputs — the loss computes every output's
        preout in this ONE pass (ref: computeGradientAndScore :1298 runs a
        single feedForward for all outputs).

        `pad` (traced scalar) marks a left-padded streaming chunk
        (single-input graphs): non-streaming vertices see an ordinary key
        mask; streaming cache layers get pad_left for packed slot
        accounting (pads never enter caches) — see
        SelfAttentionLayer._stream_attend."""
        preout_set = ({preout_of} if isinstance(preout_of, str)
                      else set(preout_of or ()))
        # inference honors the bf16 compute policy too (also applied by
        # _loss for reg in f32 — double application is a no-op): bf16
        # activations + weights halve HBM traffic and carried KV-cache
        # memory; output() / rnn_time_step cast final activations back
        # to f32 (f32_head)
        params, inputs = self._cast_compute(params, inputs)
        fused_plan, fused_skip, bneck_plan = self._fusion()
        stem_plan = self._stem_plan()
        acts: Dict[str, Any] = dict(inputs)
        masks: Dict[str, Any] = dict(fmasks or {})
        if pad is not None:
            masks = {name: jnp.broadcast_to(
                jnp.arange(a.shape[-1]) >= pad, (a.shape[0], a.shape[-1]))
                for name, a in inputs.items()}
        new_state: Dict[str, Any] = {}
        for i, name in enumerate(self._topo):
            v = self.conf.vertices[name]
            ins = self.conf.vertex_inputs.get(name, [])
            in_masks = [masks.get(i_) for i_ in ins]
            if name in fused_skip:
                # absorbed into a downstream fused conv: produce no
                # activation; masks still propagate, bn state is written
                # by the fused step
                masks[name] = v.output_mask(
                    in_masks, self._vertex_input_types[name])
                new_state[name] = state.get(name, {})
                continue
            if name in fused_plan:
                bn_name, p_act, src = fused_plan[name]
                self._apply_fused(name, bn_name, p_act, acts[src], params,
                                  state, new_state, acts, train=train)
                masks[name] = v.output_mask(
                    in_masks, self._vertex_input_types[name])
                continue
            if name in bneck_plan:
                self._apply_fused_bottleneck(
                    name, bneck_plan[name], params, state, new_state,
                    acts, train=train)
                masks[name] = v.output_mask(
                    in_masks, self._vertex_input_types[name])
                continue
            if name in stem_plan:
                self._apply_fused_stem(
                    name, stem_plan[name], params, state, new_state,
                    acts, train=train)
                masks[name] = v.output_mask(
                    in_masks, self._vertex_input_types[name])
                continue
            xs = [acts[i_] for i_ in ins]
            if getattr(v, "wants_all_masks", False):
                mask = in_masks      # e.g. cross attention: keys = input 1
            else:
                mask = next((m for m in in_masks if m is not None), None)
            v_state = state.get(name, {})
            if not carry_rnn:
                v_state = {k: val for k, val in v_state.items()
                           if k not in STREAM_STATE_KEYS}
            rng_i = jax.random.fold_in(rng, i) if rng is not None else None
            if name in preout_set and isinstance(v, LayerVertex) and \
                    hasattr(v.layer, "compute_score"):
                x = xs[0]
                if v.preprocessor is not None:
                    x = v.preprocessor.apply(x, mask)
                acts[name] = v.layer.preout(v.layer and params[name], x,
                                            train=train, rng=rng_i)
                new_state[name] = v_state
            else:
                # stream (inference KV-cache decode) is distinct from
                # carry_rnn (tbptt h/c carry)
                extra = {}
                m_i = mask
                if getattr(v, "supports_streaming", False):
                    extra["stream"] = stream
                    if pad is not None:
                        # packed accounting replaces the mask (see
                        # MultiLayerNetwork._forward)
                        extra["pad_left"] = pad
                        m_i = None
                y, s_new = v.apply(params[name], xs, v_state, train=train,
                                   rng=rng_i, mask=m_i, **extra)
                acts[name] = y
                new_state[name] = s_new
            masks[name] = v.output_mask(in_masks, self._vertex_input_types[name])
        return acts, new_state, masks

    def _apply_fused(self, conv_name, bn_name, p_act, y, params, state,
                     new_state, acts, *, train):
        """Execute one fused bn→act→conv1x1 group (see nn/layers/fused.py):
        y is the RAW activation feeding the bn vertex; writes the conv
        output into acts[conv_name] and the bn running stats into
        new_state[bn_name]."""
        from deeplearning4j_tpu.nn.layers.fused import bn_act_conv1x1
        from deeplearning4j_tpu.nn import activations as _act
        bn = self.conf.vertices[bn_name].layer
        conv = self.conf.vertices[conv_name].layer
        bn_params = params.get(bn_name, {})
        bn_state = state.get(bn_name, {})
        nf = bn_state["mean"].shape[0]
        gamma = bn_params.get("gamma", jnp.full((nf,), bn.gamma, y.dtype))
        beta = bn_params.get("beta", jnp.full((nf,), bn.beta, y.dtype))
        out, new_mean, new_var = bn_act_conv1x1(
            y, gamma, beta, bn_state["mean"], bn_state["var"],
            params[conv_name]["W"], params[conv_name].get("b"),
            train=train, eps=bn.eps, decay=bn.decay, act=p_act,
            data_format=conv.data_format)
        acts[conv_name] = _act.get(conv.activation)(out)
        new_state[bn_name] = ({"mean": new_mean, "var": new_var}
                              if train else bn_state)
        new_state[conv_name] = state.get(conv_name, {})

    def _apply_fused_bottleneck(self, out_name, group, params, state,
                                new_state, acts, *, train):
        """Execute one fused identity-bottleneck group (see
        nn/layers/bottleneck.py): reads the block input activation,
        writes the final relu output into acts[out_name] and each BN's
        running stats into new_state; params/state stay keyed by the
        original vertex names (serialization/import unaffected)."""
        from deeplearning4j_tpu.nn.layers.bottleneck import (
            BnParams, fused_bottleneck)
        x = acts[group["src"]]

        def bn_params(bn_name):
            bn = self.conf.vertices[bn_name].layer
            p = params.get(bn_name, {})
            s = state.get(bn_name, {})
            nf = s["mean"].shape[0]
            gamma = p.get("gamma", jnp.full((nf,), bn.gamma, x.dtype))
            beta = p.get("beta", jnp.full((nf,), bn.beta, x.dtype))
            # quantize through x.dtype exactly like the unfused
            # BatchNormalization.apply (fused.py precision-chain note):
            # the persistent running stats must round identically under
            # bf16 or the two execution plans train diverging state
            return bn, BnParams(
                gamma=gamma.astype(x.dtype),
                beta=beta.astype(x.dtype),
                running_mean=s["mean"].astype(x.dtype)
                .astype(jnp.float32),
                running_var=s["var"].astype(x.dtype)
                .astype(jnp.float32))

        bn_a, pa = bn_params(group["bn_a"])
        bn_b, pb = bn_params(group["bn_b"])
        bn_c, pc = bn_params(group["bn_c"])
        wa4 = params[group["conv_a"]]["W"]        # [O, I, 1, 1]
        wb4 = params[group["conv_b"]]["W"]        # [O, I, 3, 3]
        wc4 = params[group["conv_c"]]["W"]
        wa = wa4.reshape(wa4.shape[0], wa4.shape[1]).T
        wc = wc4.reshape(wc4.shape[0], wc4.shape[1]).T
        # tap-major [9, Cin, Cout]: tap t = kh*3+kw matches the kernel's
        # shifted-window order (cross-correlation, like lax.conv)
        wb = wb4.transpose(2, 3, 1, 0).reshape(9, wb4.shape[1],
                                               wb4.shape[0])
        if "conv_skip" in group:                  # downsample (entry) form
            ps = bn_params(group["bn_skip"])[1]
            ws4 = params[group["conv_skip"]]["W"]
            ws = ws4.reshape(ws4.shape[0], ws4.shape[1]).T
        else:
            ps = ws = None
        out, new_stats = fused_bottleneck(
            x, wa, pa, wb, pb, wc, pc, w_skip=ws, bn_skip=ps,
            stride=group.get("stride", 1), train=train, eps=bn_a.eps,
            decay=bn_a.decay,
            interpret=jax.default_backend() != "tpu")
        acts[out_name] = out
        # absorbed members already got pass-through state from the
        # fused_skip branch; only the trained BN stats and the output
        # vertex are written here
        if train:
            mua, vara, mub, varb, muc, varc = new_stats[:6]
            new_state[group["bn_a"]] = {"mean": mua, "var": vara}
            new_state[group["bn_b"]] = {"mean": mub, "var": varb}
            new_state[group["bn_c"]] = {"mean": muc, "var": varc}
            if ws is not None:
                new_state[group["bn_skip"]] = {"mean": new_stats[6],
                                               "var": new_stats[7]}
        new_state[out_name] = state.get(out_name, {})

    def _apply_fused_stem(self, out_name, group, params, state,
                          new_state, acts, *, train):
        """Execute the fused space-to-depth stem group (see
        nn/layers/stem.py): reads the raw network input activation,
        writes the pooled output into acts[out_name] and the stem BN's
        running stats into new_state; params/state stay keyed by the
        original vertex names (serialization/import unaffected)."""
        from deeplearning4j_tpu.nn.layers.bottleneck import BnParams
        from deeplearning4j_tpu.nn.layers.stem import fused_stem
        x = acts[group["src"]]
        if group.get("pre_vertex"):
            # the absorbed pad vertex's input preprocessor (e.g.
            # FeedForwardToCnn under the NHWC internal layout) still
            # runs — the kernel sees the same NHWC image the unfused
            # chain would
            x = self.conf.vertices[group["pre_vertex"]] \
                .preprocessor.apply(x, None)
        bn = self.conf.vertices[group["bn"]].layer
        p = params.get(group["bn"], {})
        s = state.get(group["bn"], {})
        nf = s["mean"].shape[0]
        gamma = p.get("gamma", jnp.full((nf,), bn.gamma, x.dtype))
        beta = p.get("beta", jnp.full((nf,), bn.beta, x.dtype))
        # same precision chain as the bottleneck plumbing: running stats
        # round through x.dtype so both execution plans train identical
        # persistent state under bf16
        bnp = BnParams(
            gamma=gamma.astype(x.dtype), beta=beta.astype(x.dtype),
            running_mean=s["mean"].astype(x.dtype).astype(jnp.float32),
            running_var=s["var"].astype(x.dtype).astype(jnp.float32))
        out, (nm, nv) = fused_stem(
            x, params[group["conv"]]["W"], bnp, train=train,
            eps=bn.eps, decay=bn.decay,
            interpret=jax.default_backend() != "tpu")
        acts[out_name] = out
        if train:
            new_state[group["bn"]] = {"mean": nm, "var": nv}
        new_state[out_name] = state.get(out_name, {})

    def _as_mask_dict(self, masks, default_key=None) -> Optional[Dict[str, Any]]:
        """Normalize a masks argument: a dict maps vertex name -> mask
        (None entries dropped); a bare array masks `default_key` (the
        first network input unless given, e.g. an output for label
        masks); None/all-None -> None."""
        if masks is None:
            return None
        if not isinstance(masks, dict):
            key = default_key or self.conf.network_inputs[0]
            # jit-boundary copy of the unprefetched compat path (the
            # multilayer._fit_batch twin lives in TPULINT_BASELINE):
            # fit(prefetch=N) stages these in the background worker, and
            # asarray on an already-device array is a no-op reference
            # tpulint: disable=device-transfer-in-hot-loop
            return {key: jnp.asarray(masks)}
        # tpulint: disable=device-transfer-in-hot-loop (same compat copy)
        out = {k: jnp.asarray(v) for k, v in masks.items() if v is not None}
        return out or None

    def _as_input_dict(self, inputs) -> Dict[str, Any]:
        if isinstance(inputs, dict):
            # jit-boundary copy of the unprefetched compat path — see
            # _as_mask_dict
            # tpulint: disable=device-transfer-in-hot-loop
            return {k: jnp.asarray(v) for k, v in inputs.items()}
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        # tpulint: disable=device-transfer-in-hot-loop (same compat copy)
        return {name: jnp.asarray(x)
                for name, x in zip(self.conf.network_inputs, inputs)}

    def _dequantized(self, params):
        """Materialize int8 QuantizedTensor leaves (W8A16 serving,
        optimize/quantization.py) as float32; XLA fuses the int8 convert
        into each consumer, which is where the HBM saving lives.
        Mirrors MultiLayerNetwork._dequantized."""
        from deeplearning4j_tpu.optimize.quantization import dequantize_tree
        return dequantize_tree(params, jnp.float32)

    def _cast_compute(self, params, inputs):
        """Dequantize int8 leaves, then apply the bf16 compute cast to
        params + the input dict (mirrors MultiLayerNetwork._cast_compute;
        conf.dtype sits in every jit key, so the policy can't go stale)."""
        from deeplearning4j_tpu.nn.compute import bf16_cast, bf16_cast_tree
        if getattr(self, "_quantized", False):
            params = self._dequantized(params)
        if self.conf.dtype in ("bfloat16", "bf16"):
            params = bf16_cast_tree(params)
            inputs = {k: bf16_cast(jnp.asarray(v))
                      for k, v in inputs.items()}
        return params, inputs

    def _loss(self, params, state, inputs, labels: Dict[str, Any], rng,
              fmasks, lmasks, *, train=True, carry_rnn=False):
        """Sum of output-layer losses + regularization."""
        # _forward applies the compute cast; dequantize here only so the
        # reg term below never sees int8 leaves (scoring path — training
        # itself is refused in _get_train_step)
        if getattr(self, "_quantized", False):
            params = self._dequantized(params)
        # ONE forward pass yields every output layer's preout (stateful
        # vertices update exactly once per step, matching the reference's
        # single feedForward in computeGradientAndScore :1298)
        total = 0.0
        acts, new_state, masks = self._forward(
            params, state, inputs, train=train, rng=rng, fmasks=fmasks,
            carry_rnn=carry_rnn, preout_of=self.conf.network_outputs)
        for out_name in self.conf.network_outputs:
            v = self.conf.vertices[out_name]
            if not (isinstance(v, LayerVertex) and
                    hasattr(v.layer, "compute_score")):
                raise ValueError(f"output vertex {out_name} is not an output layer")
            y = labels[out_name]
            lmask = (lmasks or {}).get(out_name)
            if lmask is None:
                ins = self.conf.vertex_inputs[out_name]
                lmask = next((masks.get(i_) for i_ in ins if masks.get(i_) is not None),
                             None)
            a_out = acts[out_name]
            a_out = a_out.astype(jnp.promote_types(a_out.dtype, jnp.float32))
            total = total + v.layer.compute_score(y, a_out, lmask)
            if isinstance(v.layer, CenterLossOutputLayer):
                ins = self.conf.vertex_inputs[out_name]
                feats = acts[ins[0]]
                o_state = new_state.get(out_name, {})
                total = total + v.layer.center_loss(feats, y, o_state)
                new_state[out_name] = v.layer.update_centers(
                    jax.lax.stop_gradient(feats), y, o_state)
        total = total + self._reg_loss(params)
        return total, new_state

    def _reg_loss(self, params):
        reg = 0.0
        for name, v in self.conf.vertices.items():
            if not isinstance(v, LayerVertex):
                continue
            l1c = v.layer.l1_coeffs()
            l2c = v.layer.l2_coeffs()
            p = params.get(name, {})
            for k, coeff in l1c.items():
                if k in p:
                    reg = reg + coeff * jnp.sum(jnp.abs(p[k]))
            for k, coeff in l2c.items():
                if k in p:
                    reg = reg + 0.5 * coeff * jnp.sum(p[k] ** 2)
        return reg

    # ------------------------------------------------------------------
    # training
    # ------------------------------------------------------------------
    def _get_train_step(self, carry_rnn: bool, policy: str = "off"):
        """One jitted step — sentinel semantics as in
        MultiLayerNetwork._get_train_step (5-tuple with a raw ok-flag
        when policy != "off"; "skip" where-zeroes bad updates)."""
        if getattr(self, "_quantized", False):
            raise RuntimeError(
                "this network was quantized for inference "
                "(quantize_for_inference) — int8 weights have no "
                "gradient path; train the fp checkpoint and re-quantize")
        key = ("train", carry_rnn, self.conf.dtype, policy)
        if key not in self._jit_cache:
            conf = self.conf

            def step(params, state, upd_state, inputs, labels, rng, fmasks, lmasks):
                (loss, new_state), grads = jax.value_and_grad(
                    lambda p: self._loss(p, state, inputs, labels, rng, fmasks,
                                         lmasks, train=True, carry_rnn=carry_rnn),
                    has_aux=True)(params)
                ok = None if policy == "off" else tree_finite(loss, grads)
                grads = normalize_gradients(grads, conf.gradient_normalization,
                                            conf.gradient_normalization_threshold)
                steps, new_upd = conf.updater.update(grads, upd_state, params)
                new_params = _tree_sub(params, steps)
                if policy == "off":
                    return new_params, new_state, new_upd, loss
                new_params, new_upd, new_state = guard_updates(
                    ok, policy, (new_params, params),
                    (new_upd, upd_state), (new_state, state))
                return new_params, new_state, new_upd, loss, ok

            self._jit_cache[key] = jax.jit(step, donate_argnums=(0, 2))
        return self._jit_cache[key]

    def _get_scan_train_step(self, k: int, policy: str = "off"):
        """Fused multi-step dispatch — the ComputationGraph twin of
        MultiLayerNetwork._get_scan_train_step: K optimizer updates in
        one jitted, buffer-donating lax.scan over stacked (dict-keyed)
        batches, returning the per-step loss vector (plus the per-step
        sentinel ok-flags when policy != "off")."""
        if getattr(self, "_quantized", False):
            raise RuntimeError(
                "this network was quantized for inference "
                "(quantize_for_inference) — int8 weights have no "
                "gradient path; train the fp checkpoint and re-quantize")
        key = ("scan", k, self.conf.dtype, policy)
        if key not in self._jit_cache:
            conf = self.conf

            def stepk(params, state, upd_state, xs, ys, rngs, fmasks, lmasks):
                def one(carry, inp):
                    p, s, u = carry
                    ins, lbs, rng, fm, lm = inp
                    (loss, s2), grads = jax.value_and_grad(
                        lambda pp: self._loss(pp, s, ins, lbs, rng, fm, lm,
                                              train=True),
                        has_aux=True)(p)
                    ok = None if policy == "off" else \
                        tree_finite(loss, grads)
                    grads = normalize_gradients(
                        grads, conf.gradient_normalization,
                        conf.gradient_normalization_threshold)
                    steps, u2 = conf.updater.update(grads, u, p)
                    p2 = _tree_sub(p, steps)
                    s2 = _strip_stream_state(s2)
                    if policy != "off":
                        p2, u2, s2 = guard_updates(
                            ok, policy, (p2, p), (u2, u), (s2, s))
                    out = loss if policy == "off" else (loss, ok)
                    return (p2, s2, u2), out

                (p, s, u), out = jax.lax.scan(
                    one, (params, _strip_stream_state(state), upd_state),
                    (xs, ys, rngs, fmasks, lmasks))
                if policy == "off":
                    return p, s, u, out
                losses, oks = out
                return p, s, u, losses, oks

            self._jit_cache[key] = jax.jit(stepk, donate_argnums=(0, 2))
        return self._jit_cache[key]

    def _get_phase_steps(self, carry_rnn: bool, policy: str = "off"):
        """Split train step for span phase detail — the ComputationGraph
        twin of MultiLayerNetwork._get_phase_steps (see its docstring for
        the vjp-across-jit pattern, the fusion-cost tradeoff, and the
        debug-path sentinel caveat)."""
        if getattr(self, "_quantized", False):
            raise RuntimeError(
                "this network was quantized for inference "
                "(quantize_for_inference) — int8 weights have no "
                "gradient path; train the fp checkpoint and re-quantize")
        key = ("phase", carry_rnn, self.conf.dtype, policy)
        if key not in self._jit_cache:
            conf = self.conf

            def fwd(params, state, inputs, labels, rng, fmasks, lmasks):
                loss, vjp_fn, new_state = jax.vjp(
                    lambda p: self._loss(p, state, inputs, labels, rng,
                                         fmasks, lmasks, train=True,
                                         carry_rnn=carry_rnn),
                    params, has_aux=True)
                return loss, new_state, vjp_fn

            def bwd(vjp_fn, loss):
                (grads,) = vjp_fn(jnp.ones_like(loss))
                return normalize_gradients(grads, conf.gradient_normalization,
                                           conf.gradient_normalization_threshold)

            def upd(params, grads, upd_state, loss, state, new_state):
                steps, new_upd = conf.updater.update(grads, upd_state, params)
                new_params = _tree_sub(params, steps)
                if policy == "off":
                    return new_params, new_upd, new_state
                ok = tree_finite(loss, grads)
                new_params, new_upd, new_state = guard_updates(
                    ok, policy, (new_params, params),
                    (new_upd, upd_state), (new_state, state))
                return new_params, new_upd, new_state, ok

            self._jit_cache[key] = (jax.jit(fwd), jax.jit(bwd),
                                    jax.jit(upd, donate_argnums=(1, 2)))
        return self._jit_cache[key]

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def fit(self, data, labels=None, epochs: int = 1, batch_size: int = 32,
            *, steps_per_dispatch: int = 1, prefetch: int = 0,
            pad_tail: Optional[bool] = None,
            execution_plan: Optional[str] = None):
        """Train (ref: ComputationGraph.fit :837). Accepts a DataSetIterator
        (single-input/single-output), a DataSet, (features, labels), or dicts
        keyed by input/output names (MultiDataSet equivalent).

        ``execution_plan`` ("auto" | "fused" | "xla") selects how the
        eligible fused chains (bottleneck blocks, the space-to-depth
        stem) execute — "auto" resolves per shape from the measured
        kernel-crossover store with the XLA plan as the uncalibrated
        default (tuning/plan.py). Resolution happens ONCE here;
        re-resolving the same plan never rebuilds jitted steps, so the
        zero-retrace contract holds. None leaves an explicitly
        set_fusion'd plan untouched.

        `steps_per_dispatch` / `prefetch` / `pad_tail` are the fused
        multi-step dispatch and device-prefetch knobs — see
        MultiLayerNetwork.fit and ARCHITECTURE.md "Input pipeline &
        fused dispatch". Tail padding is skipped for feature-masked
        batches without an explicit labels mask: there the loss falls
        back to the PROPAGATED feature mask (see _loss), which a
        synthesized example-weight mask would shadow."""
        if not self._initialized:
            self.init()
        ensure_started()
        if execution_plan is not None:
            from deeplearning4j_tpu.tuning.plan import apply_execution_plan
            apply_execution_plan(self, execution_plan)
        if labels is not None:
            it = ArrayDataSetIterator(data, labels, batch_size)
        elif isinstance(data, DataSet):
            it = ArrayDataSetIterator(data.features, data.labels, batch_size,
                                      data.features_mask, data.labels_mask)
        else:
            it = data
        if it is not data:
            # align the internal iterator's pass counter with the
            # absolute epoch count — see MultiLayerNetwork.fit
            it.restore_state({"epoch": self.epoch_count, "pos": 0})
        k = max(1, int(steps_per_dispatch))
        pad = (k > 1) if pad_tail is None else bool(pad_tail)
        if prefetch:
            from deeplearning4j_tpu.pipeline.prefetch import \
                DevicePrefetchIterator
            # pad in the worker, BEFORE the transfer (padding a
            # device-resident batch in the fit loop would be a D2H
            # round-trip); pad_when carries the mask-shadowing
            # exemption the loop below applies to unprefetched batches
            it = DevicePrefetchIterator(
                it, prefetch=prefetch, pad_to="auto" if pad else None,
                pad_when=lambda ds: ds.labels is not None and (
                    ds.labels_mask is not None or ds.features_mask is None))
        # listener capability scan hoisted out of the per-batch path
        self._stash_features = any(getattr(l, "needs_batch_features", False)
                                   for l in self.listeners)
        # restored data-pipeline cursor: see MultiLayerNetwork.fit
        consume_restored_cursor(self, it)
        capture_cursor_pass(self, it)
        try:
            for _ in range(epochs):
                for lst in self.listeners:
                    lst.on_epoch_start(self, self.epoch_count)
                self._fit_epoch(it, k, pad)
                # completed-epoch ordering: see multilayer.py fit
                epoch_idx = self.epoch_count
                self.epoch_count += 1
                self._dispatched_in_epoch = 0
                self._canon_in_epoch = None
                self._cursor_pass += 1
                for lst in self.listeners:
                    lst.on_epoch_end(self, epoch_idx)
            # one allowed sync, after the final batch (see multilayer.fit)
            finalize_fit_telemetry(self)
        finally:
            self._stash_features = None
            self._cursor_pass = None
            close_listeners(self.listeners)
        return self

    def _fit_epoch(self, it, k: int, pad: bool):
        """One pass over the iterator — the graph twin of
        MultiLayerNetwork._fit_epoch: pad ragged batches to the
        canonical row count when `pad` and fuse runs of `k`
        same-signature batches into single scan dispatches; anything
        unfusable falls back to the per-batch step.

        Dispatch boundaries + cursor counters: see
        MultiLayerNetwork._fit_epoch."""
        canon = self._canon_in_epoch
        group: List[DataSet] = []
        sig = None

        def flush():
            nonlocal sig
            if not group:
                sig = None
                return
            if len(group) == k:
                self._fit_group(group)
            else:
                for b in group:
                    self._fit_batch(b)
            self._dispatched_in_epoch += len(group)
            group.clear()
            sig = None
            dispatch_boundary(self)

        for ds in it:
            if canon is None:
                canon = ds.num_examples()
                self._canon_in_epoch = canon
            # feature-masked batches without an explicit labels mask use
            # the PROPAGATED mask in _loss; a synthesized example-weight
            # mask would shadow it, so those stay unpadded
            if pad and ds.labels is not None and (
                    ds.labels_mask is not None or ds.features_mask is None):
                if ds.num_examples() < canon:
                    ds = pad_batch(ds, canon)
                ds = with_example_weights(ds)
            if k == 1:
                self._fit_batch(ds)
                self._dispatched_in_epoch += 1
                dispatch_boundary(self)
                continue
            s = group_signature(ds)
            if group and s != sig:
                flush()
            sig = s
            group.append(ds)
            if len(group) == k:
                flush()
        flush()

    def _fit_group(self, group: Sequence[DataSet]):
        """One fused K-step scan dispatch over stacked dict-keyed
        batches; listeners fire per logical step with lazy loss slices
        (see MultiLayerNetwork._fit_group)."""
        t0 = time.perf_counter()
        k = len(group)
        out0 = self.conf.network_outputs[0]
        with span("etl"):
            rngs = jnp.stack([self._next_rng() for _ in range(k)])
            ins = [self._as_input_dict(b.features) for b in group]
            lbs = [{out0: b.labels} if not isinstance(b.labels, dict)
                   else b.labels for b in group]
            fms = [self._as_mask_dict(b.features_mask) for b in group]
            lms = [self._as_mask_dict(b.labels_mask, default_key=out0)
                   for b in group]

            def stack_dicts(ds_list):
                if ds_list[0] is None:
                    return None
                return {kk: jnp.stack([d[kk] for d in ds_list])
                        for kk in ds_list[0]}

            xs = stack_dicts(ins)
            ys = stack_dicts(lbs)
            fmasks = stack_dicts(fms)
            lmasks = stack_dicts(lms)
        policy = effective_policy(self)
        step = self._get_scan_train_step(k, policy)
        with span("step"):
            # apply_step absorbs the [K] sentinel flag vector (recorded
            # lazily — accounting syncs at its own cadence)
            self.params, self.state, self.updater_state, losses = \
                apply_step(self, policy, step, self.params, self.state,
                           self.updater_state, xs, ys, rngs, fmasks, lmasks)
        # raw device scalar: float() (the host sync) deferred to access
        self.score_value = losses[-1]
        with span("listener"):
            for i, b in enumerate(group):
                loss_i = losses[i]  # lazy device slice, no sync
                if self._stash_features:
                    # per LOGICAL step, so viz listeners pair each
                    # iteration_done with its own batch's features
                    self._last_batch_features = b.features
                for lst in self.listeners:
                    if hasattr(lst, "record_batch"):
                        lst.record_batch(num_real_examples(b))
                    lst.iteration_done(self, self.iteration_count, loss_i)
                self.iteration_count += 1
        maybe_record_fit_iteration(
            self, sum(num_real_examples(b) for b in group),
            time.perf_counter() - t0, n_batches=k)

    def _fit_batch(self, ds: DataSet):
        t0 = time.perf_counter()
        # listener parity with MultiLayerNetwork._fit_batch: viz listeners
        # (needs_batch_features) get the raw batch stashed here too
        stash = self._stash_features
        if stash is None:  # direct call outside fit(): no hoisted scan
            stash = any(getattr(l, "needs_batch_features", False)
                        for l in self.listeners)
        if stash:
            self._last_batch_features = ds.features
        with span("etl"):
            rng = self._next_rng()
            # jnp.asarray here is the jit-boundary copy of the
            # UNPREFETCHED compat path (baselined for tpulint
            # device-transfer-in-hot-loop): fit(prefetch=N) moves these
            # H2D copies into the background pipeline stage
            inputs = self._as_input_dict(ds.features)
            labels = {self.conf.network_outputs[0]: jnp.asarray(ds.labels)} \
                if not isinstance(ds.labels, dict) else \
                {k: jnp.asarray(v) for k, v in ds.labels.items()}
            fmasks = self._as_mask_dict(ds.features_mask)
            lmasks = self._as_mask_dict(ds.labels_mask,
                                        default_key=self.conf.network_outputs[0])
        policy = effective_policy(self)
        if phase_detail() and not getattr(self, "_quantized", False):
            # dispatch-time spans, no device barrier: see multilayer.py
            fwd, bwd, upd = self._get_phase_steps(False, policy)
            with span("forward"):
                loss, new_state, vjp_fn = fwd(self.params, self.state, inputs,
                                              labels, rng, fmasks, lmasks)
            with span("backward"):
                grads = bwd(vjp_fn, loss)
            with span("update"):
                self.params, self.updater_state, self.state = apply_step(
                    self, policy, upd, self.params, grads,
                    self.updater_state, loss, self.state, new_state)
        else:
            step = self._get_train_step(False, policy)
            with span("step"):
                self.params, self.state, self.updater_state, loss = \
                    apply_step(self, policy, step, self.params, self.state,
                               self.updater_state, inputs, labels, rng,
                               fmasks, lmasks)
        # raw device scalar: float() (the host sync) deferred to access
        self.score_value = loss
        with span("listener"):
            # num_real_examples: a padded tail batch reports its true
            # row count to throughput stats, not the bucket size
            n_real = num_real_examples(ds)
            for lst in self.listeners:
                if hasattr(lst, "record_batch"):
                    lst.record_batch(n_real)
                # raw score, NOT the float property: listeners that use the
                # score sync at their own cadence, the rest never sync
                lst.iteration_done(self, self.iteration_count,
                                   self._score_raw)
        self.iteration_count += 1
        maybe_record_fit_iteration(self, n_real,
                                   time.perf_counter() - t0)

    # ------------------------------------------------------------------
    # inference
    # ------------------------------------------------------------------
    def output(self, *inputs, train: bool = False, masks=None):
        """Output activations (ref: output :1532). Returns a single array if
        the graph has one output, else a list."""
        if not self._initialized:
            self.init()
        key = ("out", train, self.conf.dtype)
        if key not in self._jit_cache:
            def fwd(params, state, ins, rng, fmasks):
                acts, new_state, _ = self._forward(params, state, ins, train=train,
                                                   rng=rng, fmasks=fmasks)
                return [_f32_head(acts[o])
                        for o in self.conf.network_outputs], new_state

            self._jit_cache[key] = jax.jit(fwd)
        if len(inputs) == 1 and isinstance(inputs[0], dict):
            ins = self._as_input_dict(inputs[0])
        else:
            ins = self._as_input_dict(list(inputs))
        fmasks = self._as_mask_dict(masks)
        rng = self._next_rng() if train else jax.random.PRNGKey(0)
        outs, _ = self._jit_cache[key](self.params, self.state, ins, rng, fmasks)
        return outs[0] if len(outs) == 1 else outs

    def score(self, ds: DataSet) -> float:
        inputs = self._as_input_dict(ds.features)
        labels = {self.conf.network_outputs[0]: jnp.asarray(ds.labels)} \
            if not isinstance(ds.labels, dict) else \
            {k: jnp.asarray(v) for k, v in ds.labels.items()}
        loss, _ = self._loss(self.params, self.state, inputs, labels, None,
                             None, None, train=False)
        return float(loss)

    def evaluate(self, iterator):
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        e = Evaluation()
        if isinstance(iterator, DataSet):
            iterator = ArrayDataSetIterator(iterator.features, iterator.labels, 128)
        for ds in iterator:
            out = self.output(ds.features, masks=ds.features_mask)
            e.eval(ds.labels, np.asarray(out), mask=ds.labels_mask)
        return e


    def rnn_time_step(self, *inputs, masks=None, pad_left=None,
                      donate_state=False):
        """Stateful streaming inference over the graph, carrying RNN h/c in
        self.state across calls (ref: ComputationGraph.rnnTimeStep).
        `masks` maps network-input name -> this chunk's [N, T] key mask
        for padded variable-length batches; attention vertices carry it
        in the KV cache so padded positions stay masked on later steps.

        `pad_left` (int, mutually exclusive with masks; single-input
        graphs only) marks the first pad_left positions as LEFT padding
        with packed accounting — pads never enter caches nor consume
        streaming positions, so any prompt length primes in one dispatch
        at a bucketed shape (see MultiLayerNetwork.rnn_time_step)."""
        # stream-cache sharding / paged-decode impl configs key the
        # cache: flipping the process-wide setting retraces for every
        # net on next use. donate_state (TPU/GPU only — a no-op on CPU)
        # aliases the carried state buffers into the dispatch: the
        # serving engine's direct-paged decode sets it so the page
        # pools update in place (see MultiLayerNetwork.rnn_time_step).
        from deeplearning4j_tpu.nn.conf import layers as _L
        padded = pad_left is not None
        donate = donate_state and jax.default_backend() != "cpu"
        key = ("rnn_step", padded, donate, self.conf.dtype,
               _L._STREAM_CACHE_SHARDING, _L._PAGED_DECODE_IMPL)
        if key not in self._jit_cache:
            if padded:
                def fwd(params, state, ins, rng, pad):
                    acts, new_state, _ = self._forward(
                        params, state, ins, train=False, rng=rng,
                        fmasks=None, carry_rnn=True, stream=True, pad=pad)
                    return [_f32_head(acts[o]) for o in
                            self.conf.network_outputs], new_state
            else:
                def fwd(params, state, ins, rng, fmasks):
                    acts, new_state, _ = self._forward(
                        params, state, ins, train=False, rng=rng,
                        fmasks=fmasks, carry_rnn=True, stream=True)
                    return [_f32_head(acts[o]) for o in
                            self.conf.network_outputs], new_state

            self._jit_cache[key] = jax.jit(
                fwd, donate_argnums=(1,) if donate else ())
        if len(inputs) == 1 and isinstance(inputs[0], dict):
            ins = self._as_input_dict(inputs[0])
        else:
            ins = self._as_input_dict(list(inputs))
        if padded:
            if masks is not None:
                raise ValueError("pad_left and masks are mutually exclusive")
            if len(ins) != 1:
                raise ValueError("pad_left needs a single-input graph "
                                 "(the pad applies to THE streamed input)")
            pad_left = int(pad_left)
            t = next(iter(ins.values())).shape[-1]
            if not 0 <= pad_left < t:
                raise ValueError(f"pad_left {pad_left} out of range for a "
                                 f"chunk of {t} positions")
            new_pos_map = self._check_graph_stream_budget(ins, pad=pad_left)
            outs, new_state = self._jit_cache[key](
                self.params, self.state, ins, jax.random.PRNGKey(0),
                jnp.asarray(pad_left, jnp.int32))
        else:
            fmasks = self._as_mask_dict(masks)
            new_pos_map = self._check_graph_stream_budget(ins)
            outs, new_state = self._jit_cache[key](
                self.params, self.state, ins, jax.random.PRNGKey(0), fmasks)
        self.state = new_state
        old_max = max(getattr(self, "_stream_pos_map", {}).values(),
                      default=0)
        self._stream_pos_map = new_pos_map
        rows = getattr(self, "_stream_pos_rows", None)
        if rows is not None:     # per-row positions (after per-row rewind)
            consumed = max(new_pos_map.values(), default=0) - old_max
            self._stream_pos_rows = rows + consumed
        return outs[0] if len(outs) == 1 else outs

    def _vertex_time_lengths(self, ins):
        """Propagate each vertex's output TIME length (None when
        non-temporal) through the topo order for this call's inputs.
        Temporality comes from the statically inferred output InputTypes
        (kind == "rnn"), so time-collapsing layers/vertices (LastTimeStep,
        GlobalPooling, …) propagate None without per-class special cases;
        the length itself is this call's runtime chunk length, taken from
        the first temporal input (DuplicateToTimeSeries re-expands from
        its reference sequence, which that rule also picks: its first —
        collapsed — input is non-temporal)."""
        out_types = self._infer_types()
        lens = {name: (int(a.shape[-1]) if getattr(a, "ndim", 0) == 3
                       else None)
                for name, a in ins.items()}
        for name in self._topo:
            if out_types[name].kind != "rnn":
                lens[name] = None
                continue
            slens = [lens.get(s)
                     for s in self.conf.vertex_inputs.get(name, [])]
            lens[name] = next((l for l in slens if l is not None), None)
        return lens

    def _check_graph_stream_budget(self, ins, pad: int = 0):
        """Per-vertex streaming budget: each streaming layer is charged
        the time length of the activation actually reaching it — in a
        multi-input graph (e.g. seq2seq decode re-feeding the full
        encoder sequence each step, or an encoder path collapsed through
        LastTimeStep+DuplicateToTimeSeries) different caches advance by
        different amounts. `pad` left-pad positions (packed padded
        priming; single-input graphs, so every temporal length carries
        the same pad) are free. Validates every vertex, returning the
        counter updates; the caller commits them after the forward
        succeeds."""
        lens = self._vertex_time_lengths(ins)
        pos = getattr(self, "_stream_pos_map", {})
        updates = {}
        for name, v in self.conf.vertices.items():
            layer = getattr(v, "layer", None)
            if layer is None or not getattr(layer, "supports_streaming",
                                            False):
                continue
            srcs = self.conf.vertex_inputs.get(name, [])
            t = next((lens[s] for s in srcs if lens.get(s) is not None),
                     None)
            if t is None:
                continue
            new_pos = pos.get(name, 0) + t - pad
            cap = stream_capacity([layer])
            if cap is not None and new_pos > cap:
                raise ValueError(
                    f"vertex '{name}' streamed {new_pos} positions, "
                    f"exceeding its streaming capacity ({cap}); call "
                    "rnn_clear_previous_state() or raise "
                    "cache_length/max_length")
            updates[name] = new_pos
        return {**pos, **updates}


    def set_stream_cache_sharding(self, mesh, axis: str = "data"):
        """Shard streaming attention KV caches over the sequence axis of
        `mesh` (None reverts to single-device caches). PROCESS-WIDE, like
        use_cnn_data_format: the setting applies to every net, and since
        it is part of each streaming step's jit key, any net retraces
        with the new layout on its next streaming call — no stale
        compiled steps. Streaming decode (rnn_time_step / sample_stream /
        beam_search) then runs sequence-parallel: per-device cache memory
        is O(cache_length / n_devices) and XLA inserts the cross-device
        softmax combine."""
        from deeplearning4j_tpu.nn.conf.layers import (
            set_stream_cache_sharding)
        set_stream_cache_sharding(mesh, axis)
        return self

    def rnn_clear_previous_state(self):
        """ref: ComputationGraph.rnnClearPreviousState."""
        self._stream_pos_map = {}
        self._stream_pos_rows = None
        for k, s in self.state.items():
            if isinstance(s, dict):
                self.state[k] = {kk: vv for kk, vv in s.items()
                                 if kk not in STREAM_STATE_KEYS}

    def summary(self) -> str:
        self._infer_types()
        lines = ["=" * 80,
                 f"{'vertex':<24}{'type':<26}{'inputs':<20}{'params':<10}",
                 "-" * 80]
        total = 0
        for name in self._topo:
            v = self.conf.vertices[name]
            nparams = sum(int(np.prod(p.shape))
                          for p in jax.tree_util.tree_leaves(self.params.get(name, {})))
            total += nparams
            tname = type(v.layer).__name__ if isinstance(v, LayerVertex) \
                else type(v).__name__
            ins = ",".join(self.conf.vertex_inputs.get(name, []))
            lines.append(f"{name:<24}{tname:<26}{ins:<20}{nparams:<10}")
        lines.append("-" * 80)
        lines.append(f"Total params: {total}")
        lines.append("=" * 80)
        return "\n".join(lines)

