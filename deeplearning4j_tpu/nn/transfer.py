"""Transfer learning: surgery on trained networks.

TPU-native equivalent of nn/transferlearning/TransferLearning.java (Builder
:59: fineTuneConfiguration :73, setFeatureExtractor :84 freeze, nOutReplace
:98-175, add/remove layers), FineTuneConfiguration, and
TransferLearningHelper (featurize + fit the unfrozen tail).

Params are pytrees, so surgery = structural edits on (conf, params) pairs —
no flat-view re-slicing like the reference.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from deeplearning4j_tpu.nn.conf.layers import FrozenLayer, LayerConf, layer_from_dict, layer_to_dict
from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


@dataclass
class FineTuneConfiguration:
    """Overrides applied to every non-frozen layer (ref:
    FineTuneConfiguration.java)."""

    updater: Any = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    dropout: Optional[float] = None
    seed: Optional[int] = None

    def apply(self, conf: MultiLayerConfiguration):
        if self.updater is not None:
            conf.updater = self.updater
        if self.seed is not None:
            conf.seed = self.seed
        for layer in conf.layers:
            if isinstance(layer, FrozenLayer):
                continue
            for f in ("l1", "l2", "dropout"):
                v = getattr(self, f)
                if v is not None and hasattr(layer, f):
                    setattr(layer, f, v)


class TransferLearning:
    """Namespace matching the reference entry point."""

    class Builder:
        def __init__(self, net: MultiLayerNetwork):
            self._net = net
            self._conf = MultiLayerConfiguration.from_dict(net.conf.to_dict())
            # materialize copies: the source net's buffers get donated by its
            # own train steps, so sharing references would alias deleted arrays
            self._params = jax.tree_util.tree_map(lambda a: jax.numpy.array(a),
                                                  net.params)
            self._state = jax.tree_util.tree_map(lambda a: jax.numpy.array(a),
                                                 net.state)
            self._freeze_upto: Optional[int] = None
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._nout_replace: Dict[int, tuple] = {}
            self._remove_from: Optional[int] = None
            self._appended: List[LayerConf] = []

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._fine_tune = ftc
            return self

        def set_feature_extractor(self, layer_index: int):
            """Freeze layers [0..layer_index] (ref: setFeatureExtractor :84)."""
            self._freeze_upto = layer_index
            return self

        def n_out_replace(self, layer_index: int, n_out: int,
                          weight_init: str = "xavier"):
            """Replace a layer's output width, re-initializing it and the
            next layer's n_in (ref: nOutReplace :98-175)."""
            self._nout_replace[layer_index] = (n_out, weight_init)
            return self

        def remove_layers_from_output(self, n: int):
            """Remove the last n layers (ref: removeLayersFromOutput)."""
            self._remove_from = len(self._conf.layers) - n
            return self

        def remove_output_layer(self):
            return self.remove_layers_from_output(1)

        def add_layer(self, layer: LayerConf):
            self._appended.append(layer)
            return self

        def build(self) -> MultiLayerNetwork:
            conf = self._conf
            params = dict(self._params)
            state = dict(self._state)

            # 1. remove tail layers
            if self._remove_from is not None:
                for i in range(self._remove_from, len(conf.layers)):
                    params.pop(str(i), None)
                    state.pop(str(i), None)
                conf.layers = conf.layers[: self._remove_from]
                conf.preprocessors = {k: v for k, v in conf.preprocessors.items()
                                      if k < self._remove_from}

            # 2. append new layers
            n0 = len(conf.layers)
            conf.layers.extend(self._appended)

            # 3. nOut replacement (re-init changed layers + downstream n_in)
            reinit = set(range(n0, len(conf.layers)))
            for idx, (n_out, w_init) in self._nout_replace.items():
                layer = conf.layers[idx]
                layer.n_out = n_out
                layer.weight_init = w_init
                reinit.add(idx)
                if idx + 1 < len(conf.layers):
                    nxt = conf.layers[idx + 1]
                    if hasattr(nxt, "n_in"):
                        nxt.n_in = None  # re-infer
                        reinit.add(idx + 1)

            # 4. freeze prefix
            if self._freeze_upto is not None:
                for i in range(self._freeze_upto + 1):
                    if not isinstance(conf.layers[i], FrozenLayer):
                        conf.layers[i] = FrozenLayer(inner=conf.layers[i])

            # 5. fine-tune overrides
            if self._fine_tune is not None:
                self._fine_tune.apply(conf)

            # 6. build net; re-init params for changed layers, keep the rest
            from deeplearning4j_tpu.nn.conf.network import _infer_shapes_and_preprocessors
            net = MultiLayerNetwork(conf)
            net.init()
            for i in range(len(conf.layers)):
                k = str(i)
                if i not in reinit and k in params:
                    net.params[k] = params[k]
                    if k in state and state[k]:
                        net.state[k] = state[k]
            net.updater_state = conf.updater.init_state(net.params)
            return net


class TransferLearningHelper:
    """Featurize-then-train on the unfrozen tail (ref:
    TransferLearningHelper.java). The frozen prefix runs once per batch
    (inference-only), the tail trains on cached features — the same split the
    reference uses to avoid recomputing the frozen body."""

    def __init__(self, net: MultiLayerNetwork, frozen_until: int):
        self.full_net = net
        self.frozen_until = frozen_until
        # tail network over the remaining layers
        tail_conf = MultiLayerConfiguration.from_dict(net.conf.to_dict())
        tail_conf.layers = tail_conf.layers[frozen_until + 1:]
        tail_conf.preprocessors = {
            k - (frozen_until + 1): v for k, v in net.conf.preprocessors.items()
            if k > frozen_until}
        its = net.conf.layer_input_types()
        tail_conf.input_type = net.conf.layers[frozen_until].output_type(
            its[frozen_until])
        self.tail = MultiLayerNetwork(tail_conf)
        self.tail.init()
        for i in range(frozen_until + 1, len(net.conf.layers)):
            self.tail.params[str(i - frozen_until - 1)] = net.params[str(i)]
            self.tail.state[str(i - frozen_until - 1)] = net.state[str(i)]
        self.tail.updater_state = tail_conf.updater.init_state(self.tail.params)

    def featurize(self, ds):
        """Run the frozen prefix (ref: TransferLearningHelper.featurize)."""
        import jax.numpy as jnp
        from deeplearning4j_tpu.datasets.dataset import DataSet
        acts, _ = self.full_net._forward(
            self.full_net.params, self.full_net.state, jnp.asarray(ds.features),
            train=False, rng=None, upto=self.frozen_until + 1)
        return DataSet(np.asarray(acts[-1]), ds.labels)

    def fit_featurized(self, ds, epochs: int = 1, batch_size: int = 32):
        self.tail.fit(ds.features, ds.labels, epochs=epochs,
                      batch_size=batch_size)
        # write tail params back into the full net
        for i in range(self.frozen_until + 1, len(self.full_net.conf.layers)):
            self.full_net.params[str(i)] = self.tail.params[str(i - self.frozen_until - 1)]

    def output_from_featurized(self, features):
        return self.tail.output(features)

    def unfrozen_network(self):
        return self.tail



def _ancestor_closure(vertices, vertex_inputs, frontier) -> set:
    """Frontier vertices + every ancestor (the 'up to and including'
    freeze semantics shared by GraphBuilder and the helper)."""
    out = set()
    stack = list(frontier)
    while stack:
        n = stack.pop()
        if n in out or n not in vertices:
            continue
        out.add(n)
        stack.extend(i for i in vertex_inputs.get(n, []) if i in vertices)
    return out


class _GraphBuilderNS:
    """Implementation of TransferLearning.GraphBuilder (ref:
    TransferLearning.java:447-778): surgery on a trained ComputationGraph —
    freeze a feature-extractor frontier, replace layer widths, remove
    vertices (cascading to dependents), graft new layers/vertices, and
    re-point outputs, keeping every untouched vertex's trained params."""

    def __init__(self, net):
        from deeplearning4j_tpu.nn.conf.network import (
            ComputationGraphConfiguration)
        self._conf = ComputationGraphConfiguration.from_dict(
            net.conf.to_dict())
        self._params = jax.tree_util.tree_map(
            lambda a: jax.numpy.array(a), net.params)
        self._state = jax.tree_util.tree_map(
            lambda a: jax.numpy.array(a), net.state)
        self._freeze_frontier: List[str] = []
        self._fine_tune: Optional[FineTuneConfiguration] = None
        self._nout_replace: Dict[str, tuple] = {}
        self._removed: List[str] = []
        self._added: List[str] = []

    def fine_tune_configuration(self, ftc: FineTuneConfiguration):
        self._fine_tune = ftc
        return self

    def set_feature_extractor(self, *vertex_names: str):
        """Freeze the named vertices and every ancestor
        (ref: setFeatureExtractor :499 — 'up to and including').
        Unknown names fail fast like the reference (a typo must not
        silently leave the feature extractor trainable)."""
        missing = [n for n in vertex_names if n not in self._conf.vertices]
        if missing:
            raise ValueError(
                f"set_feature_extractor: unknown vertex name(s) {missing}; "
                f"graph has {sorted(self._conf.vertices)}")
        self._freeze_frontier = list(vertex_names)
        return self

    def n_out_replace(self, layer_name: str, n_out: int,
                      weight_init: str = "xavier"):
        """ref: nOutReplace :518-561 — the layer re-initializes and its
        consumers' n_in re-infer."""
        self._nout_replace[layer_name] = (n_out, weight_init)
        return self

    def remove_vertex_and_connections(self, vertex_name: str):
        """Remove a vertex and (cascading) everything that consumed it
        (ref: removeVertexAndConnections :640)."""
        conf = self._conf
        doomed = {vertex_name}
        changed = True
        while changed:
            changed = False
            for name, ins in conf.vertex_inputs.items():
                if name not in doomed and any(i in doomed for i in ins):
                    doomed.add(name)
                    changed = True
        for name in doomed:
            conf.vertices.pop(name, None)
            conf.vertex_inputs.pop(name, None)
            self._params.pop(name, None)
            self._state.pop(name, None)
        conf.network_outputs = [o for o in conf.network_outputs
                                if o not in doomed]
        self._removed.extend(doomed)
        return self

    def add_layer(self, name: str, layer: LayerConf, *inputs: str,
                  preprocessor=None):
        """ref: addLayer :653-668."""
        from deeplearning4j_tpu.nn.conf.graph_conf import LayerVertex
        layer.name = name
        self._conf.vertices[name] = LayerVertex(layer=layer,
                                                preprocessor=preprocessor)
        self._conf.vertex_inputs[name] = list(inputs)
        self._added.append(name)
        return self

    def add_vertex(self, name: str, vertex, *inputs: str):
        """ref: addVertex :683."""
        self._conf.vertices[name] = vertex
        self._conf.vertex_inputs[name] = list(inputs)
        self._added.append(name)
        return self

    def set_outputs(self, *names: str):
        self._conf.network_outputs = list(names)
        return self

    def build(self):
        from deeplearning4j_tpu.nn.conf.graph_conf import LayerVertex
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        conf = self._conf

        reinit = set(self._added)
        # nOut replacement: re-init the layer and every direct consumer
        # whose n_in must re-infer
        for name, (n_out, w_init) in self._nout_replace.items():
            v = conf.vertices[name]
            if not isinstance(v, LayerVertex):
                raise ValueError(f"nOutReplace target {name!r} is not a "
                                 "layer vertex")
            v.layer.n_out = n_out
            v.layer.weight_init = w_init
            reinit.add(name)
            # the width change propagates through parameterless vertices
            # (merge/elementwise/subset/...) until it reaches layer
            # vertices, whose n_in must re-infer; anything shape-touched
            # re-initializes (the reference re-inits consumers too)
            frontier = [name]
            seen = {name}
            while frontier:
                cur = frontier.pop()
                for cname, ins in conf.vertex_inputs.items():
                    if cur not in ins or cname in seen:
                        continue
                    seen.add(cname)
                    cv = conf.vertices[cname]
                    if isinstance(cv, LayerVertex):
                        if hasattr(cv.layer, "n_in"):
                            cv.layer.n_in = None  # re-infer
                        reinit.add(cname)
                    else:
                        # shape flows through; keep walking downstream
                        reinit.add(cname)
                        frontier.append(cname)

        # freeze the ancestor closure of the frontier
        if self._freeze_frontier:
            for name in _ancestor_closure(
                    conf.vertices, conf.vertex_inputs,
                    self._freeze_frontier):
                v = conf.vertices[name]
                if isinstance(v, LayerVertex) and \
                        not isinstance(v.layer, FrozenLayer):
                    v.layer = FrozenLayer(inner=v.layer)

        if self._fine_tune is not None:
            ft = self._fine_tune
            if ft.updater is not None:
                conf.updater = ft.updater
            if ft.seed is not None:
                conf.seed = ft.seed
            for v in conf.vertices.values():
                layer = getattr(v, "layer", None)
                if layer is None or isinstance(layer, FrozenLayer):
                    continue
                for f in ("l1", "l2", "dropout"):
                    val = getattr(ft, f)
                    if val is not None and hasattr(layer, f):
                        setattr(layer, f, val)

        net = ComputationGraph(conf)
        net.init()
        for name in conf.vertices:
            if name not in reinit and name in self._params:
                net.params[name] = self._params[name]
                if name in self._state and self._state[name]:
                    net.state[name] = self._state[name]
        net.updater_state = conf.updater.init_state(net.params)
        return net


TransferLearning.GraphBuilder = _GraphBuilderNS


class GraphTransferLearningHelper:
    """Featurize-then-train for a ComputationGraph with a frozen frontier
    (ref: TransferLearningHelper.java CG path :52-57, initHelperGraph —
    split the graph at the frontier; the frozen subgraph runs once per
    batch, the unfrozen subset trains on the cached crossing activations).

    `frozen_at`: vertex names to freeze at (the frontier); the frozen set
    is their ancestor closure. Crossing edges (frozen vertex feeding an
    unfrozen one) become the tail subgraph's network inputs. Both halves
    get COPIES of the trained params (the jitted train steps donate their
    buffers, so sharing references across nets aliases deleted arrays)."""

    def __init__(self, net, *frozen_at: str):
        from deeplearning4j_tpu.nn.conf.network import (
            ComputationGraphConfiguration)
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        if not frozen_at:
            raise ValueError("name at least one frontier vertex")
        missing = [n for n in frozen_at if n not in net.conf.vertices]
        if missing:
            raise ValueError(f"unknown vertex name(s) {missing}")
        self.full_net = net
        conf = net.conf

        frozen = _ancestor_closure(conf.vertices, conf.vertex_inputs,
                                   frozen_at)
        self.frozen = frozen
        tail_names = [n for n in conf.vertices if n not in frozen]
        if not tail_names:
            raise ValueError("frontier freezes the whole graph")
        out_types = net._infer_types()

        def _subconf(names, inputs, input_types, outputs):
            sub = ComputationGraphConfiguration(
                seed=conf.seed, updater=conf.updater, dtype=conf.dtype,
                gradient_normalization=conf.gradient_normalization,
                gradient_normalization_threshold=(
                    conf.gradient_normalization_threshold),
                tbptt_fwd_length=conf.tbptt_fwd_length,
                tbptt_back_length=conf.tbptt_back_length)
            for n in conf.topological_order():
                if n in names:
                    sub.vertices[n] = conf.vertices[n]
                    sub.vertex_inputs[n] = list(
                        conf.vertex_inputs.get(n, []))
            sub.network_inputs = list(inputs)
            sub.input_types = dict(input_types)
            sub.network_outputs = list(outputs)
            return sub

        # crossing sources: frozen vertices feeding the tail
        crossing: List[str] = []
        for name in conf.topological_order():
            if name in frozen:
                continue
            for src in conf.vertex_inputs.get(name, []):
                if src in frozen and src not in crossing:
                    crossing.append(src)
        if not crossing:
            raise ValueError("no frozen vertex feeds the unfrozen tail")
        self._crossing = crossing

        tail_outputs = [o for o in conf.network_outputs if o in tail_names]
        if not tail_outputs:
            raise ValueError("no network output survives outside the "
                             "frozen set")
        tail_conf = _subconf(tail_names, crossing,
                             {c: out_types[c] for c in crossing},
                             tail_outputs)
        # frozen subgraph: original inputs -> crossing activations ONLY
        # (featurize must not pay for the tail's forward)
        frozen_conf = _subconf(
            frozen, conf.network_inputs,
            {k: conf.input_types[k] for k in conf.network_inputs},
            crossing)

        def _copy(tree):
            return jax.tree_util.tree_map(lambda a: jax.numpy.array(a),
                                          tree)

        self._frozen_net = ComputationGraph(frozen_conf)
        self._frozen_net.init()
        for n in frozen:
            self._frozen_net.params[n] = _copy(net.params[n])
            if net.state.get(n):
                self._frozen_net.state[n] = _copy(net.state[n])

        self.tail = ComputationGraph(tail_conf)
        self.tail.init()
        for n in tail_names:
            self.tail.params[n] = _copy(net.params[n])
            if net.state.get(n):
                self.tail.state[n] = _copy(net.state[n])
        self.tail.updater_state = tail_conf.updater.init_state(
            self.tail.params)

    def featurize(self, ds):
        """Run ONLY the frozen subgraph; returns ({crossing: activation},
        labels). Masked variable-length inputs are not supported (the
        crossing cache would need per-input masks threaded to the tail) —
        rejected loudly rather than silently mis-featurized."""
        if getattr(ds, "features_mask", None) is not None or \
                getattr(ds, "labels_mask", None) is not None:
            raise NotImplementedError(
                "featurize with feature/label masks is unsupported; train "
                "the graph directly (fit handles masks) or drop the masks")
        outs = self._frozen_net.output(ds.features)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        feats = {c: np.asarray(o) for c, o in zip(self._crossing, outs)}
        return feats, ds.labels

    def fit_featurized(self, feats, labels, epochs: int = 1,
                       batch_size: int = 32) -> None:
        # ArrayDataSetIterator accepts dict features (MultiDataSet
        # equivalent), so multi-crossing tails batch like any CG fit
        x = feats[self._crossing[0]] if len(feats) == 1 else feats
        self.tail.fit(x, labels, epochs=epochs, batch_size=batch_size)
        # tail params AND state (BN running stats, centers) flow back
        # into the full net by name — copies, not donated aliases
        for name in self.tail.conf.vertices:
            self.full_net.params[name] = jax.tree_util.tree_map(
                lambda a: jax.numpy.array(a), self.tail.params[name])
            if self.tail.state.get(name):
                self.full_net.state[name] = jax.tree_util.tree_map(
                    lambda a: jax.numpy.array(a), self.tail.state[name])

    def output_from_featurized(self, feats):
        if len(self._crossing) == 1:
            return self.tail.output(feats[self._crossing[0]])
        return self.tail.output(feats)

    def unfrozen_graph(self):
        return self.tail

