"""Activation functions.

TPU-native equivalent of the ND4J activation set the reference dispatches to
(referenced from layer configs, e.g. deeplearning4j-nn/src/main/java/org/
deeplearning4j/nn/conf/layers/Layer.java `activation` field). On TPU every
activation is a pure jnp function fused by XLA into the surrounding matmul —
there is no per-activation native kernel to manage (ref's cuDNN fused
bias+activation, CudnnConvolutionHelper.java:435-436, comes for free here).
"""

from __future__ import annotations

import re

import jax
import jax.numpy as jnp

__all__ = ["get", "register", "ACTIVATIONS"]


def _identity(x):
    return x


def _cube(x):
    return x ** 3


def _hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def _hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


def _leakyrelu(x, alpha=0.01):
    return jnp.where(x >= 0, x, alpha * x)


def _rationaltanh(x):
    # 1.7159 * tanh(2x/3) approximated rationally (ND4J ActivationRationalTanh)
    ax = jnp.abs(2.0 * x / 3.0)
    tanh_approx = jnp.sign(x) * (1.0 - 1.0 / (1.0 + ax + ax * ax + 1.41645 * ax ** 4))
    return 1.7159 * tanh_approx


def _rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


def _softsign(x):
    return x / (1.0 + jnp.abs(x))


def _swish(x):
    return x * jax.nn.sigmoid(x)


def _gelu(x):
    return jax.nn.gelu(x)


def _softmax(x):
    return jax.nn.softmax(x, axis=1 if x.ndim > 1 else -1)


def _thresholdedrelu(x, theta=1.0):
    return jnp.where(x > theta, x, 0.0)


ACTIVATIONS = {
    "identity": _identity,
    "linear": _identity,
    "cube": _cube,
    "elu": jax.nn.elu,
    "hardsigmoid": _hardsigmoid,
    "hardtanh": _hardtanh,
    "leakyrelu": _leakyrelu,
    "rationaltanh": _rationaltanh,
    "rectifiedtanh": _rectifiedtanh,
    "relu": jax.nn.relu,
    "relu6": lambda x: jnp.clip(x, 0.0, 6.0),
    "sigmoid": jax.nn.sigmoid,
    "softmax": _softmax,
    "softplus": jax.nn.softplus,
    "softsign": _softsign,
    "tanh": jnp.tanh,
    "selu": jax.nn.selu,
    "swish": _swish,
    "gelu": _gelu,
    "thresholdedrelu": _thresholdedrelu,
}


def register(name: str, fn) -> None:
    """Register a custom activation under ``name``."""
    ACTIVATIONS[name.lower()] = fn


def get(name):
    """Resolve an activation by name (case-insensitive) or pass through
    callables. Parameterized form "name(0.3)" binds the function's second
    positional parameter (e.g. leakyrelu alpha, thresholdedrelu theta) —
    mirrors the reference's IActivation configs carrying an alpha
    (ActivationLReLU.java)."""
    if callable(name):
        return name
    key = str(name).lower()
    m = re.fullmatch(r"(\w+)\(([-+0-9.e]+)\)", key)
    if m:
        base, param = m.group(1), float(m.group(2))
        if base not in ACTIVATIONS:
            raise ValueError(f"Unknown activation '{base}'")
        fn = ACTIVATIONS[base]
        return lambda x: fn(x, param)
    if key not in ACTIVATIONS:
        raise ValueError(f"Unknown activation '{name}'. Known: {sorted(ACTIVATIONS)}")
    return ACTIVATIONS[key]
