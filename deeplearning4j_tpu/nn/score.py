"""Lazy score materialization for the fit hot paths.

The per-batch `self.score_value = float(loss)` the fit loops used to do
is a device->host sync on every batch: it stalls jax's async dispatch
pipeline to one-batch-at-a-time lockstep (tpulint rule
host-sync-in-hot-loop). Instead the loops now assign the RAW device
scalar; `float()` — the sync — happens only when somebody actually reads
`.score_value` (a listener, early stopping, a test) and the result is
cached so repeated reads cost one sync total. Training with no score
consumers never blocks on the loss at all.
"""

from __future__ import annotations

from typing import Any


class LazyScore:
    """Mixin providing a `score_value` float property backed by a raw
    (possibly device-resident) `_score_raw` slot."""

    _score_raw: Any = float("nan")

    @property
    def score_value(self) -> float:
        raw = self._score_raw
        if not isinstance(raw, float):
            raw = float(raw)  # the one deliberate host sync, then cached
            self._score_raw = raw
        return raw

    @score_value.setter
    def score_value(self, value: Any) -> None:
        """Accepts a float or a raw device scalar; conversion is deferred
        to the next read."""
        self._score_raw = value
