"""Loss functions.

TPU-native equivalent of the ND4J LossFunctions set used by the reference's
output layers (deeplearning4j-nn/.../conf/layers/OutputLayer.java `lossFunction`;
impls live in ND4J org.nd4j.linalg.lossfunctions). Every loss here is a pure
function ``loss(labels, preout, activation, mask) -> scalar`` differentiated by
``jax.grad`` — replacing the reference's hand-written computeGradient methods.

Masking semantics follow the reference: per-example (or per-timestep) mask
multiplies the per-element score before reduction, and the mean is taken over
the *unmasked* count (ref: LossUtil / BaseLossFunction scoreArray handling).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations as _act

__all__ = ["get", "score", "LOSSES"]

_EPS = 1e-7


def _reduce(per_elem: jax.Array, mask: Optional[jax.Array]) -> jax.Array:
    """Sum per-element scores to per-example, apply mask, mean over examples.

    per_elem has shape [batch, features] (2-D, time already folded by caller).
    """
    per_example = jnp.sum(per_elem, axis=tuple(range(1, per_elem.ndim)))
    if mask is not None:
        m = mask.reshape(per_example.shape).astype(per_example.dtype)
        return jnp.sum(per_example * m) / jnp.maximum(jnp.sum(m), 1.0)
    return jnp.mean(per_example)


def _mse(y, out):
    return (out - y) ** 2


def _l1(y, out):
    return jnp.abs(out - y)


def _l2(y, out):
    return (out - y) ** 2


def _xent(y, out):
    out = jnp.clip(out, _EPS, 1.0 - _EPS)
    return -(y * jnp.log(out) + (1.0 - y) * jnp.log(1.0 - out))


def _mcxent(y, out):
    return -y * jnp.log(jnp.clip(out, _EPS, None))


def _kld(y, out):
    return y * (jnp.log(jnp.clip(y, _EPS, None)) - jnp.log(jnp.clip(out, _EPS, None)))


def _hinge(y, out):
    # labels in {-1, +1}
    return jnp.maximum(0.0, 1.0 - y * out)


def _squared_hinge(y, out):
    return jnp.maximum(0.0, 1.0 - y * out) ** 2


def _poisson(y, out):
    return out - y * jnp.log(jnp.clip(out, _EPS, None))


def _mape(y, out):
    return 100.0 * jnp.abs((y - out) / jnp.clip(jnp.abs(y), _EPS, None))


def _msle(y, out):
    return (jnp.log1p(jnp.clip(out, -1 + _EPS, None)) - jnp.log1p(jnp.clip(y, -1 + _EPS, None))) ** 2


def _cosine_proximity(y, out):
    yn = y / jnp.clip(jnp.linalg.norm(y, axis=-1, keepdims=True), _EPS, None)
    on = out / jnp.clip(jnp.linalg.norm(out, axis=-1, keepdims=True), _EPS, None)
    return -yn * on


LOSSES = {
    "mse": _mse,
    "squared_loss": _mse,
    "l1": _l1,
    "mean_absolute_error": _l1,
    "l2": _l2,
    "xent": _xent,
    "binary_crossentropy": _xent,
    "mcxent": _mcxent,
    "negativeloglikelihood": _mcxent,
    "categorical_crossentropy": _mcxent,  # Keras-familiar alias
    "kl_divergence": _kld,
    "reconstruction_crossentropy": _xent,
    "hinge": _hinge,
    "squared_hinge": _squared_hinge,
    "poisson": _poisson,
    "mean_absolute_percentage_error": _mape,
    "mean_squared_logarithmic_error": _msle,
    "cosine_proximity": _cosine_proximity,
}


def get(name):
    if callable(name):
        return name
    key = str(name).lower()
    if key not in LOSSES:
        raise ValueError(f"Unknown loss '{name}'. Known: {sorted(LOSSES)}")
    return LOSSES[key]


def score(
    labels: jax.Array,
    preout: jax.Array,
    loss: str,
    activation: str = "identity",
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Mean per-example loss given pre-activation output (ref: computeScore).

    For softmax+MCXENT the log-softmax path is used for numerical stability —
    the gradient is then the standard (p - y), matching the reference's fused
    softmax/MCXENT gradient (ND4J LossMCXENT special case).
    """
    lkey = str(loss).lower() if not callable(loss) else None
    akey = str(activation).lower() if not callable(activation) else None
    if lkey in ("mcxent", "negativeloglikelihood") and akey == "softmax":
        logp = jax.nn.log_softmax(preout, axis=-1)
        per_elem = -labels * logp
        return _reduce(per_elem, mask)
    if lkey in ("xent", "binary_crossentropy") and akey == "sigmoid":
        # stable sigmoid-xent from logits
        per_elem = jnp.maximum(preout, 0.0) - preout * labels + jnp.log1p(
            jnp.exp(-jnp.abs(preout))
        )
        return _reduce(per_elem, mask)
    out = _act.get(activation)(preout)
    per_elem = get(loss)(labels, out)
    return _reduce(per_elem, mask)
