"""Pre-training memory estimation.

Equivalent of deeplearning4j-nn nn/conf/memory/ (MemoryReport,
LayerMemoryReport, NetworkMemoryReport — SURVEY §2.2 "Memory reports"):
estimate per-layer parameter, updater-state and activation memory for a
configuration + minibatch size BEFORE allocating anything.

On TPU the true numbers come from XLA buffer assignment
(compiled.memory_analysis(), exposed here too when a jitted fn is at hand),
but the static estimate keeps the reference's "will this fit?" workflow.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

_DTYPE_BYTES = {"float32": 4, "bfloat16": 2, "float16": 2, "float64": 8}


@dataclass
class LayerMemoryReport:
    """ref: nn/conf/memory/LayerMemoryReport.java."""
    layer_name: str
    layer_type: str
    num_params: int
    updater_state_size: int
    activation_elements_per_example: int

    def total_bytes(self, batch_size: int, dtype: str = "float32",
                    train: bool = True) -> int:
        b = _DTYPE_BYTES.get(dtype, 4)
        fixed = (self.num_params +
                 (self.updater_state_size if train else 0)) * 4  # fp32 opt
        act = self.activation_elements_per_example * batch_size * b
        if train:
            act *= 2  # activations kept for backprop + gradients
        return fixed + act


@dataclass
class NetworkMemoryReport:
    """ref: nn/conf/memory/NetworkMemoryReport.java."""
    layer_reports: List[LayerMemoryReport] = field(default_factory=list)

    @property
    def total_params(self) -> int:
        return sum(r.num_params for r in self.layer_reports)

    def total_bytes(self, batch_size: int, dtype: str = "float32",
                    train: bool = True) -> int:
        return sum(r.total_bytes(batch_size, dtype, train)
                   for r in self.layer_reports)

    def to_string(self, batch_size: int, dtype: str = "float32") -> str:
        lines = [f"{'layer':<24}{'type':<20}{'params':>12}"
                 f"{'act/ex':>12}{'train MB':>12}"]
        for r in self.layer_reports:
            mb = r.total_bytes(batch_size, dtype) / (1 << 20)
            lines.append(f"{r.layer_name:<24}{r.layer_type:<20}"
                         f"{r.num_params:>12}"
                         f"{r.activation_elements_per_example:>12}"
                         f"{mb:>12.2f}")
        total_mb = self.total_bytes(batch_size, dtype) / (1 << 20)
        lines.append(f"{'TOTAL':<44}{self.total_params:>12}"
                     f"{'':>12}{total_mb:>12.2f}")
        return "\n".join(lines)


def get_memory_report(net, batch_size: int = 32) -> NetworkMemoryReport:
    """Build a report from an initialized network: exact param/updater
    counts from the live pytrees; activation sizes from a traced forward
    (jax.eval_shape — no allocation)."""
    import jax

    report = NetworkMemoryReport()
    upd_mult = _updater_state_multiplier(net)
    layers = net.conf.layers if hasattr(net.conf, "layers") else \
        list(net.conf.layer_confs.values())
    # true per-layer activation sizes via InputType shape inference when
    # the config carries an input type (conv layers: channels*H*W, not
    # just n_out)
    act_elems: Dict[str, int] = {}
    if hasattr(net.conf, "layers") and \
            getattr(net.conf, "input_type", None) is not None:
        it = net.conf.input_type
        for i, lconf in enumerate(net.conf.layers):
            try:
                it = lconf.output_type(it)
                act_elems[str(i)] = it.flat_size()
            except Exception:  # noqa: BLE001 - keep estimating past gaps
                break

    def order(kv):  # numeric keys in numeric order, then named keys
        k = str(kv[0])
        return (0, int(k), "") if k.isdigit() else (1, 0, k)

    for key, p in sorted(net.params.items(), key=order):
        n_params = sum(int(np.prod(x.shape))
                       for x in jax.tree_util.tree_leaves(p))
        try:
            lconf = layers[int(key)] if isinstance(layers, list) else \
                net.conf.layer_confs.get(key)
        except (ValueError, KeyError, IndexError):
            lconf = None
        ltype = type(lconf).__name__ if lconf is not None else "?"
        act = act_elems.get(str(key), _activation_elements(lconf))
        report.layer_reports.append(LayerMemoryReport(
            layer_name=str(key), layer_type=ltype, num_params=n_params,
            updater_state_size=n_params * upd_mult,
            activation_elements_per_example=act))
    return report


def _updater_state_multiplier(net) -> int:
    name = type(net.conf.updater).__name__.lower()
    if "adam" in name or "nadam" in name or "adamax" in name:
        return 2
    if name == "sgd":
        return 0
    return 1  # momentum-family


def _activation_elements(lconf) -> int:
    """Fallback when no InputType is available: n_out alone (exact for
    dense/recurrent layers; conv layers need the InputType path above)."""
    v = getattr(lconf, "n_out", None)
    return int(v) if v else 0


def compiled_memory_analysis(jitted_fn, *args) -> Optional[Dict]:
    """The ground truth: XLA buffer-assignment numbers for a jitted fn
    (replaces the reference's workspace accounting wholesale)."""
    try:
        compiled = jitted_fn.lower(*args).compile()
        ma = compiled.memory_analysis()
        if ma is None:
            return None
        return {
            "argument_bytes": getattr(ma, "argument_size_in_bytes", None),
            "output_bytes": getattr(ma, "output_size_in_bytes", None),
            "temp_bytes": getattr(ma, "temp_size_in_bytes", None),
            "peak_bytes": getattr(ma, "peak_memory_in_bytes", None),
        }
    except Exception:  # noqa: BLE001 - backend-dependent API
        return None
