"""MultiLayerNetwork — sequential network runtime.

TPU-native equivalent of deeplearning4j-nn/.../nn/multilayer/
MultiLayerNetwork.java (3156 LoC): fit(:1156), computeGradientAndScore(:2206),
feedForward(:852-964), output(:1866), doTruncatedBPTT(:1393), rnnTimeStep.

Design (SURVEY §7 stance): the reference's Solver/ConvexOptimizer/Updater-view
machinery collapses into ONE jitted train step — `jax.value_and_grad` over the
whole forward replaces per-layer backpropGradient; the updater is a pure
pytree transform; XLA buffer assignment replaces workspaces; `donate_argnums`
donates param/opt-state buffers so the step is in-place on device.

State (BN running stats, RNN carried h/c, center-loss centers) is an explicit
pytree threaded through the step — the functional formulation of the
reference's mutable layer fields.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator, DataSetIterator
from deeplearning4j_tpu.nn.conf.layers import (
    STREAM_STATE_KEYS,
    check_stream_budget,
    AutoEncoder,
    BaseOutputLayerConf,
    CenterLossOutputLayer,
    FrozenLayer,
)
from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
from deeplearning4j_tpu.nn.score import LazyScore
from deeplearning4j_tpu.nn.updater import normalize_gradients
from deeplearning4j_tpu.monitoring import ensure_started
from deeplearning4j_tpu.monitoring.listener import (
    finalize_fit_telemetry, maybe_record_fit_iteration)
from deeplearning4j_tpu.monitoring.tracing import phase_detail, span
from deeplearning4j_tpu.optimize.listeners import close_listeners
from deeplearning4j_tpu.pipeline.padding import (
    group_signature, num_real_examples, pad_batch, with_example_weights)
from deeplearning4j_tpu.resilience.durable import (
    capture_cursor_pass, consume_restored_cursor, dispatch_boundary)
from deeplearning4j_tpu.resilience.sentinel import (
    apply_step, effective_policy, guard_updates, tree_finite)

log = logging.getLogger(__name__)


def _tree_sub(params, steps):
    return jax.tree_util.tree_map(lambda p, s: p - s, params, steps)


def _strip_stream_state(state):
    """Drop transient streaming carries (RNN h/c, attention KV caches —
    STREAM_STATE_KEYS) from a state pytree. The fused lax.scan fit path
    needs the carry structure identical on every step, and non-carry
    training already ignores these keys at read (_forward strips them),
    so the scan path keeps them out of the carry entirely — same rule
    ParallelWrapper's averaging scan applies."""
    return {k: ({kk: vv for kk, vv in v.items()
                 if kk not in STREAM_STATE_KEYS}
                if isinstance(v, dict) else v)
            for k, v in state.items()}


class MultiLayerNetwork(LazyScore):
    """Sequential network with fit/output/evaluate (ref: MultiLayerNetwork.java)."""

    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layers = conf.layers
        self.params: Dict[str, Any] = {}
        self.state: Dict[str, Any] = {}
        self.updater_state: Dict[str, Any] = {}
        self.listeners: List = []
        self.iteration_count = 0
        self.epoch_count = 0
        self.score_value = float("nan")
        self._rng = None
        self._jit_cache: Dict[Any, Any] = {}
        self._initialized = False
        # listener capability flags, hoisted to fit-loop setup (None =
        # not inside fit(): _fit_batch recomputes for direct callers)
        self._stash_features: Optional[bool] = None
        # non-finite sentinel policy override (None = process default;
        # see resilience/sentinel.py)
        self.nonfinite_policy: Optional[str] = None
        # durable-state plumbing (resilience/durable.py): the data-
        # pipeline cursor a checkpoint captures (batches DISPATCHED this
        # epoch + the canonical pad width), a restored cursor awaiting
        # application at the next fit, and the armed preemption guard
        self._dispatched_in_epoch = 0
        self._canon_in_epoch: Optional[int] = None
        self._restored_pipeline_state: Optional[Dict[str, Any]] = None
        self._cursor_pass: Optional[int] = None  # pass index mid-fit
        self._preemption_guard = None

    # ------------------------------------------------------------------
    # init
    # ------------------------------------------------------------------
    def init(self):
        """Initialize params/state (ref: MultiLayerNetwork.init())."""
        if self.conf.input_type is None:
            # try to infer from first layer's n_in
            first = self.layers[0]
            n_in = getattr(first, "n_in", None)
            if n_in is None:
                raise ValueError("set conf.input_type or first layer n_in")
            from deeplearning4j_tpu.nn.conf.inputs import InputType
            self.conf.input_type = InputType.feed_forward(n_in)
        from deeplearning4j_tpu.nn.conf.network import _infer_shapes_and_preprocessors
        _infer_shapes_and_preprocessors(self.conf)

        key = jax.random.PRNGKey(self.conf.seed)
        self._rng = jax.random.PRNGKey(self.conf.seed + 1)
        its = self.conf.layer_input_types()
        keys = jax.random.split(key, max(2, len(self.layers)))
        self.params, self.state = {}, {}
        for i, layer in enumerate(self.layers):
            p, s = layer.init(keys[i], its[i])
            self.params[str(i)] = p
            self.state[str(i)] = s
        self.updater_state = self.conf.updater.init_state(self.params)
        self._initialized = True
        return self

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def add_listener(self, listener):
        self.listeners.append(listener)
        return self

    def num_params(self) -> int:
        return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(self.params))

    # ------------------------------------------------------------------
    # forward
    # ------------------------------------------------------------------
    def _forward(self, params, state, x, *, train, rng, fmask=None,
                 carry_rnn=False, stream=False, pad=None,
                 upto: Optional[int] = None):
        """Pure forward pass. Returns (activation_list, new_state).

        activation_list[i] is the OUTPUT of layer i (post preprocessor+layer).

        `pad` (traced scalar) marks a left-padded streaming chunk:
        non-streaming layers (LSTM h/c carry-through on masked steps) see
        an ordinary key mask, while streaming cache layers get pad_left
        for packed slot accounting (pads never enter caches).
        """
        acts = []
        new_state = {}
        mask = fmask
        # inference honors the bf16 compute policy too (training gets it
        # in _loss; double application is a no-op): bf16 activations +
        # weights halve HBM traffic and the carried KV-cache memory. The
        # public output() / rnn_time_step cast the final activation back
        # to f32 at the jit boundary.
        params, x = self._cast_compute(params, x)
        if pad is not None:
            mask = jnp.broadcast_to(jnp.arange(x.shape[-1]) >= pad,
                                    (x.shape[0], x.shape[-1]))
        its = self.conf.layer_input_types()
        h = x
        n = len(self.layers) if upto is None else upto
        for i in range(n):
            layer = self.layers[i]
            pre = self.conf.preprocessors.get(i)
            if pre is not None:
                h = pre.apply(h, mask)
                mask = pre.output_mask(mask, its[i])
            li_state = state.get(str(i), {})
            if not carry_rnn:
                li_state = {k: v for k, v in li_state.items()
                            if k not in STREAM_STATE_KEYS}
            rng_i = None
            if rng is not None:
                rng_i = jax.random.fold_in(rng, i)
            p_i = params[str(i)]
            wn = getattr(layer, "weight_noise", None)
            if wn is not None and train and rng_i is not None and \
                    isinstance(p_i, dict):
                p_i = wn.apply_to_params(
                    p_i, jax.random.fold_in(rng_i, 987))
            # stream (inference KV-cache decode) is distinct from
            # carry_rnn (tbptt h/c carry during training): tbptt trains
            # attention full-context per chunk
            extra = {}
            m_i = mask
            if getattr(layer, "supports_streaming", False):
                extra["stream"] = stream
                if pad is not None:
                    # packed accounting replaces the mask for cache layers
                    extra["pad_left"] = pad
                    m_i = None
            h, s_new = layer.apply(p_i, h, li_state, train=train,
                                   rng=rng_i, mask=m_i, **extra)
            mask = layer.output_mask(mask, its[i])
            new_state[str(i)] = s_new
            acts.append(h)
        # pass through untouched state of layers beyond `upto`
        for i in range(n, len(self.layers)):
            new_state[str(i)] = state.get(str(i), {})
        return acts, new_state

    def _dequantized(self, params):
        """Materialize int8 QuantizedTensor leaves (W8A16 serving,
        optimize/quantization.py) as float32 — the inference paths run
        activations in f32 (conf.dtype is a TRAINING-cast policy), and
        _cast_compute re-casts to bf16 after this when scoring under a
        bf16 conf. XLA fuses the int8 convert into each consumer either
        way, which is where the HBM saving lives."""
        from deeplearning4j_tpu.optimize.quantization import dequantize_tree
        return dequantize_tree(params, jnp.float32)

    def _cast_compute(self, params, x):
        """Mixed precision: when conf.dtype is bfloat16, run forward in bf16
        (master params stay fp32 — grads flow back through the cast). On TPU
        this keeps matmuls/convs on the MXU bf16 path with fp32 accumulation
        (XLA default), the same fp16-compute policy the reference's cuDNN
        helpers select (BaseCudnnHelper dataType)."""
        from deeplearning4j_tpu.nn.compute import bf16_cast, bf16_cast_tree
        if getattr(self, "_quantized", False):
            params = self._dequantized(params)
        if self.conf.dtype in ("bfloat16", "bf16"):
            return bf16_cast_tree(params), bf16_cast(x)
        return params, x

    def _loss(self, params, state, x, y, rng, fmask, lmask, *, train=True,
              carry_rnn=False):
        """Scalar loss (data loss + L1/L2) and new state
        (ref: computeGradientAndScore :2206 + calcL1/L2 terms)."""
        params, x = self._cast_compute(params, x)
        out_idx = len(self.layers) - 1
        out_layer = self.layers[out_idx]
        acts, new_state = self._forward(params, state, x, train=train, rng=rng,
                                        fmask=fmask, carry_rnn=carry_rnn,
                                        upto=out_idx)
        h = acts[-1] if acts else x
        mask = lmask
        pre = self.conf.preprocessors.get(out_idx)
        if pre is not None:
            h = pre.apply(h, fmask)
        rng_o = jax.random.fold_in(rng, out_idx) if rng is not None else None
        if not hasattr(out_layer, "compute_score"):
            raise ValueError("last layer must be an output layer to compute loss")
        preout = out_layer.preout(params[str(out_idx)], h, train=train, rng=rng_o)
        # loss in >=fp32 under mixed precision (keeps f64 for gradient checks)
        preout = preout.astype(jnp.promote_types(preout.dtype, jnp.float32))
        score = out_layer.compute_score(y, preout, mask)
        o_state = state.get(str(out_idx), {})
        if isinstance(out_layer, CenterLossOutputLayer):
            score = score + out_layer.center_loss(h, y, o_state)
            o_state = out_layer.update_centers(jax.lax.stop_gradient(h), y, o_state)
        new_state[str(out_idx)] = o_state
        score = score + self._reg_loss(params)
        return score, new_state

    def _reg_loss(self, params):
        reg = 0.0
        for i, layer in enumerate(self.layers):
            l1c = layer.l1_coeffs()
            l2c = layer.l2_coeffs()
            if not l1c and not l2c:
                continue
            p = params[str(i)]
            for k, coeff in l1c.items():
                if k in p:
                    reg = reg + coeff * jnp.sum(jnp.abs(p[k]))
            for k, coeff in l2c.items():
                if k in p:
                    reg = reg + 0.5 * coeff * jnp.sum(p[k] ** 2)
        return reg

    # ------------------------------------------------------------------
    # jitted steps (cached per (carry_rnn, mask presence) signature)
    # ------------------------------------------------------------------
    def _get_train_step(self, carry_rnn: bool, policy: str = "off"):
        """One jitted optimizer step. With the non-finite sentinel
        (policy "skip"/"record" — resilience/sentinel.py) the step also
        returns a raw device ok-flag, and under "skip" a bad step
        applies a where-zeroed update: params/opt-state/BN-stats keep
        their pre-step values, all on device, no host sync. Returns a
        4-tuple under "off" (the pre-resilience contract bench.py and
        the distributed workers rely on), a 5-tuple otherwise."""
        if getattr(self, "_quantized", False):
            raise RuntimeError(
                "this network was quantized for inference "
                "(quantize_for_inference) — int8 weights have no "
                "gradient path; train the fp checkpoint and re-quantize")
        # conf.dtype is baked into the trace: key it (stale compiled
        # steps would silently keep the old precision); ditto policy
        key = ("train", carry_rnn, self.conf.dtype, policy)
        if key not in self._jit_cache:
            conf = self.conf

            def step(params, state, upd_state, x, y, rng, fmask, lmask):
                (loss, new_state), grads = jax.value_and_grad(
                    lambda p: self._loss(p, state, x, y, rng, fmask, lmask,
                                         train=True, carry_rnn=carry_rnn),
                    has_aux=True)(params)
                # sentinel reads RAW grads: normalization (clipping)
                # must not mask an Inf by rescaling it
                ok = None if policy == "off" else tree_finite(loss, grads)
                grads = normalize_gradients(grads, conf.gradient_normalization,
                                            conf.gradient_normalization_threshold)
                steps, new_upd = conf.updater.update(grads, upd_state, params)
                new_params = _tree_sub(params, steps)
                if any(getattr(l, "constraints", None) for l in self.layers):
                    from deeplearning4j_tpu.nn.conf.constraints import \
                        apply_constraints
                    new_params = apply_constraints(self.layers, new_params)
                if policy == "off":
                    return new_params, new_state, new_upd, loss
                new_params, new_upd, new_state = guard_updates(
                    ok, policy, (new_params, params),
                    (new_upd, upd_state), (new_state, state))
                return new_params, new_state, new_upd, loss, ok

            self._jit_cache[key] = jax.jit(step, donate_argnums=(0, 2))
        return self._jit_cache[key]

    def _get_scan_train_step(self, k: int, policy: str = "off"):
        """Fused multi-step dispatch: K optimizer steps in ONE jitted,
        buffer-donating call via lax.scan over stacked batches
        ([K, B, ...]), returning the per-step loss vector as a single
        device array. Each scan iteration is exactly the _get_train_step
        body, so K Python→XLA round-trips (and K listener-side dispatch
        gaps) collapse into one — the micro-batch fusion μ-cuDNN applies
        to framework overhead (PAPERS.md). Streaming carries are
        stripped from the scanned state (see _strip_stream_state).

        With the non-finite sentinel on (policy != "off") each scan
        iteration checks its own loss/grads and (under "skip") zeroes
        its own update, so one poisoned batch cannot corrupt the other
        K-1 fused steps; the per-step ok-flags come back as a [K] device
        vector alongside the losses."""
        if getattr(self, "_quantized", False):
            raise RuntimeError(
                "this network was quantized for inference "
                "(quantize_for_inference) — int8 weights have no "
                "gradient path; train the fp checkpoint and re-quantize")
        key = ("scan", k, self.conf.dtype, policy)
        if key not in self._jit_cache:
            conf = self.conf

            def stepk(params, state, upd_state, xs, ys, rngs, fmasks, lmasks):
                def one(carry, inp):
                    p, s, u = carry
                    x, y, rng, fm, lm = inp
                    (loss, s2), grads = jax.value_and_grad(
                        lambda pp: self._loss(pp, s, x, y, rng, fm, lm,
                                              train=True, carry_rnn=False),
                        has_aux=True)(p)
                    ok = None if policy == "off" else \
                        tree_finite(loss, grads)
                    grads = normalize_gradients(
                        grads, conf.gradient_normalization,
                        conf.gradient_normalization_threshold)
                    steps, u2 = conf.updater.update(grads, u, p)
                    p2 = _tree_sub(p, steps)
                    if any(getattr(l, "constraints", None)
                           for l in self.layers):
                        from deeplearning4j_tpu.nn.conf.constraints import \
                            apply_constraints
                        p2 = apply_constraints(self.layers, p2)
                    s2 = _strip_stream_state(s2)
                    if policy != "off":
                        p2, u2, s2 = guard_updates(
                            ok, policy, (p2, p), (u2, u), (s2, s))
                    out = loss if policy == "off" else (loss, ok)
                    return (p2, s2, u2), out

                (p, s, u), out = jax.lax.scan(
                    one, (params, _strip_stream_state(state), upd_state),
                    (xs, ys, rngs, fmasks, lmasks))
                if policy == "off":
                    return p, s, u, out
                losses, oks = out
                return p, s, u, losses, oks

            self._jit_cache[key] = jax.jit(stepk, donate_argnums=(0, 2))
        return self._jit_cache[key]

    def _get_phase_steps(self, carry_rnn: bool, policy: str = "off"):
        """Split train step for span phase detail
        (monitoring.set_phase_detail): forward (vjp residuals), backward
        (vjp apply + grad normalization), update (updater + constraints)
        as three jitted calls, so the forward/backward/update spans carry
        real device timings. Same math as _get_train_step —
        value_and_grad IS vjp — but the seams cost cross-phase XLA fusion
        and materialize the residuals, so the fused step stays the
        default for production throughput.

        Sentinel caveat on this debug path: the flag is computed from
        the NORMALIZED grads (the raw ones live only inside bwd) — the
        fused step, which tests the raw grads, is the exact-semantics
        path. The state leg (BN running stats) IS guarded: upd receives
        the pre/post state and where-selects it with params/opt."""
        if getattr(self, "_quantized", False):
            raise RuntimeError(
                "this network was quantized for inference "
                "(quantize_for_inference) — int8 weights have no "
                "gradient path; train the fp checkpoint and re-quantize")
        key = ("phase", carry_rnn, self.conf.dtype, policy)
        if key not in self._jit_cache:
            conf = self.conf

            def fwd(params, state, x, y, rng, fmask, lmask):
                loss, vjp_fn, new_state = jax.vjp(
                    lambda p: self._loss(p, state, x, y, rng, fmask, lmask,
                                         train=True, carry_rnn=carry_rnn),
                    params, has_aux=True)
                return loss, new_state, vjp_fn

            def bwd(vjp_fn, loss):
                (grads,) = vjp_fn(jnp.ones_like(loss))
                return normalize_gradients(grads, conf.gradient_normalization,
                                           conf.gradient_normalization_threshold)

            def upd(params, grads, upd_state, loss, state, new_state):
                steps, new_upd = conf.updater.update(grads, upd_state, params)
                new_params = _tree_sub(params, steps)
                if any(getattr(l, "constraints", None) for l in self.layers):
                    from deeplearning4j_tpu.nn.conf.constraints import \
                        apply_constraints
                    new_params = apply_constraints(self.layers, new_params)
                if policy == "off":
                    return new_params, new_upd, new_state
                ok = tree_finite(loss, grads)
                new_params, new_upd, new_state = guard_updates(
                    ok, policy, (new_params, params),
                    (new_upd, upd_state), (new_state, state))
                return new_params, new_upd, new_state, ok

            self._jit_cache[key] = (jax.jit(fwd), jax.jit(bwd),
                                    jax.jit(upd, donate_argnums=(1, 2)))
        return self._jit_cache[key]

    def _get_output_fn(self, train: bool, carry_rnn: bool,
                       stream: bool = False, padded: bool = False,
                       donate: bool = False):
        # the process-wide stream-cache sharding config is part of the
        # key: flipping it retraces the step for EVERY net on next use
        # (a stale compiled step would silently keep the old layout);
        # same for the paged-decode impl (xla fallback vs pallas kernel)
        from deeplearning4j_tpu.nn.compute import f32_head as head
        from deeplearning4j_tpu.nn.conf import layers as _L
        # donation only means anything where XLA aliases buffers; on CPU
        # it would just warn, so resolve it off there and share the
        # non-donating trace
        donate = donate and jax.default_backend() != "cpu"
        key = ("out", train, carry_rnn, stream, padded, donate,
               self.conf.dtype,
               _L._STREAM_CACHE_SHARDING if stream else None,
               _L._PAGED_DECODE_IMPL if stream else None)
        if key not in self._jit_cache:
            if padded:
                # left-padded packed chunk: pad count is a TRACED scalar,
                # so every prompt length shares this one compiled shape
                def fwd(params, state, x, rng, pad):
                    acts, new_state = self._forward(
                        params, state, x, train=train, rng=rng, fmask=None,
                        carry_rnn=carry_rnn, stream=stream, pad=pad)
                    return head(acts[-1]), new_state
            else:
                def fwd(params, state, x, rng, fmask):
                    acts, new_state = self._forward(
                        params, state, x, train=train, rng=rng, fmask=fmask,
                        carry_rnn=carry_rnn, stream=stream)
                    return head(acts[-1]), new_state

            self._jit_cache[key] = jax.jit(
                fwd, donate_argnums=(1,) if donate else ())
        return self._jit_cache[key]

    def _get_score_fn(self):
        key = ("score", self.conf.dtype)
        if key not in self._jit_cache:
            def sf(params, state, x, y, fmask, lmask):
                loss, _ = self._loss(params, state, x, y, None, fmask, lmask,
                                     train=False)
                return loss

            self._jit_cache[key] = jax.jit(sf)
        return self._jit_cache[key]

    # ------------------------------------------------------------------
    # training
    # ------------------------------------------------------------------
    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def fit(self, data, labels=None, epochs: int = 1, batch_size: int = 32,
            *, steps_per_dispatch: int = 1, prefetch: int = 0,
            pad_tail: Optional[bool] = None,
            execution_plan: Optional[str] = None):
        """Train (ref: MultiLayerNetwork.fit(DataSetIterator) :1156).

        Accepts a DataSetIterator, a DataSet, or (features, labels) arrays.

        ``execution_plan`` ("auto" | "fused" | "xla", tuning/plan.py)
        selects how eligible chains execute — resolved ONCE here, never
        inside a step builder. Sequential nets have no fused graph
        chains, so every plan runs the XLA step; the kwarg validates
        and keeps the fit-loop API uniform across the step builders.

        Dispatch-overhead knobs (pipeline/ — see ARCHITECTURE.md "Input
        pipeline & fused dispatch"):

        - ``steps_per_dispatch=K``: fuse K optimizer steps into one
          jitted lax.scan dispatch (_get_scan_train_step). Listeners
          still fire once per LOGICAL step, receiving a lazy slice of
          the per-step loss vector (no sync unless they float() it).
          Epoch-trailing groups smaller than K run per-batch.
        - ``prefetch=N``: stage batches through DevicePrefetchIterator
          so H2D transfer overlaps compute, N batches deep.
        - ``pad_tail``: pad the ragged last batch to the canonical batch
          shape with an example-weight mask folded into the loss (exact
          for row-wise layers; approximate under batch-stat layers like
          BatchNormalization — pipeline/padding.py). Defaults to ON when
          steps_per_dispatch > 1, OFF otherwise.
        """
        if not self._initialized:
            self.init()
        ensure_started()
        if execution_plan is not None:
            from deeplearning4j_tpu.tuning.plan import apply_execution_plan
            apply_execution_plan(self, execution_plan)
        if labels is not None:
            it: DataSetIterator = ArrayDataSetIterator(data, labels, batch_size)
        elif isinstance(data, DataSet):
            it = ArrayDataSetIterator(data.features, data.labels, batch_size,
                                      data.features_mask, data.labels_mask)
        else:
            it = data
        if it is not data:
            # internally-built iterator: align its pass counter with the
            # ABSOLUTE epoch count, so shuffle orders are a function of
            # the global epoch (a fresh per-fit iterator replays the
            # same stream an uninterrupted single fit would produce —
            # what makes checkpoint cursors transplant across fits)
            it.restore_state({"epoch": self.epoch_count, "pos": 0})
        k = max(1, int(steps_per_dispatch))
        pad = (k > 1) if pad_tail is None else bool(pad_tail)
        if prefetch:
            from deeplearning4j_tpu.pipeline.prefetch import \
                DevicePrefetchIterator
            # pad in the worker, BEFORE the transfer (padding a
            # device-resident batch in the fit loop would be a D2H
            # round-trip)
            it = DevicePrefetchIterator(
                it, prefetch=prefetch, pad_to="auto" if pad else None,
                pad_when=lambda ds: ds.labels is not None)
        # listener capability scan hoisted out of the per-batch path
        self._stash_features = any(getattr(l, "needs_batch_features", False)
                                   for l in self.listeners)
        # a restored checkpoint's data-pipeline cursor fast-forwards the
        # iterator so a mid-epoch resume continues at the exact batch an
        # uninterrupted run would see next (resilience/durable.py);
        # _cursor_pass pins the iterator's OWN pass index (the shuffle
        # seed) for the duration of each pass
        consume_restored_cursor(self, it)
        capture_cursor_pass(self, it)
        try:
            for epoch in range(epochs):
                for lst in self.listeners:
                    lst.on_epoch_start(self, self.epoch_count)
                self._fit_epoch(it, k, pad)
                # increment BEFORE listeners fire: a CheckpointListener save
                # in on_epoch_end must record this epoch as COMPLETED, or
                # resume re-trains it (off-by-one). Listeners still receive
                # the pre-increment epoch index.
                epoch_idx = self.epoch_count
                self.epoch_count += 1
                self._dispatched_in_epoch = 0
                self._canon_in_epoch = None
                self._cursor_pass += 1
                for lst in self.listeners:
                    lst.on_epoch_end(self, epoch_idx)
            # the steady-state loop above never blocks on the device; the
            # one allowed sync is here, after the final batch
            finalize_fit_telemetry(self)
        finally:
            self._stash_features = None
            self._cursor_pass = None
            close_listeners(self.listeners)
        return self

    def _fit_epoch(self, it, k: int, pad: bool):
        """One pass over the iterator: pad ragged batches to the
        canonical (first-batch) row count when `pad`, and fuse runs of
        `k` same-signature batches into single scan dispatches when
        k > 1. Anything unfusable (tbptt sequences, signature changes,
        the trailing partial group) falls back to the per-batch step.

        After every dispatch fully retires, ``dispatch_boundary`` runs:
        deferred checkpoint-cadence saves and a pending preemption are
        honored THERE, where params/counters/RNG/cursor are mutually
        consistent. ``_dispatched_in_epoch``/``_canon_in_epoch`` feed
        the checkpoint's data-pipeline cursor (a resumed fit re-enters
        here with both restored by consume_restored_cursor)."""
        canon = self._canon_in_epoch
        group: List[DataSet] = []
        sig = None

        def flush():
            nonlocal sig
            if not group:
                sig = None
                return
            if len(group) == k:
                self._fit_group(group)
            else:
                for b in group:
                    self._fit_batch(b)
            self._dispatched_in_epoch += len(group)
            group.clear()
            sig = None
            dispatch_boundary(self)

        for ds in it:
            if self.conf.tbptt and ds.features.ndim == 3:
                flush()
                self._fit_tbptt(ds)
                self._dispatched_in_epoch += 1
                dispatch_boundary(self)
                continue
            if canon is None:
                canon = ds.num_examples()
                self._canon_in_epoch = canon
            if pad and ds.labels is not None:
                if ds.num_examples() < canon:
                    ds = pad_batch(ds, canon)
                # every batch carries an example-weight mask so the padded
                # tail shares the full batches' jit signature (exact:
                # ones-masked mean == plain mean)
                ds = with_example_weights(ds)
            if k == 1:
                self._fit_batch(ds)
                self._dispatched_in_epoch += 1
                dispatch_boundary(self)
                continue
            s = group_signature(ds)
            if group and s != sig:
                flush()
            sig = s
            group.append(ds)
            if len(group) == k:
                flush()
        flush()

    def _fit_group(self, group: Sequence[DataSet]):
        """Dispatch one fused K-step scan over stacked batches. Listeners
        fire per logical step with a LAZY slice of the device loss
        vector — the sync-free steady-state contract holds."""
        t0 = time.perf_counter()
        k = len(group)
        with span("etl"):
            rngs = jnp.stack([self._next_rng() for _ in range(k)])
            # jnp.stack is a device-side concat for prefetched (already
            # device-resident) batches and one fused H2D copy otherwise
            xs = jnp.stack([b.features for b in group])
            ys = jnp.stack([b.labels for b in group])
            fmasks = None if group[0].features_mask is None else \
                jnp.stack([b.features_mask for b in group])
            lmasks = None if group[0].labels_mask is None else \
                jnp.stack([b.labels_mask for b in group])
        policy = effective_policy(self)
        step = self._get_scan_train_step(k, policy)
        with span("step"):
            # apply_step absorbs the [K] sentinel flag vector (recorded
            # lazily — accounting syncs at its own cadence)
            self.params, self.state, self.updater_state, losses = \
                apply_step(self, policy, step, self.params, self.state,
                           self.updater_state, xs, ys, rngs, fmasks, lmasks)
        # raw device scalar: float() (the host sync) deferred to access
        self.score_value = losses[-1]
        with span("listener"):
            for i, b in enumerate(group):
                loss_i = losses[i]  # lazy device slice, no sync
                if self._stash_features:
                    # per LOGICAL step, so viz listeners pair each
                    # iteration_done with its own batch's features
                    self._last_batch_features = b.features
                for lst in self.listeners:
                    if hasattr(lst, "record_batch"):
                        lst.record_batch(num_real_examples(b))
                    lst.iteration_done(self, self.iteration_count, loss_i)
                self.iteration_count += 1
        maybe_record_fit_iteration(
            self, sum(num_real_examples(b) for b in group),
            time.perf_counter() - t0, n_batches=k)

    def _fit_batch(self, ds: DataSet, carry_rnn: bool = False):
        t0 = time.perf_counter()
        stash = self._stash_features
        if stash is None:  # direct call outside fit(): no hoisted scan
            stash = any(getattr(l, "needs_batch_features", False)
                        for l in self.listeners)
        if stash:
            self._last_batch_features = ds.features  # for viz listeners
        with span("etl"):
            rng = self._next_rng()
            # jnp.asarray here is the jit-boundary copy of the
            # UNPREFETCHED compat path (baselined for tpulint
            # device-transfer-in-hot-loop): fit(prefetch=N) moves these
            # H2D copies into the background pipeline stage
            fmask = None if ds.features_mask is None else jnp.asarray(ds.features_mask)
            lmask = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)
            x = jnp.asarray(ds.features)
            y = jnp.asarray(ds.labels)
        policy = effective_policy(self)
        if phase_detail() and not getattr(self, "_quantized", False):
            # spans time DISPATCH per phase (async: the device may still
            # be executing) — no block_until_ready here, the fit loop's
            # steady state must never stall the pipeline
            fwd, bwd, upd = self._get_phase_steps(carry_rnn, policy)
            with span("forward"):
                loss, new_state, vjp_fn = fwd(self.params, self.state, x, y,
                                              rng, fmask, lmask)
            with span("backward"):
                grads = bwd(vjp_fn, loss)
            with span("update"):
                self.params, self.updater_state, self.state = apply_step(
                    self, policy, upd, self.params, grads,
                    self.updater_state, loss, self.state, new_state)
        else:
            step = self._get_train_step(carry_rnn, policy)
            with span("step"):
                self.params, self.state, self.updater_state, loss = \
                    apply_step(self, policy, step, self.params, self.state,
                               self.updater_state, x, y, rng, fmask, lmask)
        # raw device scalar: float() (the host sync) deferred to access
        self.score_value = loss
        with span("listener"):
            # num_real_examples: a padded tail batch reports its true
            # row count to throughput stats, not the bucket size
            n_real = num_real_examples(ds)
            for lst in self.listeners:
                if hasattr(lst, "record_batch"):
                    lst.record_batch(n_real)
                # raw score, NOT the float property: listeners that use the
                # score sync at their own cadence, the rest never sync
                lst.iteration_done(self, self.iteration_count,
                                   self._score_raw)
        self.iteration_count += 1
        maybe_record_fit_iteration(self, n_real,
                                   time.perf_counter() - t0)

    def _fit_tbptt(self, ds: DataSet):
        """Truncated BPTT: split the sequence into tbptt_fwd_length chunks,
        carrying RNN state across chunks within the batch
        (ref: doTruncatedBPTT :1393)."""
        t = ds.features.shape[2]
        L = self.conf.tbptt_fwd_length
        self.rnn_clear_previous_state()
        for s in range(0, t, L):
            chunk = DataSet(
                ds.features[:, :, s:s + L],
                ds.labels[:, :, s:s + L] if ds.labels is not None and ds.labels.ndim == 3
                else ds.labels,
                ds.features_mask[:, s:s + L] if ds.features_mask is not None else None,
                ds.labels_mask[:, s:s + L] if ds.labels_mask is not None else None,
            )
            self._fit_batch(chunk, carry_rnn=True)

    # ------------------------------------------------------------------
    # inference / scoring
    # ------------------------------------------------------------------
    def output(self, x, train: bool = False, mask=None):
        """Forward pass returning output activations (ref: output :1866)."""
        if not self._initialized:
            self.init()
        fn = self._get_output_fn(train, False)
        rng = self._next_rng() if train else jax.random.PRNGKey(0)
        fmask = None if mask is None else jnp.asarray(mask)
        out, _ = fn(self.params, self.state, jnp.asarray(x), rng, fmask)
        return out

    def feed_forward(self, x, train: bool = False):
        """All layer activations (ref: feedForward :852). Public outputs
        follow the same f32 boundary as output()."""
        from deeplearning4j_tpu.nn.compute import f32_head
        acts, _ = self._forward(self.params, self.state, jnp.asarray(x),
                                train=train, rng=jax.random.PRNGKey(0))
        return [f32_head(a) for a in acts]

    def score(self, ds: DataSet = None, features=None, labels=None) -> float:
        """Loss on a dataset (ref: MultiLayerNetwork.score(DataSet))."""
        if ds is None:
            ds = DataSet(np.asarray(features), np.asarray(labels))
        fn = self._get_score_fn()
        fmask = None if ds.features_mask is None else jnp.asarray(ds.features_mask)
        lmask = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)
        return float(fn(self.params, self.state, jnp.asarray(ds.features),
                        jnp.asarray(ds.labels), fmask, lmask))

    def evaluate(self, iterator):
        """Classification evaluation (ref: MultiLayerNetwork.evaluate)."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        e = Evaluation()
        if isinstance(iterator, DataSet):
            iterator = ArrayDataSetIterator(iterator.features, iterator.labels, 128)
        for ds in iterator:
            out = self.output(ds.features, mask=ds.features_mask)
            e.eval(ds.labels, np.asarray(out), mask=ds.labels_mask)
        return e

    def evaluate_regression(self, iterator):
        from deeplearning4j_tpu.eval.evaluation import RegressionEvaluation
        e = RegressionEvaluation()
        if isinstance(iterator, DataSet):
            iterator = ArrayDataSetIterator(iterator.features, iterator.labels, 128)
        for ds in iterator:
            out = self.output(ds.features, mask=ds.features_mask)
            e.eval(ds.labels, np.asarray(out), mask=ds.labels_mask)
        return e

    # ------------------------------------------------------------------
    # RNN streaming state (ref: rnnTimeStep :~2300, rnnClearPreviousState)
    # ------------------------------------------------------------------
    def rnn_time_step(self, x, mask=None, pad_left=None,
                      donate_state=False):
        """Stateful streaming inference: feeds one (or more) timesteps,
        carrying h/c (and attention KV caches) across calls
        (ref: rnnTimeStep). `mask` is this chunk's [N, T] key mask for
        padded variable-length batches; attention layers carry it in the
        KV cache so padded positions stay masked on later steps.

        `pad_left` (int, mutually exclusive with mask) marks the first
        pad_left positions as LEFT padding with packed accounting: pads
        never enter caches nor consume streaming positions, so an
        arbitrary-length prompt primes in ONE dispatch at a bucketed
        shape (util/decoding pads to a power of two) with results
        identical to unpadded chunked priming. The pad count rides the
        jit as a traced scalar — one compiled shape per bucket.

        `donate_state=True` donates the carried state's buffers to the
        dispatch (TPU/GPU; a no-op on CPU): the serving engine's
        direct-paged decode path sets it so the page pools update IN
        PLACE (the O(one-token) append) instead of being copied each
        step. The caller must hold no references to the pre-call state
        leaves — the returned state is the only live copy."""
        x = jnp.asarray(x)
        if pad_left is not None:
            if mask is not None:
                raise ValueError("pad_left and mask are mutually exclusive")
            pad_left = int(pad_left)
            if not 0 <= pad_left < x.shape[-1]:
                raise ValueError(f"pad_left {pad_left} out of range for a "
                                 f"chunk of {x.shape[-1]} positions")
            new_pos = check_stream_budget(self, x.shape[-1], self.layers,
                                          pad=pad_left)
            fn = self._get_output_fn(False, True, stream=True, padded=True,
                                     donate=donate_state)
            out, new_state = fn(self.params, self.state, x,
                                jax.random.PRNGKey(0),
                                jnp.asarray(pad_left, jnp.int32))
        else:
            new_pos = check_stream_budget(self, x.shape[-1], self.layers)
            fn = self._get_output_fn(False, True, stream=True,
                                     donate=donate_state)
            out, new_state = fn(self.params, self.state, x,
                                jax.random.PRNGKey(0),
                                None if mask is None else jnp.asarray(mask))
        consumed = new_pos - getattr(self, "_stream_pos", 0)
        self._stream_pos = new_pos
        rows = getattr(self, "_stream_pos_rows", None)
        if rows is not None:     # per-row positions (after per-row rewind)
            self._stream_pos_rows = rows + consumed
            self._stream_pos = int(self._stream_pos_rows.max())
        self.state = new_state
        return out


    def set_stream_cache_sharding(self, mesh, axis: str = "data"):
        """Shard streaming attention KV caches over the sequence axis of
        `mesh` (None reverts to single-device caches). PROCESS-WIDE, like
        use_cnn_data_format: the setting applies to every net, and since
        it is part of each streaming step's jit key, any net retraces
        with the new layout on its next streaming call — no stale
        compiled steps. Streaming decode (rnn_time_step / sample_stream /
        beam_search) then runs sequence-parallel: per-device cache memory
        is O(cache_length / n_devices) and XLA inserts the cross-device
        softmax combine."""
        from deeplearning4j_tpu.nn.conf.layers import (
            set_stream_cache_sharding)
        set_stream_cache_sharding(mesh, axis)
        return self

    def rnn_clear_previous_state(self):
        self._stream_pos = 0
        self._stream_pos_rows = None
        for k, s in self.state.items():
            self.state[k] = {kk: vv for kk, vv in s.items()
                             if kk not in STREAM_STATE_KEYS}

    # ------------------------------------------------------------------
    # layerwise pretraining (ref: MultiLayerNetwork.pretrain :220)
    # ------------------------------------------------------------------
    def pretrain(self, iterator, epochs: int = 1):
        """Greedy layerwise pretraining of AutoEncoder/VAE layers."""
        if getattr(self, "_quantized", False):
            raise RuntimeError(
                "this network was quantized for inference "
                "(quantize_for_inference) — int8 weights have no "
                "gradient path; train the fp checkpoint and re-quantize")
        if not self._initialized:
            self.init()
        for i, layer in enumerate(self.layers):
            if not isinstance(layer, AutoEncoder) and not hasattr(layer, "pretrain_loss"):
                continue
            self._pretrain_layer(i, layer, iterator, epochs)
        return self

    def _pretrain_layer(self, idx, layer, iterator, epochs):
        upd = self.conf.updater
        upd_state = upd.init_state(self.params[str(idx)])

        @jax.jit
        def pstep(p_i, all_params, u_state, x, rng):
            def loss_fn(p):
                params2 = dict(all_params)
                params2[str(idx)] = p
                acts, _ = self._forward(params2, self.state, x, train=False,
                                        rng=None, upto=idx)
                h = acts[-1] if acts else x
                return layer.pretrain_loss(p, h, rng)

            loss, grads = jax.value_and_grad(loss_fn)(p_i)
            steps, new_u = upd.update(grads, u_state, p_i)
            return _tree_sub(p_i, steps), new_u, loss

        for _ in range(epochs):
            if isinstance(iterator, DataSet):
                batches = ArrayDataSetIterator(iterator.features, iterator.labels, 32)
            else:
                batches = iterator
            for ds in batches:
                rng = self._next_rng()
                p_new, upd_state, loss = pstep(self.params[str(idx)], self.params,
                                               upd_state, jnp.asarray(ds.features), rng)
                self.params[str(idx)] = p_new

    # ------------------------------------------------------------------
    # info
    # ------------------------------------------------------------------
    def summary(self) -> str:
        """Layer table (ref: MultiLayerNetwork.summary())."""
        its = self.conf.layer_input_types()
        lines = ["=" * 72,
                 f"{'idx':<4}{'layer':<28}{'out type':<24}{'params':<12}",
                 "-" * 72]
        total = 0
        for i, layer in enumerate(self.layers):
            nparams = sum(int(np.prod(p.shape))
                          for p in jax.tree_util.tree_leaves(self.params.get(str(i), {})))
            total += nparams
            ot = layer.output_type(its[i])
            lines.append(f"{i:<4}{type(layer).__name__:<28}{str(ot.to_dict()):<24}"
                         f"{nparams:<12}")
        lines.append("-" * 72)
        lines.append(f"Total params: {total}")
        lines.append("=" * 72)
        return "\n".join(lines)

    def clone(self) -> "MultiLayerNetwork":
        import copy
        net = MultiLayerNetwork(MultiLayerConfiguration.from_dict(self.conf.to_dict()))
        if self._initialized:
            net.init()
            net.params = jax.tree_util.tree_map(lambda a: jnp.array(a), self.params)
            net.state = jax.tree_util.tree_map(lambda a: jnp.array(a), self.state)
        return net
