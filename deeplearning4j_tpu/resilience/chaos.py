"""Deterministic fault injection for resilience testing.

Each injector wraps any ``DataSetIterator`` (including
``DevicePrefetchIterator`` — or sits UNDER one, in which case the fault
fires inside the prefetch worker thread, which is exactly how you test
worker-death delivery). Faults are counted in GLOBAL batch order across
epochs/passes so "kill at batch 7" means the 8th batch the training run
ever pulls, wherever the epoch boundary falls; with ``once=True`` (the
default) the fault fires a single time and the stream then behaves
normally — the shape every recovery test needs (fail once, prove the
stack completes anyway).

Injectors are plain iterator OBJECTS, not generators: raising out of
``__next__`` does not end the stream, so a retry layer
(``resilience.retry`` in the prefetch worker) can call ``next()`` again
and receive the SAME batch the failed attempt would have produced —
transient-flake semantics with numerics identical to a fault-free run.

Catalog:

- ``RaiseOnBatch``: raise an arbitrary exception before the Nth batch
  (flaky ETL, a dead shard, a poisoned record batch decode).
- ``NaNPoisonIterator``: replace the Nth batch's features (or labels)
  with NaN/Inf — the sentinel's adversary.
- ``LatencyIterator``: sleep before delivering selected batches (H2D /
  ETL stall; exercises prefetch-depth headroom and serving deadlines).
- ``PreemptionIterator``: ``SimulatedPreemption`` after N batches — the
  SIGTERM-style mid-epoch kill for checkpoint-restart tests.
- Mailbox injectors (``MailboxInjector`` subclasses — torn, duplicate,
  delayed delivery): faults on the cross-process serving fleet's
  command transport (``serving/fleet/transport.Mailbox(chaos=...)``),
  plus ``LeaseStallInjector`` for the stalled-lease-but-alive replica.
"""

from __future__ import annotations

import time
from typing import Callable, Iterator, Optional, Sequence, Union

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator

__all__ = ["ChaosIterator", "DelayedDeliveryInjector",
           "DuplicateDeliveryInjector", "FaultBurstInjector",
           "HostLossInjector", "InjectedFault", "LatencyIterator",
           "LeaseStallInjector", "MailboxInjector",
           "NaNPoisonIterator", "PageExhaustionInjector",
           "PreemptionIterator", "ProcessKillInjector", "RaiseOnBatch",
           "RequestFaultInjector", "SimulatedPreemption",
           "TornCommandInjector", "fire"]


def fire(injector, index: int, ctx=None) -> None:
    """Drive an injector OUTSIDE an iterator pipeline.

    The serving engine counts its own events — one "batch" per prefill
    admission or decode dispatch — and fires the injector's
    ``before_batch(index)`` (which may raise or sleep) exactly like
    ``_Cursor`` does for iterator-wrapped faults, advancing the
    injector's global count on success. Pass any ``ChaosIterator``
    constructed with ``base=None`` (the base is only touched by
    iteration, which request-level use never does), or a bare callable
    ``(index) -> None``. None is a no-op.

    ``ctx`` carries the event's subject when the seam has one (the
    serving engine passes the ``GenerationRequest`` being admitted):
    injectors that define ``before_event(index, ctx)`` (e.g.
    :class:`RequestFaultInjector`) receive it and can target faults at
    specific requests; index-only injectors ignore it."""
    if injector is None:
        return
    if not hasattr(injector, "before_batch"):
        injector(index)
        return
    if hasattr(injector, "before_event"):
        injector.before_event(index, ctx)
    else:
        injector.before_batch(index)
    injector.batches_seen = max(injector.batches_seen, index + 1)


class InjectedFault(RuntimeError):
    """Default exception planted by RaiseOnBatch."""


class SimulatedPreemption(RuntimeError):
    """SIGTERM-style mid-epoch kill (the TPU-preemption stand-in)."""


class ChaosIterator(DataSetIterator):
    """Base injector: global batch counting, once-latch, reset passthrough.

    Subclasses override ``before_batch`` (may raise; the underlying batch
    is NOT consumed, so a retry re-delivers it) and/or ``transform``
    (rewrites the batch about to be yielded).

    ``base`` may be None for request-level (non-iterator) use: the
    serving engine drives ``before_batch`` directly through ``fire()``,
    one event per prefill admission or decode dispatch.
    """

    def __init__(self, base: DataSetIterator, once: bool = True):
        self.base = base
        self.once = once
        self.batches_seen = 0
        self.faults_fired = 0

    def reset(self):
        self.base.reset()

    # -- override points ------------------------------------------------
    def before_batch(self, index: int) -> None:
        """Called with the global index of the batch ABOUT to be pulled."""

    def transform(self, ds: DataSet, index: int) -> DataSet:
        return ds

    # -- plumbing -------------------------------------------------------
    def _fire(self) -> bool:
        """Latch: True if a fault may fire now (respects `once`)."""
        if self.once and self.faults_fired:
            return False
        self.faults_fired += 1
        return True

    def __iter__(self) -> Iterator[DataSet]:
        return _Cursor(self)


class _Cursor:
    """Non-generator iterator so an injected raise doesn't end the pass."""

    def __init__(self, chaos: ChaosIterator):
        self._chaos = chaos
        self._it = iter(chaos.base)

    def __iter__(self):
        return self

    def __next__(self) -> DataSet:
        c = self._chaos
        c.before_batch(c.batches_seen)  # may raise; batch not yet consumed
        ds = next(self._it)
        out = c.transform(ds, c.batches_seen)
        c.batches_seen += 1
        return out


class RaiseOnBatch(ChaosIterator):
    """Raise before delivering global batch `n` (0-based).

    ``exc`` is an exception factory (class or zero-arg callable); with
    ``once=False`` every pull of batch-index ``n + k*period`` fails
    (period=0 repeats the same index forever — pair with a bounded
    retry to prove exhaustion raises)."""

    def __init__(self, base: DataSetIterator, n: int,
                 exc: Callable[[], BaseException] = InjectedFault,
                 once: bool = True, period: int = 0):
        super().__init__(base, once=once)
        self.n = int(n)
        self.exc = exc
        self.period = int(period)

    def before_batch(self, index: int) -> None:
        hit = index == self.n or (
            self.period > 0 and index > self.n
            and (index - self.n) % self.period == 0)
        if hit and self._fire():
            raise self.exc()


class FaultBurstInjector(ChaosIterator):
    """A BURST of exactly `k` faults starting at event `n`, then clear.

    The once-latch generalized to a count: every event at index >= `n`
    raises until `k` faults have fired (optionally only while the index
    stays inside ``[n, n + window)``), after which the stream behaves
    normally forever. Built to drive the serving supervisor's
    escalation-vs-recovery boundary deterministically: a burst of
    ``k <= budget`` decode faults must be ridden out with every request
    completing bit-identically, while ``k > budget`` within the budget
    window must escalate to the terminal fail-all state. Works
    request-level (``base=None`` via ``chaos.fire``) or wrapping an
    iterator.

    Note the count is FAULTS FIRED, not event indices: a seam whose
    index only advances on success (the engine's dispatch counter)
    re-presents the same index after each fault, and an index-based
    burst would fire forever."""

    def __init__(self, base: Optional[DataSetIterator] = None,
                 n: int = 0, k: int = 3,
                 exc: Callable[[], BaseException] = InjectedFault,
                 window: Optional[int] = None):
        super().__init__(base, once=False)
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.n = int(n)
        self.k = int(k)
        self.exc = exc
        self.window = None if window is None else int(window)

    def before_batch(self, index: int) -> None:
        if index < self.n:
            return
        if self.window is not None and index >= self.n + self.window:
            return
        if self.faults_fired < self.k:
            self.faults_fired += 1
            raise self.exc()


class RequestFaultInjector(ChaosIterator):
    """Fault targeted at specific REQUESTS rather than event indices.

    The serving seams (prefill admission, the pop-to-seat window) pass
    the ``GenerationRequest`` being processed as the event context;
    ``match(request)`` picks the victim(s) — by prompt, priority,
    deadline, identity, whatever the test needs — independent of where
    in the admission order the request lands (an index-keyed injector
    breaks as soon as admission order shifts under load). ``once=True``
    (default) faults the first match only."""

    def __init__(self, match: Callable[[object], bool],
                 exc: Callable[[], BaseException] = InjectedFault,
                 base: Optional[DataSetIterator] = None,
                 once: bool = True):
        super().__init__(base, once=once)
        self.match = match
        self.exc = exc

    def before_event(self, index: int, ctx) -> None:
        if ctx is None:
            return
        if self.match(ctx) and self._fire():
            raise self.exc()


class NaNPoisonIterator(ChaosIterator):
    """Replace batch `n`'s features (or labels) with a non-finite value.

    The batch keeps its shape/mask signature, so the fused scan path
    groups it like any other batch — which is the point: prove the
    sentinel skips it INSIDE a fused dispatch."""

    def __init__(self, base: DataSetIterator,
                 n: Union[int, Sequence[int]] = 0,
                 field: str = "features", value: float = np.nan):
        super().__init__(base, once=False)
        if field not in ("features", "labels"):
            raise ValueError(f"field must be features|labels, got {field!r}")
        self.targets = {int(n)} if isinstance(n, (int, np.integer)) \
            else {int(i) for i in n}
        self.field = field
        self.value = value

    def _poison(self, arr):
        if arr is None:
            return None
        if isinstance(arr, dict):
            return {k: self._poison(v) for k, v in arr.items()}
        out = np.array(arr, dtype=np.asarray(arr).dtype, copy=True)
        out[...] = self.value
        return out

    def transform(self, ds: DataSet, index: int) -> DataSet:
        if index not in self.targets:
            return ds
        f, l = ds.features, ds.labels
        if self.field == "features":
            f = self._poison(f)
        else:
            l = self._poison(l)
        out = DataSet(f, l, ds.features_mask, ds.labels_mask)
        real = getattr(ds, "real_examples", None)
        if real is not None:
            out.real_examples = real
        return out


class LatencyIterator(ChaosIterator):
    """Sleep before delivering selected batches (every batch when
    ``every=1``): the H2D/ETL-stall injector."""

    def __init__(self, base: DataSetIterator, seconds: float,
                 every: int = 1, start: int = 0):
        super().__init__(base, once=False)
        self.seconds = float(seconds)
        self.every = max(1, int(every))
        self.start = int(start)

    def before_batch(self, index: int) -> None:
        if index >= self.start and (index - self.start) % self.every == 0:
            time.sleep(self.seconds)


class PageExhaustionInjector(ChaosIterator):
    """Force the serving engine's free KV-page pool down to
    ``free_target`` pages before dispatch `n` (pass it as the engine's
    ``decode_chaos``; one event per decode dispatch).

    `pool` is the paged engine's ``PagePool`` (``engine.page_pool``):
    the injector SEIZES free pages — it never touches allocated ones —
    so active requests keep their pages and complete bit-identically to
    an unperturbed run while new admissions head-block (or time
    out / fail fast, per their deadline and queue policy) until
    ``release()`` returns the seized pages. The graceful-degradation
    proof every capacity incident wants: starvation must shed load,
    never corrupt in-flight streams.

    Quantized pools (``kv_dtype="int8"``) need no special handling:
    seizure is host-side page-id accounting, so the int8 pool bytes and
    the per-page scale sidecar rows never move — a seized page's scales
    simply sit unreferenced until the id is reallocated and the next
    prime/append rewrites both. The bit-identical-actives guarantee
    therefore holds unchanged under quantization (pinned in
    tests/test_serving_quant.py)."""

    def __init__(self, pool, n: int, free_target: int = 0,
                 once: bool = True):
        super().__init__(None, once=once)
        self.pool = pool
        self.n = int(n)
        self.free_target = int(free_target)

    def before_batch(self, index: int) -> None:
        if index >= self.n and self._fire():
            self.pool.seize(self.pool.free_count() - self.free_target)

    def release(self) -> None:
        """Return every seized page to the pool (the incident ends)."""
        self.pool.restore()


class PreemptionIterator(RaiseOnBatch):
    """SIGTERM-style kill: SimulatedPreemption before global batch `n`,
    once — rerunning the fit (FaultTolerantTrainer restart) proceeds
    normally from wherever its checkpoint restored."""

    def __init__(self, base: DataSetIterator, n: int):
        super().__init__(base, n, exc=SimulatedPreemption, once=True)


class ProcessKillInjector(ChaosIterator):
    """HARD kill: send a real signal (default SIGKILL — no handlers, no
    finally blocks, no atexit) to this process before global batch `n`.

    The adversary of the crash-consistent checkpoint format: run a fit
    in a SUBPROCESS with this injector in its pipeline, then prove from
    the parent that every checkpoint committed before the kill is intact
    and loadable, and that a FaultTolerantTrainer resume completes the
    run (tests/test_durable.py). Unlike PreemptionIterator this is not
    catchable, and unlike PreemptionGuard (SIGTERM → drain + emergency
    save) nothing gets to run — it validates durability of what was
    ALREADY on disk, not orderly shutdown.

    With ``delay`` the signal is sent that many seconds after batch `n`
    is reached — landing the kill MID-save when the cadence is arranged
    so a save is in flight."""

    def __init__(self, base: DataSetIterator, n: int,
                 sig: int = 9, delay: float = 0.0):
        super().__init__(base, once=True)
        self.n = int(n)
        self.sig = int(sig)
        self.delay = float(delay)

    def before_batch(self, index: int) -> None:
        if index >= self.n and self._fire():
            import os
            if self.delay:
                time.sleep(self.delay)
            os.kill(os.getpid(), self.sig)
            # SIGKILL never returns; a catchable sig may — give the
            # handler a beat before the stream continues
            time.sleep(0.5)


class HostLossInjector(ProcessKillInjector):
    """RANK-TARGETED host loss: SIGKILL this process at global batch
    ``n`` — but only when this process IS the targeted rank.

    The multi-host adversary of the elastic membership layer
    (resilience/elastic.py): every rank of a fleet runs the SAME
    training script with the same injector config ("kill rank 1 at
    batch 5"), exactly one process dies, and the survivors must detect
    the expired lease, re-mesh, and resume from the committed step
    (tests/test_elastic_multiprocess.py). ``rank`` is the process's own
    stable GLOBAL rank (the lease identity — pass it explicitly; reading
    ``jax.process_index()`` here would be a per-generation id that
    changes across re-meshes). Drive it from an iterator pipeline like
    any ChaosIterator, or request-level via ``chaos.fire`` with
    ``base=None`` (one event per global training step — the
    ElasticTrainer's ``step_chaos`` seam).

    ``kill`` is the action seam (defaults to ``os.kill(getpid(), sig)``)
    so single-process tests can prove the rank gating without dying."""

    def __init__(self, base: Optional[DataSetIterator], n: int,
                 target_rank: int, rank: int, sig: int = 9,
                 delay: float = 0.0,
                 kill: Optional[Callable[[int], None]] = None):
        super().__init__(base, n, sig=sig, delay=delay)
        self.target_rank = int(target_rank)
        self.rank = int(rank)
        self._kill = kill

    def before_batch(self, index: int) -> None:
        if self.rank != self.target_rank:
            return  # not this host's day
        if index >= self.n and self._fire():
            if self.delay:
                time.sleep(self.delay)
            if self._kill is not None:
                self._kill(self.sig)
                return
            import os
            os.kill(os.getpid(), self.sig)
            time.sleep(0.5)  # catchable-signal grace, as ProcessKill


class LeaseStallInjector(ChaosIterator):
    """Freeze a host's lease heartbeats WITHOUT killing the process at
    global batch ``n`` — the hung-host simulation.

    Death and hang must be testable separately: a SIGKILLed host stops
    heartbeating because it is gone; a host wedged in a driver call (or
    livelocked) stops heartbeating while its process — and any collective
    it is half-way through — lives on. Peers see the identical signal
    (an expired lease) and must re-mesh without it, which is exactly
    what this injector proves. ``ledger`` is the process's own
    ``LeaseLedger``; ``release()`` (or ``duration`` seconds) un-freezes
    so recovery-of-the-hung-host scenarios can rejoin."""

    def __init__(self, ledger, n: int, base: Optional[DataSetIterator]
                 = None, once: bool = True,
                 duration: Optional[float] = None):
        super().__init__(base, once=once)
        self.ledger = ledger
        self.n = int(n)
        self.duration = duration
        self._stall_t0: Optional[float] = None

    def before_batch(self, index: int) -> None:
        if self._stall_t0 is not None and self.duration is not None and \
                time.monotonic() >= self._stall_t0 + self.duration:
            self.release()
        if index >= self.n and self._fire():
            self._stall_t0 = time.monotonic()
            self.ledger.stall()

    def release(self) -> None:
        """Un-freeze the heartbeats (the hung host came back)."""
        self._stall_t0 = None
        self.ledger.resume()


# ----------------------------------------------------------------------
# transport chaos (the cross-process serving fleet's mailbox seam)
# ----------------------------------------------------------------------

class MailboxInjector:
    """Base for transport-level faults on the cross-process fleet's
    command mailbox (``serving/fleet/transport.Mailbox(chaos=...)``).

    The mailbox calls ``on_send(dirpath, name, data)`` with the
    serialized command BEFORE its normal atomic-rename write; returning
    True means the injector took over (or withheld) delivery, False
    means deliver normally. Sends are counted so faults target "the
    Nth command this mailbox ever carried", with the same once-latch
    semantics as the iterator injectors — fault once, then behave.

    Subclasses override :meth:`inject`. All of them attack the
    TRANSPORT, never the agent: the delivery contract under test is
    that a torn file quarantines (poll loop survives), a duplicate
    deduplicates (admission idempotent by ``(request id, attempt)``),
    and a delayed command is simply late (at-least-once, unordered)."""

    def __init__(self, n: int = 0, once: bool = True):
        self.n = int(n)
        self.once = once
        self.sends_seen = 0
        self.faults_fired = 0

    def _fire(self) -> bool:
        if self.once and self.faults_fired:
            return False
        self.faults_fired += 1
        return True

    def on_send(self, dirpath: str, name: str, data: bytes) -> bool:
        idx = self.sends_seen
        self.sends_seen += 1
        if idx >= self.n and self._fire():
            return self.inject(dirpath, name, data)
        return False

    def inject(self, dirpath: str, name: str, data: bytes) -> bool:
        raise NotImplementedError


class TornCommandInjector(MailboxInjector):
    """Deliver a TORN command file: the first ``frac`` of the payload
    bytes written straight to the final name — no tmp file, no rename,
    no fsync — exactly the artifact a crashed copy tool (or a sender
    killed mid-write on a filesystem without atomic rename) leaves
    behind. The receiving agent must quarantine it and keep polling;
    the command itself is LOST, which is why every command is safe to
    re-send (at-least-once + dedupe)."""

    def __init__(self, n: int = 0, frac: float = 0.5,
                 keep_bytes: Optional[int] = None, once: bool = True):
        super().__init__(n=n, once=once)
        self.frac = float(frac)
        self.keep_bytes = keep_bytes

    def inject(self, dirpath: str, name: str, data: bytes) -> bool:
        import os
        cut = self.keep_bytes if self.keep_bytes is not None \
            else max(1, int(len(data) * self.frac))
        with open(os.path.join(dirpath, name), "wb") as f:
            f.write(data[:cut])
        return True


class DuplicateDeliveryInjector(MailboxInjector):
    """Deliver the SAME command twice (two atomic files, distinct
    names): the at-least-once failure mode a sender that died between
    "wrote the file" and "recorded that it wrote it" produces on
    re-send. The agent's ``(request id, attempt)`` dedupe must make the
    second copy a counted no-op — admitting a request twice would
    double-serve it."""

    def inject(self, dirpath: str, name: str, data: bytes) -> bool:
        import os
        from deeplearning4j_tpu.resilience.durable import (
            atomic_write_bytes)
        atomic_write_bytes(os.path.join(dirpath, name), data)
        # the duplicate sorts right after the original and still
        # matches the mailbox's cmd_*.json consume filter
        atomic_write_bytes(
            os.path.join(dirpath, name[:-len(".json")] + "_dup.json"),
            data)
        return True


class DelayedDeliveryInjector(MailboxInjector):
    """WITHHOLD matching commands until :meth:`release` — the
    slow-shared-filesystem / delayed-visibility simulation. Ordering is
    a courtesy in the mailbox contract, so a late command must admit
    exactly as a prompt one (possibly after the router already
    re-placed the request elsewhere, in which case the stale
    ``attempt`` fence makes the late admission journal events the
    relay ignores)."""

    def __init__(self, n: int = 0, once: bool = True):
        super().__init__(n=n, once=once)
        self.held: list = []

    def inject(self, dirpath: str, name: str, data: bytes) -> bool:
        self.held.append((dirpath, name, data))
        return True

    def release(self) -> int:
        """Deliver every withheld command (atomically); returns how
        many were released."""
        import os
        from deeplearning4j_tpu.resilience.durable import (
            atomic_write_bytes)
        held, self.held = self.held, []
        for dirpath, name, data in held:
            atomic_write_bytes(os.path.join(dirpath, name), data)
        return len(held)
