"""Bounded exponential-backoff retry with jitter.

The repo-wide retry shape: every transient-failure loop (prefetch worker
re-pulling a flaky base iterator, dataset file resolution racing another
process's decompress, a serving client re-dialing) goes through
``retry_call`` instead of a hand-rolled ``while True: ... time.sleep``.
Hand-rolled unbounded loops are flagged by the tpulint rule
``unbounded-retry``; this helper is the fix it points at.

Design points:

- **Bounded**: ``max_attempts`` is a hard ceiling — the last exception
  re-raises. Unbounded retry turns a dead dependency into a hung
  process (the serving analogue of a lost Spark task retried forever).
- **Backoff with jitter**: delay grows ``base_delay * multiplier**n``
  capped at ``max_delay``, then shrinks by a random fraction up to
  ``jitter`` (decorrelates a fleet of workers hammering a recovering
  dependency in lockstep). Pass an ``rng`` for deterministic tests.
- **Observable**: retries and exhaustions land in the metrics registry
  (``dl4jtpu_retries_total`` / ``dl4jtpu_retry_exhausted_total``,
  labeled by operation).

Deliberately jax-free (like monitoring.metrics): importable from bench
failure paths and pure-host tooling.
"""

from __future__ import annotations

import logging
import random
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Type

from deeplearning4j_tpu.monitoring.metrics import (
    MetricsRegistry, global_registry)

RETRIES = "dl4jtpu_retries_total"
RETRY_EXHAUSTED = "dl4jtpu_retry_exhausted_total"

log = logging.getLogger(__name__)

__all__ = ["RETRIES", "RETRY_EXHAUSTED", "RestartBudget", "RetryPolicy",
           "retry_call", "retryable"]


@dataclass(frozen=True)
class RetryPolicy:
    """How to retry: which exceptions, how many times, how long between.

    ``delay(attempt)`` for attempt=1.. grows geometrically and is capped,
    so the worst-case total stall is bounded and computable:
    ``sum(delay(i) for i in range(1, max_attempts))``.
    """

    max_attempts: int = 3
    base_delay: float = 0.05
    multiplier: float = 2.0
    max_delay: float = 2.0
    #: fraction of each delay randomized away (0 = deterministic)
    jitter: float = 0.5
    retry_on: Tuple[Type[BaseException], ...] = (
        OSError, ConnectionError, TimeoutError)

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError(f"jitter must be in [0, 1], got {self.jitter}")
        if self.multiplier < 1.0:
            raise ValueError(
                f"multiplier must be >= 1 (backoff must not shrink), "
                f"got {self.multiplier}")

    def delay(self, attempt: int,
              rng: Optional[random.Random] = None) -> float:
        """Seconds to sleep before retry `attempt` (1-based)."""
        d = min(self.max_delay,
                self.base_delay * self.multiplier ** (attempt - 1))
        if self.jitter:
            d *= 1.0 - self.jitter * (rng or random).random()
        return d


def retry_call(fn: Callable, *args,
               policy: Optional[RetryPolicy] = None,
               op: Optional[str] = None,
               sleep: Callable[[float], None] = time.sleep,
               rng: Optional[random.Random] = None,
               registry: Optional[MetricsRegistry] = None,
               **kwargs):
    """Call ``fn(*args, **kwargs)``, retrying ``policy.retry_on``
    exceptions with bounded exponential backoff; the final failure
    re-raises. ``op`` labels the retry metrics (defaults to the
    function's name); ``sleep``/``rng`` are injectable for tests."""
    p = policy or RetryPolicy()
    name = op or getattr(fn, "__name__", "call")
    r = registry or global_registry()
    for attempt in range(1, p.max_attempts + 1):
        try:
            return fn(*args, **kwargs)
        except p.retry_on as e:
            if attempt >= p.max_attempts:
                r.counter(RETRY_EXHAUSTED,
                          "Operations that failed every retry attempt",
                          ("op",)).inc(op=name)
                log.warning("%s: giving up after %d attempts (%r)",
                            name, attempt, e)
                raise
            d = p.delay(attempt, rng)
            r.counter(RETRIES, "Transient failures retried with backoff",
                      ("op",)).inc(op=name)
            log.info("%s: attempt %d/%d failed (%r); retrying in %.3fs",
                     name, attempt, p.max_attempts, e, d)
            sleep(d)
    raise AssertionError("unreachable")  # pragma: no cover


class RestartBudget:
    """Sliding-window restart budget: at most ``max_restarts``
    acquisitions per ``window_s`` seconds.

    The windowed sibling of :class:`RetryPolicy`'s attempt bound, for
    *whole-component* restarts (a serving-engine arena rebuild, a
    trainer re-mesh) where what must be bounded is the restart RATE,
    not a per-operation attempt count: a single fault burst should be
    ridden out, but a component restarting forever is a crash loop that
    must escalate to its terminal failure mode instead of masking a
    persistent fault. Old acquisitions age out, so an incident per hour
    never exhausts a per-minute budget. ``clock`` is injectable for
    deterministic tests. ``try_acquire`` callers serialize (the engine
    holds its step lock across recovery), but ``remaining()`` is read
    from lock-free health/metrics probes and therefore never mutates:
    only ``try_acquire`` prunes, so a concurrent probe cannot drop a
    just-recorded restart and leak the budget."""

    def __init__(self, max_restarts: int = 3, window_s: float = 60.0,
                 clock: Callable[[], float] = time.monotonic):
        if max_restarts < 0:
            raise ValueError(
                f"max_restarts must be >= 0, got {max_restarts}")
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        self.max_restarts = int(max_restarts)
        self.window_s = float(window_s)
        self._clock = clock
        self._acquired: list = []

    def _prune(self, now: float) -> None:
        cut = now - self.window_s
        self._acquired = [t for t in self._acquired if t > cut]

    def remaining(self) -> int:
        """Restarts still allowed in the current window. Non-mutating:
        counts live entries against a snapshot of the list."""
        cut = self._clock() - self.window_s
        live = sum(1 for t in list(self._acquired) if t > cut)
        return self.max_restarts - live

    def try_acquire(self) -> bool:
        """Consume one restart if the window has room; False means the
        budget is exhausted and the caller must escalate."""
        now = self._clock()
        self._prune(now)
        if len(self._acquired) >= self.max_restarts:
            return False
        self._acquired.append(now)
        return True


def retryable(policy: Optional[RetryPolicy] = None,
              op: Optional[str] = None):
    """Decorator form of ``retry_call``. Retry options are bound at
    decoration time; the wrapped function's own kwargs pass through
    untouched (a caller kwarg named ``rng``/``sleep``/``policy`` must
    reach the function, not the retry machinery)."""
    def deco(fn):
        def wrapped(*args, **kwargs):
            return retry_call(lambda: fn(*args, **kwargs),
                              policy=policy, op=op or fn.__name__)
        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        wrapped.__doc__ = fn.__doc__
        return wrapped
    return deco
