"""Divergence watchdog: turn a slow-motion training collapse into a
catchable event.

The sentinel (resilience/sentinel.py) makes a single poisoned batch
harmless, but two failure modes survive it: a PERSISTENT source of bad
steps (every batch NaNs — e.g. an LR so hot the loss overflows each
step, so skipping leaves params frozen forever), and a numeric
divergence that stays finite while the loss runs away. The watchdog is
a TrainingListener that checks both at its own cadence and raises
``DivergenceError`` — which ``util.recovery.FaultTolerantTrainer``
catches to roll back to the last GOOD checkpoint (optionally with LR
backoff) instead of burning the remaining epochs on a corpse.

Checks (every ``check_every`` iterations — the listener's one sanctioned
sync point, same contract as a score printer):

- **consecutive bad steps** >= ``max_consecutive_bad`` (from the
  sentinel accounting, flushed here);
- **loss blowup**: current score exceeds
  ``median + blowup_factor * max(|median|, abs_floor)`` over the last
  ``window`` cadence-sampled finite scores (needs at least
  ``min_history`` samples, so a noisy warmup can't false-trigger). The
  additive-around-the-median form keeps the check live for objectives
  whose loss is near zero or negative (log-likelihoods), where a naive
  ``factor * median`` ratio would be inert.
"""

from __future__ import annotations

import logging
from collections import deque
from statistics import median
from typing import Optional

from deeplearning4j_tpu.monitoring import flightrecorder
from deeplearning4j_tpu.monitoring.events import emit as emit_event
from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.resilience import sentinel

log = logging.getLogger(__name__)

__all__ = ["DivergenceError", "DivergenceWatchdog"]


class DivergenceError(RuntimeError):
    """Training diverged (persistent bad steps or loss blowup).

    ``limit`` (blowup trigger only) is the score threshold that fired —
    the rollback path uses it to skip checkpoints whose recorded score
    was already past it (saved mid-divergence)."""

    def __init__(self, message: str, iteration: Optional[int] = None,
                 limit: Optional[float] = None):
        super().__init__(message)
        self.iteration = iteration
        self.limit = limit


class DivergenceWatchdog(TrainingListener):
    def __init__(self, max_consecutive_bad: int = 5,
                 blowup_factor: float = 25.0, window: int = 20,
                 min_history: int = 5, check_every: int = 10,
                 abs_floor: float = 0.1):
        if max_consecutive_bad < 1:
            raise ValueError("max_consecutive_bad must be >= 1")
        if blowup_factor <= 1.0:
            raise ValueError("blowup_factor must be > 1")
        if abs_floor <= 0.0:
            raise ValueError("abs_floor must be > 0")
        self.max_consecutive_bad = max_consecutive_bad
        self.blowup_factor = blowup_factor
        self.abs_floor = abs_floor
        self.min_history = max(2, min_history)
        self.check_every = max(1, check_every)
        self._scores = deque(maxlen=max(self.min_history, window))
        self._ticks = 0

    def reset(self) -> None:
        """Forget history (called after a rollback restored good state)."""
        self._scores.clear()
        self._ticks = 0

    # -- durable state (checkpointed via util/checkpoint extras) --------
    def durable_state(self) -> dict:
        """The trailing score window + cadence phase, so a
        preemption-exact resume re-arms the blowup check with the SAME
        history an uninterrupted run would hold (an empty window after
        resume would silently disable the check for min_history
        cadences)."""
        return {"scores": [float(s) for s in self._scores],
                "ticks": int(self._ticks)}

    def restore_durable_state(self, state: dict) -> None:
        self._scores = deque((float(s) for s in state.get("scores", ())),
                             maxlen=self._scores.maxlen)
        self._ticks = int(state.get("ticks", 0))

    def iteration_done(self, model, iteration: int, score) -> None:
        self._ticks += 1
        if self._ticks % self.check_every:
            return
        # cadence sync #1: materialize pending sentinel flags
        acct = sentinel.flush_accounting(model)
        if acct is not None and \
                acct.consecutive_bad >= self.max_consecutive_bad:
            err = DivergenceError(
                f"{acct.consecutive_bad} consecutive non-finite train "
                f"steps (threshold {self.max_consecutive_bad}) — the "
                f"input or the step size is persistently poisoned",
                iteration=iteration)
            self._flight(err, iteration, kind="bad_steps")
            raise err
        # cadence sync #2: the score (lazy device scalar until floated)
        s = float(score)
        if s != s or s in (float("inf"), float("-inf")):
            return  # non-finite scores are the sentinel counter's job
        if len(self._scores) >= self.min_history:
            base = median(self._scores)
            # additive around the median: stays live for near-zero and
            # NEGATIVE losses, matches factor*median for positive ones
            limit = base + self.blowup_factor * max(abs(base),
                                                    self.abs_floor)
            if s > limit:
                err = DivergenceError(
                    f"loss {s:.4g} blew past the divergence limit "
                    f"{limit:.4g} (trailing-window median {base:.4g}, "
                    f"factor {self.blowup_factor:g})",
                    iteration=iteration, limit=limit)
                self._flight(err, iteration, kind="blowup",
                             score=s, limit=limit)
                raise err
        self._scores.append(s)

    def _flight(self, err: DivergenceError, iteration: Optional[int],
                **extra) -> None:
        """Timeline event + post-mortem artifact at the raise site —
        FaultTolerantTrainer may roll the process state back seconds
        later, and the diverging trajectory (score window + recent ops
        events) is exactly what the rollback erases."""
        emit_event("resilience", "divergence", iteration=iteration,
                   error=str(err), **extra)
        flightrecorder.maybe_dump(
            "divergence", error=err,
            extra={"iteration": iteration,
                   "score_window": [float(s) for s in self._scores],
                   **extra})
