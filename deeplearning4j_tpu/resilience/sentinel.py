"""On-device non-finite sentinel: detect and skip poisoned updates.

A single NaN/Inf batch (bad input, overflowing LR, a dropout-free fp16
edge) poisons params forever — and on the PR 3 fused path it silently
corrupts ALL K optimizer steps of a scan dispatch. The sentinel folds
detection INTO the jitted train step so the skip costs no host sync:

    ok  = isfinite(loss) & all(isfinite(g) for g in grad leaves)
    p'  = where(ok, p - update, p)        # the zeroed-update math
    u'  = where(ok, u_next, u)            # optimizer state too
    s'  = where(ok, s_next, s)            # and BN running stats

``where(ok, new, old)`` with a traced scalar ``ok`` is a device select —
when the step is bad the update is exactly zero (params bit-equal to the
pre-step values), when it is good the math is bit-equal to the
sentinel-free step. Grads are tested BEFORE gradient normalization, so a
clipping rule can't mask an Inf by rescaling it.

The flag is returned from the step as a raw device bool (on the scan
path: a [K] vector, one per fused step) and accumulated host-side by
``SentinelAccounting`` WITHOUT synchronizing: the fit loops append the
raw flag per logical step; at cadence (every ``flush_every`` steps) the
accounting settles only flags whose computation already finished
(non-blocking ``is_ready``), and everything else waits for the
sanctioned sync points — watchdog cadence, checkpoint save, end of
fit. Steady state stays sync-free (the tests/test_input_pipeline.py
guards hold with the sentinel enabled).

Metrics (global registry, labeled by model class):

- ``dl4jtpu_bad_steps_total``: steps whose loss or raw grads were
  non-finite.
- ``dl4jtpu_skipped_updates_total``: bad steps whose update was zeroed
  (== bad steps under the default "skip" policy; 0 under "record").
- ``dl4jtpu_consecutive_bad_steps`` (gauge): current run length — the
  divergence watchdog's primary signal.

Policies (``set_default_nonfinite_policy`` / ``net.nonfinite_policy``):
``"skip"`` (default) zeroes bad updates, ``"record"`` counts but applies
them (debugging: watch a divergence happen), ``"off"`` removes the
sentinel from the trace entirely (the pre-resilience step, kept for
benchmarks that want the raw step unchanged).
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.monitoring.metrics import (
    MetricsRegistry, global_registry)

BAD_STEPS = "dl4jtpu_bad_steps_total"
SKIPPED_UPDATES = "dl4jtpu_skipped_updates_total"
CONSECUTIVE_BAD = "dl4jtpu_consecutive_bad_steps"

POLICIES = ("skip", "record", "off")

_DEFAULT_POLICY = "skip"

_MISSING = object()

__all__ = ["BAD_STEPS", "CONSECUTIVE_BAD", "POLICIES", "SKIPPED_UPDATES",
           "SentinelAccounting", "accounting_for", "effective_policy",
           "flush_accounting", "record_step_flags",
           "set_default_nonfinite_policy", "tree_finite", "where_finite"]


def set_default_nonfinite_policy(policy: str) -> str:
    """Set the process-wide default policy; returns the previous value."""
    global _DEFAULT_POLICY
    if policy not in POLICIES:
        raise ValueError(f"policy must be one of {POLICIES}, got {policy!r}")
    prev, _DEFAULT_POLICY = _DEFAULT_POLICY, policy
    return prev


def effective_policy(model=None) -> str:
    """Policy for a model: its ``nonfinite_policy`` attribute if set,
    else the process default."""
    p = getattr(model, "nonfinite_policy", None)
    if p is None:
        return _DEFAULT_POLICY
    if p not in POLICIES:
        raise ValueError(f"nonfinite_policy must be one of {POLICIES}, "
                         f"got {p!r}")
    return p


# ---------------------------------------------------------------------------
# traced helpers (called inside jitted train steps)
# ---------------------------------------------------------------------------
def tree_finite(loss, grads):
    """Traced: scalar bool — loss and EVERY raw-gradient leaf finite."""
    import jax
    import jax.numpy as jnp

    ok = jnp.all(jnp.isfinite(loss))
    for leaf in jax.tree_util.tree_leaves(grads):
        ok = ok & jnp.all(jnp.isfinite(leaf))
    return ok


def where_finite(ok, new, old):
    """Traced: ``new`` where ``ok`` else ``old``, merged structurally.

    Leaves ``new`` carries that ``old`` lacks (an RNN h/c carry
    materializing on the first tbptt chunk) — or whose shape changed
    (a growing cache) — have no pre-step value to fall back to; on a
    bad step they fall back to ZEROS, the absent-carry semantic the
    layers use, so a poisoned first chunk cannot smuggle a NaN carry
    past the skip."""
    import jax.numpy as jnp

    def merge(n, o):
        if isinstance(n, dict):
            o_map = o if isinstance(o, dict) else {}
            return {k: merge(v, o_map.get(k, _MISSING))
                    for k, v in n.items()}
        if n is None:
            return n
        if o is _MISSING or o is None or \
                getattr(n, "shape", None) != getattr(o, "shape", None):
            return jnp.where(ok, n, jnp.zeros_like(n))
        return jnp.where(ok, n, o)

    return merge(new, old)


# ---------------------------------------------------------------------------
# host-side lazy accounting
# ---------------------------------------------------------------------------
class SentinelAccounting:
    """Accumulates raw device flags; materializes at cadence.

    ``record`` appends without syncing. The cadence flush settles only
    flags whose device computation has ALREADY finished (``is_ready``,
    non-blocking), so the fit thread never waits on an in-flight step
    for accounting — the sanctioned sync points (watchdog cadence,
    checkpoint save, end of fit) force-flush the remainder. Host
    counters and registry metrics update on flush. The fit loop thread
    owns record/flush ordering; the lock only guards against concurrent
    observers (watchdog listeners, scrapes)."""

    def __init__(self, model_name: str, flush_every: int = 25,
                 registry: Optional[MetricsRegistry] = None):
        self.model_name = model_name
        self.flush_every = max(1, int(flush_every))
        self._registry = registry
        self._lock = threading.Lock()
        self._pending: List[Tuple[Any, bool]] = []
        self.total_steps = 0
        self.bad_steps = 0
        self.skipped_updates = 0
        self.consecutive_bad = 0

    def record(self, flags: Any, skipped: bool) -> None:
        """Queue one step's (or one fused group's [K]) raw ok-flag(s);
        at `flush_every` pending entries, settle the ones whose device
        computation already FINISHED (non-blocking — the fit thread
        never waits on an in-flight step for accounting)."""
        with self._lock:
            self._pending.append((flags, skipped))
            due = len(self._pending) >= self.flush_every
        if due:
            self.flush(force=False)

    @staticmethod
    def _is_ready(flags: Any) -> bool:
        ready = getattr(flags, "is_ready", None)
        if ready is None:
            return True  # host value (numpy/bool): nothing to wait on
        try:
            return bool(ready())
        except Exception:  # noqa: BLE001 — readiness probe must not raise
            return True

    def flush(self, force: bool = True) -> None:
        """Materialize pending flags and publish. ``force=False`` (the
        fit-loop cadence path) settles only the longest prefix whose
        arrays are already ready — zero added steady-state stalls; the
        sanctioned sync points (watchdog cadence, checkpoint save, end
        of fit) use the default force=True. A hard cap of
        4*flush_every pending entries backpressures regardless."""
        with self._lock:
            if force or len(self._pending) >= 4 * self.flush_every:
                pending, self._pending = self._pending, []
            else:
                n = 0
                while n < len(self._pending) and \
                        self._is_ready(self._pending[n][0]):
                    n += 1
                pending, self._pending = (self._pending[:n],
                                          self._pending[n:])
        if not pending:
            return
        new_bad = new_skipped = new_total = 0
        consecutive = None
        for flags, skipped in pending:
            oks = np.asarray(flags).ravel()
            for ok in oks:
                new_total += 1
                if bool(ok):
                    consecutive = 0
                else:
                    new_bad += 1
                    consecutive = (self.consecutive_bad
                                   if consecutive is None else consecutive) + 1
                    if skipped:
                        new_skipped += 1
        with self._lock:
            self.total_steps += new_total
            self.bad_steps += new_bad
            self.skipped_updates += new_skipped
            if consecutive is not None:
                self.consecutive_bad = consecutive
        r = self._registry or global_registry()
        if new_bad:
            r.counter(BAD_STEPS,
                      "Train steps with a non-finite loss or gradient",
                      ("model",)).inc(new_bad, model=self.model_name)
        if new_skipped:
            r.counter(SKIPPED_UPDATES,
                      "Non-finite updates zeroed by the sentinel",
                      ("model",)).inc(new_skipped, model=self.model_name)
        r.gauge(CONSECUTIVE_BAD,
                "Current run of consecutive non-finite train steps",
                ("model",)).set(self.consecutive_bad, model=self.model_name)

    def reset_window(self) -> None:
        """Drop pending flags and the consecutive-bad run (rollback just
        restored a good state); lifetime totals stay."""
        with self._lock:
            self._pending = []
            self.consecutive_bad = 0


def accounting_for(model) -> SentinelAccounting:
    """Get-or-create the model's accounting (stored on the model)."""
    acct = getattr(model, "_sentinel_accounting", None)
    if acct is None:
        acct = SentinelAccounting(type(model).__name__)
        model._sentinel_accounting = acct
    return acct


def record_step_flags(model, flags: Any, policy: str) -> None:
    """Fit-loop hook: queue a step's raw flag(s) — NO host sync here."""
    if policy == "off" or flags is None:
        return
    accounting_for(model).record(flags, skipped=(policy == "skip"))


def guard_updates(ok, policy: str, *pairs):
    """Traced: apply the skip-policy select to ``(new, old)`` pairs —
    the ONE place the zeroed-update triple lives, so every step builder
    (per-batch, scan, phase, averaging) shares identical skip
    semantics. Under "record" the new values pass through unguarded."""
    if policy != "skip":
        return tuple(n for n, _ in pairs)
    return tuple(where_finite(ok, n, o) for n, o in pairs)


def apply_step(model, policy: str, step, *args):
    """Call a jitted train step and absorb its sentinel flag: under
    policy "off" the step's legacy tuple passes through unchanged;
    otherwise the trailing raw ok-flag(s) are recorded (lazily, no
    sync) and the remaining tuple returned — so every fit-loop call
    site unpacks ONE shape regardless of policy."""
    out = step(*args)
    if policy == "off":
        return out
    record_step_flags(model, out[-1], policy)
    return out[:-1]


def flush_accounting(model) -> Optional[SentinelAccounting]:
    """Flush if the model has accounting (end-of-fit / watchdog cadence)."""
    acct = getattr(model, "_sentinel_accounting", None)
    if acct is not None:
        acct.flush()
    return acct
