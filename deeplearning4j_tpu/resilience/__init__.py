"""Resilience layer: staying up when the path is unhappy.

PR 3 made the hot path fast (fused scan dispatch, sync-free steady
state) — and therefore brittle: one NaN batch silently corrupts K fused
optimizer steps, a prefetch-worker exception kills the epoch, and a
serving request has no deadline. This subsystem is the counterweight
(SURVEY §5: the reference has essentially no fault tolerance beyond
Spark task retry):

- ``sentinel``: on-device non-finite detection folded into the train
  step — a bad step applies a where-zeroed update with zero host syncs,
  surfaced lazily as ``dl4jtpu_bad_steps_total`` /
  ``dl4jtpu_skipped_updates_total``.
- ``watchdog``: divergence detection (K consecutive bad steps, loss
  blowup vs a trailing window) that triggers
  ``util.recovery.FaultTolerantTrainer`` rollback to the last GOOD
  checkpoint with optional LR backoff.
- ``retry``: bounded exponential backoff with jitter — the one
  sanctioned retry loop shape (tpulint rule ``unbounded-retry`` flags
  hand-rolled unbounded ones).
- ``chaos``: deterministic fault injectors over any DataSetIterator for
  proving the above actually recovers (tests/test_resilience.py).
- ``durable``: crash-consistent state IO — atomic tmp→fsync→rename
  writes, checksummed checkpoint dirs, the bounded async checkpoint
  writer, the SIGTERM PreemptionGuard + dispatch-boundary hook, and the
  multi-process shard/COMMIT protocol (util/checkpoint.py is built on
  it; tests/test_durable.py is its chaos suite).
- ``elastic``: the membership layer over the durable substrate — a
  filesystem lease ledger with monotonically numbered membership
  generations, failure detection (lease expiry = death AND hang), and
  the split-brain-safe successor-generation agreement
  ``parallel.ElasticTrainer`` re-meshes from. A lost host becomes a
  chaos event the fleet absorbs: survivors tear down jax.distributed,
  re-initialize the new world, and resume bit-exactly from
  ``latest_committed_step``.

See ARCHITECTURE.md "Resilience", "Durable state" and "Elastic
membership".
"""

from deeplearning4j_tpu.resilience.durable import (
    AsyncCheckpointWriter, CheckpointError, CommitTimeoutError,
    CorruptCheckpointError,
    PreemptionExit, PreemptionGuard)
from deeplearning4j_tpu.resilience.elastic import (
    GenerationDead, GenerationRecord, LeaseLedger, MembershipChanged)
from deeplearning4j_tpu.resilience.retry import (
    RestartBudget, RetryPolicy, retry_call)
from deeplearning4j_tpu.resilience.sentinel import (
    effective_policy, set_default_nonfinite_policy)

__all__ = ["AsyncCheckpointWriter", "CheckpointError",
           "CommitTimeoutError",
           "CorruptCheckpointError", "GenerationDead", "GenerationRecord",
           "LeaseLedger", "MembershipChanged",
           "PreemptionExit", "PreemptionGuard",
           "RestartBudget", "RetryPolicy", "retry_call",
           "effective_policy",
           "set_default_nonfinite_policy"]
