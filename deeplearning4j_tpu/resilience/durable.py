"""Durable state: crash-consistent writes, async checkpointing, and a
distributed commit protocol.

SURVEY's L1 reference surface (ModelSerializer + checkpoint-based
recovery) assumed a process that dies politely. Production training does
not: a preemption SIGKILLs mid-save, a disk fills halfway through a
rename, a worker dies between writing its shard and the job committing
the step. This module is the format/IO layer the checkpoint stack
(util/checkpoint.py, util/recovery.py) is built on, with four
guarantees:

1. **Atomicity** — every file and every checkpoint directory is written
   tmp → flush → fsync → ``os.replace`` (+ parent-directory fsync), so a
   kill at ANY byte offset leaves either the old state or the new state,
   never a torn hybrid. A checkpoint step directory only ever EXISTS
   committed: its contents are assembled under a tmp name and renamed
   into place in one atomic step.
2. **Integrity** — a MANIFEST.json inside each checkpoint dir carries a
   format version and a per-leaf crc32 checksum (over dtype, shape, and
   raw bytes), so a reader can prove the bytes it is about to load are
   the bytes that were written — and fall back to an older intact step
   instead of crashing on (or silently loading) corruption.
3. **Asynchrony** — ``AsyncCheckpointWriter`` runs serialize+write on a
   bounded background thread with backpressure, so the fit loop blocks
   only for the device→host snapshot. Errors never vanish: they surface
   on ``health()``, ``last_error``, and the failure counter.
4. **Distributed commit** — in multi-process training each worker writes
   its own shard dir; rank 0 publishes an atomic COMMIT marker only
   after every shard is present and verified. Resume selects the highest
   *fully committed* step, so a worker dying between shard write and
   commit can never produce a half-checkpoint that restores.

``PreemptionGuard`` + ``dispatch_boundary`` turn SIGTERM into an orderly
exit: finish the in-flight dispatch, emergency-save a consistent
snapshot (params/opt-state/RNG/data-pipeline cursor all aligned at the
step boundary), and raise ``PreemptionExit``.

Telemetry (global metrics registry):

- ``dl4jtpu_checkpoint_save_seconds`` (histogram, labeled mode=sync|async)
- ``dl4jtpu_checkpoint_bytes_total`` (counter)
- ``dl4jtpu_checkpoint_inflight`` (gauge): queued + in-progress async saves
- ``dl4jtpu_checkpoint_failures_total`` (counter)
- ``dl4jtpu_checkpoint_corrupt_skipped_total`` (counter): integrity
  fallbacks taken at restore/rollback time.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import queue
import shutil
import signal
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.monitoring.events import emit as emit_event
from deeplearning4j_tpu.monitoring.metrics import (
    MetricsRegistry, global_registry)

log = logging.getLogger(__name__)

FORMAT_VERSION = 1
MANIFEST_NAME = "MANIFEST.json"
DATA_NAME = "data.npz"
COMMIT_NAME = "COMMIT.json"
_TMP_PREFIX = ".tmp-"

CKPT_SAVE_SECONDS = "dl4jtpu_checkpoint_save_seconds"
CKPT_BYTES = "dl4jtpu_checkpoint_bytes_total"
CKPT_INFLIGHT = "dl4jtpu_checkpoint_inflight"
CKPT_FAILURES = "dl4jtpu_checkpoint_failures_total"
CKPT_CORRUPT_SKIPPED = "dl4jtpu_checkpoint_corrupt_skipped_total"
CKPT_COMMIT_TIMEOUTS = "dl4jtpu_checkpoint_commit_timeouts_total"

__all__ = [
    "AsyncCheckpointWriter", "CKPT_BYTES", "CKPT_COMMIT_TIMEOUTS",
    "CKPT_CORRUPT_SKIPPED",
    "CKPT_FAILURES", "CKPT_INFLIGHT", "CKPT_SAVE_SECONDS",
    "CheckpointError", "CommitTimeoutError", "CorruptCheckpointError",
    "FORMAT_VERSION",
    "PreemptionExit", "PreemptionGuard", "atomic_replace_path",
    "atomic_write_bytes",
    "atomic_write_json", "atomic_write_text", "commit_marker_path",
    "capture_cursor_pass", "consume_restored_cursor",
    "declare_checkpoint_series",
    "dispatch_boundary",
    "latest_committed_step", "list_committed_steps", "publish_commit",
    "read_commit", "read_state_dir", "shard_dir_name", "verify_state_dir",
    "write_checkpoint_dir", "write_shard",
]


class CheckpointError(RuntimeError):
    """A checkpoint could not be written (IO failure, timeout on the
    distributed barrier, ...)."""


class CorruptCheckpointError(CheckpointError):
    """On-disk checkpoint bytes failed integrity verification (missing
    manifest, version mismatch, checksum mismatch, torn file)."""


class CommitTimeoutError(CheckpointError):
    """The distributed commit barrier timed out: shards never arrived
    (rank 0, ``missing_ranks`` known) or the COMMIT marker never
    appeared (non-zero ranks — the committer itself may have died).

    Typed, with the step and the missing ranks attached, so an elastic
    detector can tell "the committer/a shard-writer died" (cross-check
    the lease ledger, declare the generation dead) from "the disk is
    slow" (retry with a longer timeout) instead of pattern-matching a
    message string. Counted in
    ``dl4jtpu_checkpoint_commit_timeouts_total``."""

    def __init__(self, message: str, step: int,
                 missing_ranks: Optional[Sequence[int]] = None,
                 timeout: Optional[float] = None):
        super().__init__(message)
        self.step = int(step)
        self.missing_ranks = None if missing_ranks is None \
            else sorted(int(r) for r in missing_ranks)
        self.timeout = timeout


def declare_checkpoint_series(registry: Optional[MetricsRegistry] = None):
    """Get-or-create the checkpoint telemetry series so a scrape taken
    before the first save already shows the schema. Returns
    (save_seconds, bytes_total, inflight, failures, corrupt_skipped,
    commit_timeouts)."""
    r = registry or global_registry()
    return (
        r.histogram(CKPT_SAVE_SECONDS,
                    "Wall time of one checkpoint serialize+write",
                    ("mode",)),
        r.counter(CKPT_BYTES, "Bytes committed to checkpoint storage"),
        r.gauge(CKPT_INFLIGHT,
                "Async checkpoint saves queued or in progress"),
        r.counter(CKPT_FAILURES, "Checkpoint saves that raised"),
        r.counter(CKPT_CORRUPT_SKIPPED,
                  "Corrupt/torn checkpoints skipped at restore time"),
        r.counter(CKPT_COMMIT_TIMEOUTS,
                  "Distributed commit barriers that timed out"),
    )


# ---------------------------------------------------------------------------
# crash-injection seam (tests only): called with a label at each durability
# milestone of a checkpoint-dir write, so the chaos suite can prove that a
# kill at ANY point leaves the previously-committed state intact.
# ---------------------------------------------------------------------------
_crash_hook: Optional[Callable[[str], None]] = None


def _maybe_crash(point: str) -> None:
    if _crash_hook is not None:
        _crash_hook(point)


# ---------------------------------------------------------------------------
# atomic file primitives
# ---------------------------------------------------------------------------
def _fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so a just-renamed entry survives power loss.
    Best-effort: not every filesystem supports opening directories."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """tmp-in-same-dir → write → flush → fsync → os.replace → dir fsync.
    A reader never observes a partial file; a crash leaves either the
    old content or the new content."""
    path = os.path.abspath(path)
    d = os.path.dirname(path)
    tmp = os.path.join(d, f"{_TMP_PREFIX}{os.path.basename(path)}."
                          f"{os.getpid()}.{threading.get_ident()}")
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(d)


def atomic_write_text(path: str, text: str) -> None:
    atomic_write_bytes(path, text.encode("utf-8"))


def atomic_write_json(path: str, obj: Any) -> None:
    atomic_write_bytes(path, (json.dumps(obj, sort_keys=True) + "\n")
                       .encode("utf-8"))


@contextlib.contextmanager
def atomic_replace_path(path: str):
    """For writers that need a real filesystem path (zipfile, np.save):
    yields a tmp path in the same directory; on clean exit the tmp file
    is fsynced and atomically renamed onto ``path`` (+ dir fsync), on
    error it is removed. Either the old file or the complete new file
    survives a crash — never a torn hybrid."""
    path = os.path.abspath(path)
    d = os.path.dirname(path)
    tmp = os.path.join(d, f"{_TMP_PREFIX}{os.path.basename(path)}."
                          f"{os.getpid()}.{threading.get_ident()}")
    try:
        yield tmp
        fd = os.open(tmp, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(d)


# ---------------------------------------------------------------------------
# tree <-> flat arrays (nested-dict state trees; leaves = arrays/scalars)
# ---------------------------------------------------------------------------
def _flatten_tree(tree: Any, prefix: str = "") -> Tuple[Any, Dict[str, Any]]:
    """Returns (skeleton, leaves). The skeleton mirrors the dict nesting
    with leaf positions replaced by ``{"__leaf__": key}`` (or
    ``{"__none__": true}`` for None), JSON-serializable; ``leaves`` maps
    key -> array-like."""
    if isinstance(tree, dict):
        skel, leaves = {}, {}
        for k in sorted(tree):
            s, l = _flatten_tree(tree[k], f"{prefix}{k}/")
            skel[k] = s
            leaves.update(l)
        return skel, leaves
    if tree is None:
        return {"__none__": True}, {}
    key = prefix.rstrip("/")
    return {"__leaf__": key}, {key: tree}


def _unflatten_tree(skel: Any, leaves: Dict[str, np.ndarray]) -> Any:
    if isinstance(skel, dict):
        if skel.get("__none__"):
            return None
        if "__leaf__" in skel:
            return leaves[skel["__leaf__"]]
        return {k: _unflatten_tree(v, leaves) for k, v in skel.items()}
    raise CorruptCheckpointError(f"malformed tree skeleton node: {skel!r}")


def _leaf_checksum(arr: np.ndarray) -> str:
    """crc32 over dtype + shape + raw bytes (C-order)."""
    a = np.ascontiguousarray(arr)
    h = zlib.crc32(str(a.dtype).encode())
    h = zlib.crc32(str(a.shape).encode(), h)
    h = zlib.crc32(a.tobytes(), h)
    return f"{h:08x}"


def snapshot_tree(tree: Any) -> Any:
    """Materialize a (possibly device-resident) state tree as host numpy
    arrays — the ONLY part of a save the fit loop must block for."""
    def conv(x):
        if isinstance(x, dict):
            return {k: conv(v) for k, v in x.items()}
        if x is None:
            return None
        return np.asarray(x)
    return conv(tree)


# ---------------------------------------------------------------------------
# checkpoint directory format
# ---------------------------------------------------------------------------
def _npz_key(key: str) -> str:
    # np.savez forbids "/" only on some paths; keys are restored from the
    # manifest skeleton anyway, so a reversible escape is all we need
    return key.replace("/", "|")


def write_checkpoint_dir(final_dir: str, tree: Any,
                         extras: Optional[Dict[str, Any]] = None,
                         registry: Optional[MetricsRegistry] = None) -> int:
    """Write one committed checkpoint directory (data.npz +
    MANIFEST.json with per-leaf checksums) atomically: everything is
    assembled under a tmp sibling and renamed into place, so
    ``final_dir`` only ever exists fully written. Returns bytes written.

    If ``final_dir`` already exists (same-step re-save; the step=None
    "latest" path rewrites one dir every save) it is replaced via
    aside-rename: the old dir is renamed aside, the new one renamed in,
    then the aside copy removed. A kill between the two renames leaves
    BOTH copies on disk — the aside survivor under a
    ``step_N.replaced.<pid>.<tid>`` name that listings skip but sweep
    never deletes, recoverable by renaming it back; an in-process
    failure rolls the aside copy back automatically.
    """
    final_dir = os.path.abspath(final_dir)
    parent = os.path.dirname(final_dir)
    os.makedirs(parent, exist_ok=True)
    tmp_dir = os.path.join(parent, f"{_TMP_PREFIX}{os.path.basename(final_dir)}"
                                   f".{os.getpid()}.{threading.get_ident()}")
    host = snapshot_tree(tree)
    skel, leaves = _flatten_tree(host)
    aside = None
    try:
        os.makedirs(tmp_dir)
        data_path = os.path.join(tmp_dir, DATA_NAME)
        # savez straight into the file handle: no BytesIO staging, so a
        # save's peak host memory is the snapshot itself, not 3x it
        with open(data_path, "wb") as f:
            np.savez(f, **{_npz_key(k): np.asarray(v)
                           for k, v in leaves.items()})
            f.flush()
            os.fsync(f.fileno())
        data_bytes = os.path.getsize(data_path)
        _maybe_crash("data-written")
        manifest = {
            "format_version": FORMAT_VERSION,
            "tree": skel,
            "leaves": {k: {"checksum": _leaf_checksum(np.asarray(v)),
                           "dtype": str(np.asarray(v).dtype),
                           "shape": list(np.asarray(v).shape)}
                       for k, v in leaves.items()},
            "extras": extras or {},
        }
        mbytes = (json.dumps(manifest, sort_keys=True) + "\n").encode()
        with open(os.path.join(tmp_dir, MANIFEST_NAME), "wb") as f:
            f.write(mbytes)
            f.flush()
            os.fsync(f.fileno())
        _fsync_dir(tmp_dir)
        _maybe_crash("pre-rename")
        if os.path.exists(final_dir):
            # replacing an existing step (same-step re-save, the
            # step=None "latest" path): move the old copy ASIDE first —
            # a crash between the two renames leaves both copies on
            # disk (the aside name is deliberately NOT tmp-prefixed so
            # sweep_tmp_dirs never reclaims it; an operator can rename
            # it back), instead of the old rmtree-then-rename shape
            # whose crash window destroyed the only copy
            aside = os.path.join(parent,
                                 f"{os.path.basename(final_dir)}.replaced."
                                 f"{os.getpid()}.{threading.get_ident()}")
            os.rename(final_dir, aside)
            _maybe_crash("mid-replace")
            os.replace(tmp_dir, final_dir)
            shutil.rmtree(aside, ignore_errors=True)
            aside = None
        else:
            os.replace(tmp_dir, final_dir)
        _fsync_dir(parent)
        _maybe_crash("post-rename")
    except BaseException:
        shutil.rmtree(tmp_dir, ignore_errors=True)
        # an in-process failure mid-replace: put the old copy back
        if aside is not None and os.path.exists(aside) and \
                not os.path.exists(final_dir):
            try:
                os.rename(aside, final_dir)
            except OSError:
                pass
        raise
    n = data_bytes + len(mbytes)
    declare_checkpoint_series(registry)[1].inc(n)
    return n


def read_manifest(step_dir: str) -> Dict[str, Any]:
    mpath = os.path.join(step_dir, MANIFEST_NAME)
    try:
        with open(mpath, "r", encoding="utf-8") as f:
            m = json.load(f)
    except (OSError, ValueError) as e:
        raise CorruptCheckpointError(
            f"unreadable manifest at {mpath}: {e}") from e
    v = m.get("format_version")
    if v != FORMAT_VERSION:
        raise CorruptCheckpointError(
            f"{mpath}: format version {v!r} != supported {FORMAT_VERSION}")
    return m


def _read_leaves(step_dir: str, manifest: Dict[str, Any],
                 verify: bool = True) -> Dict[str, np.ndarray]:
    dpath = os.path.join(step_dir, DATA_NAME)
    try:
        with np.load(dpath, allow_pickle=False) as z:
            raw = {k: z[_npz_key(k)] for k in manifest["leaves"]}
    except Exception as e:  # noqa: BLE001 — torn bytes raise anything
        # (BadZipFile, EOFError, zlib.error, KeyError, ...): ANY failure
        # to produce the manifest's leaves is corruption by definition
        raise CorruptCheckpointError(f"torn/unreadable {dpath}: {e}") from e
    if verify:
        for k, meta in manifest["leaves"].items():
            got = _leaf_checksum(raw[k])
            if got != meta["checksum"]:
                raise CorruptCheckpointError(
                    f"{dpath}: checksum mismatch on leaf {k!r} "
                    f"({got} != recorded {meta['checksum']})")
    return raw


def read_state_dir(step_dir: str, verify: bool = True
                   ) -> Tuple[Any, Dict[str, Any]]:
    """Load (tree, manifest) from a committed checkpoint dir, verifying
    every leaf checksum by default. Raises CorruptCheckpointError on any
    integrity failure — callers decide whether to fall back."""
    manifest = read_manifest(step_dir)
    leaves = _read_leaves(step_dir, manifest, verify=verify)
    return _unflatten_tree(manifest["tree"], leaves), manifest


def verify_state_dir(step_dir: str) -> bool:
    """True iff the dir is a committed checkpoint whose bytes all pass
    their checksums."""
    try:
        manifest = read_manifest(step_dir)
        _read_leaves(step_dir, manifest, verify=True)
        return True
    except CorruptCheckpointError:
        return False


def sweep_tmp_dirs(path: str) -> int:
    """Remove leftover tmp artifacts from crashed writers under a
    checkpoint root (safe anytime: committed state never lives under a
    tmp name). Returns the number removed."""
    if not os.path.isdir(path):
        return 0
    n = 0
    for name in os.listdir(path):
        if name.startswith(_TMP_PREFIX):
            full = os.path.join(path, name)
            if os.path.isdir(full):
                shutil.rmtree(full, ignore_errors=True)
            else:
                try:
                    os.unlink(full)
                except OSError:
                    continue
            n += 1
    return n


# ---------------------------------------------------------------------------
# async writer
# ---------------------------------------------------------------------------
class AsyncCheckpointWriter:
    """Bounded background writer: the fit loop hands over an
    already-snapshotted (host-resident) state and returns immediately;
    serialize + write + rename + prune run here, strictly in submission
    order (single worker). ``submit`` BLOCKS when ``max_pending`` jobs
    are already queued — backpressure, so a slow disk throttles saving
    instead of accumulating unbounded host snapshots.

    Failures do not kill training: the job's exception lands on
    ``last_error``, increments ``dl4jtpu_checkpoint_failures_total``,
    flips ``health()["healthy"]`` until a later save succeeds, and — by
    construction (write-to-tmp) — leaves every previously committed
    checkpoint untouched.
    """

    def __init__(self, max_pending: int = 2,
                 registry: Optional[MetricsRegistry] = None):
        if max_pending < 1:
            raise ValueError(f"max_pending must be >= 1, got {max_pending}")
        self._registry = registry
        self._q: "queue.Queue" = queue.Queue(maxsize=max_pending)
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._outstanding = 0  # submitted, not yet finished (under _lock)
        self._idle = threading.Event()
        self._idle.set()
        self.last_error: Optional[BaseException] = None
        self.failures = 0
        self.completed = 0
        (self._save_hist, _, self._inflight, self._fail_counter,
         *_rest) = declare_checkpoint_series(registry)

    # -- worker ----------------------------------------------------------
    def _ensure_thread(self) -> None:
        with self._lock:
            t = self._thread
            if t is None or not t.is_alive():
                t = threading.Thread(target=self._run, daemon=True,
                                     name="checkpoint-writer")
                self._thread = t
                t.start()

    def _run(self) -> None:
        while True:
            fn, label, is_save = self._q.get()
            t0 = time.perf_counter()
            try:
                fn()
                with self._lock:
                    self.completed += 1
                    if is_save:
                        # a clean SAVE clears the unhealthy latch; a
                        # successful housekeeping job (prune) says
                        # nothing about whether saves are landing
                        self.last_error = None
                if is_save:
                    self._save_hist.observe(time.perf_counter() - t0,
                                            mode="async")
            except BaseException as e:  # noqa: BLE001 — surfaced, never lost
                with self._lock:
                    self.failures += 1
                    self.last_error = e
                self._fail_counter.inc()
                log.warning("async checkpoint save %s failed: %r", label, e)
            finally:
                self._inflight.dec()
                with self._lock:
                    self._outstanding -= 1
                    if self._outstanding == 0:
                        self._idle.set()

    # -- public ----------------------------------------------------------
    def submit(self, fn: Callable[[], None], label: str = "save",
               is_save: bool = True) -> None:
        """Queue a write job (runs in submission order). Blocks when the
        queue is full — the sanctioned backpressure point. Housekeeping
        jobs (``is_save=False``: pruning) neither clear the unhealthy
        latch nor count toward save telemetry."""
        self._ensure_thread()
        with self._lock:
            self._outstanding += 1
            self._idle.clear()
        self._inflight.inc()
        try:
            self._q.put((fn, label, is_save))
        except BaseException:
            self._inflight.dec()
            with self._lock:
                self._outstanding -= 1
                if self._outstanding == 0:
                    self._idle.set()
            raise

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Wait until every submitted job has finished. Returns False on
        timeout."""
        t = self._thread
        if t is None or not t.is_alive():
            return True
        return self._idle.wait(timeout)

    def close(self, timeout: Optional[float] = None) -> None:
        """Drain pending jobs. The worker THREAD is deliberately left
        parked on its queue: it is a daemon (dies with the process,
        costs nothing idle), the writer stays usable for the next fit
        (close runs from listener close(), which fires at the end of
        EVERY fit), and stopping a possibly-wedged worker to start a
        fresh one later would put two workers on one queue — breaking
        the FIFO save→prune ordering CheckpointListener's
        never-evict-the-predecessor guarantee rests on."""
        self.flush(timeout)

    def health(self) -> Dict[str, Any]:
        with self._lock:
            pending = self._q.qsize()
            return {
                "healthy": self.last_error is None,
                "pending": pending,
                "completed": self.completed,
                "failures": self.failures,
                "last_error": None if self.last_error is None
                else repr(self.last_error),
            }


# ---------------------------------------------------------------------------
# preemption guard + the fit-loop dispatch boundary
# ---------------------------------------------------------------------------
class PreemptionExit(SystemExit):
    """Raised at the first dispatch boundary after a preemption signal,
    AFTER the emergency checkpoint is durable. SystemExit subclass: the
    fit loops' finally blocks run (listeners closed), and an unhandled
    propagation exits the process with ``code``."""

    def __init__(self, step: int, checkpoint_dir: str, code: int = 0):
        super().__init__(code)
        self.step = step
        self.checkpoint_dir = checkpoint_dir


class PreemptionGuard:
    """SIGTERM → finish the in-flight dispatch → emergency-save → exit.

    The signal handler only sets a flag; the fit loops poll it at every
    dispatch boundary (``dispatch_boundary``), where params/opt-state/
    RNG/iterator cursor are mutually consistent, and perform a
    synchronous save there — so the emergency checkpoint resumes
    bit-identical to an uninterrupted run.

        guard = PreemptionGuard(net, ckpt_dir)        # installs SIGTERM
        try:
            net.fit(it, epochs=10)
        except PreemptionExit:
            ...                                        # saved; exit soon

    ``trigger()`` arms the guard programmatically (tests / external
    preemption notices). ``writer`` (an AsyncCheckpointWriter) is
    flushed before the emergency save so in-flight periodic saves land
    first.
    """

    def __init__(self, net, checkpoint_dir: str,
                 signals: Tuple[int, ...] = (signal.SIGTERM,),
                 writer: Optional[AsyncCheckpointWriter] = None,
                 exit_code: int = 0, install: bool = True):
        self.net = net
        self.checkpoint_dir = checkpoint_dir
        self.signals = tuple(signals)
        self.writer = writer
        self.exit_code = exit_code
        self.triggered = False
        self.saved_step: Optional[int] = None
        self._prev: Dict[int, Any] = {}
        self._installed = False
        net._preemption_guard = self
        if install:
            self.install()

    # -- signal plumbing -------------------------------------------------
    def _handler(self, signum, frame):  # noqa: ARG002 — signal signature
        self.triggered = True

    def install(self) -> "PreemptionGuard":
        try:
            for s in self.signals:
                self._prev[s] = signal.signal(s, self._handler)
            self._installed = True
        except ValueError:
            # not the main thread: signals can't be installed here —
            # trigger() remains the arming path
            log.warning("PreemptionGuard: not on main thread, signal "
                        "handler not installed (use trigger())")
        return self

    def uninstall(self) -> None:
        if self._installed:
            for s, prev in self._prev.items():
                try:
                    signal.signal(s, prev)
                except (ValueError, OSError):
                    pass
            self._installed = False
        if getattr(self.net, "_preemption_guard", None) is self:
            self.net._preemption_guard = None

    def __enter__(self) -> "PreemptionGuard":
        return self

    def __exit__(self, *exc) -> None:
        self.uninstall()

    def trigger(self) -> None:
        """Arm the guard as if the signal had arrived."""
        self.triggered = True

    # -- the boundary action ---------------------------------------------
    def handle(self, net) -> None:
        """Called at a dispatch boundary. No-op unless triggered; else
        emergency-save (sync, durable before return) and raise
        PreemptionExit."""
        if not self.triggered:
            return
        if self.writer is not None:
            self.writer.flush()
        from deeplearning4j_tpu.util.checkpoint import (
            save_checkpoint, verify_checkpoint)
        # also drain any listener writers on this net: their cadence
        # save for THIS boundary may still be queued
        for lst in getattr(net, "listeners", ()):
            w = getattr(lst, "writer", None)
            if isinstance(w, AsyncCheckpointWriter):
                w.flush()
        step = int(net.iteration_count)
        if not verify_checkpoint(self.checkpoint_dir, step):
            # skip when a cadence save at this very boundary already
            # committed the step: re-saving an EXISTING step routes
            # through write_checkpoint_dir's delete-then-rename
            # replacement window — the one place a follow-up SIGKILL
            # could destroy a just-committed checkpoint
            save_checkpoint(net, self.checkpoint_dir, step=step)
        self.saved_step = step
        emit_event("resilience", "preemption", step=step,
                   checkpoint_dir=self.checkpoint_dir)
        log.warning("preemption: emergency checkpoint at step %d (%s); "
                    "exiting", step, self.checkpoint_dir)
        raise PreemptionExit(step, self.checkpoint_dir, self.exit_code)


def dispatch_boundary(net) -> None:
    """The fit loops' per-dispatch consistency point: called after a
    train dispatch fully retired (params advanced, iteration_count
    incremented, listeners fired). Two jobs:

    1. run deferred cadence saves — listeners exposing
       ``on_dispatch_boundary`` (CheckpointListener) save HERE, where
       params, counters, RNG stream, and the data-pipeline cursor are
       mutually consistent (on the fused-scan path, iteration_done
       fires mid-group when params already hold the post-group state —
       saving there would stitch a torn logical snapshot);
    2. honor a pending preemption (PreemptionGuard.handle).
    """
    for lst in getattr(net, "listeners", ()):
        hook = getattr(lst, "on_dispatch_boundary", None)
        if hook is not None:
            hook(net)
    guard = getattr(net, "_preemption_guard", None)
    if guard is not None:
        guard.handle(net)


def consume_restored_cursor(net, it) -> int:
    """Apply a restored checkpoint's data-pipeline cursor to the fit
    iterator (called once, at fit setup). Fast-forwards ``it`` to the
    batch AFTER the last dispatched one — pass index restored too, so
    shuffle orders line up with an uninterrupted run — and re-arms the
    net's dispatch counters. Returns the restored mid-epoch position
    (0 = epoch-boundary resume).

    Iterators without the ``state()/restore_state()`` protocol degrade
    to the classic approximate continuation (the interrupted epoch's
    consumed batches are replayed); a warning says so."""
    cur = getattr(net, "_restored_pipeline_state", None)
    net._restored_pipeline_state = None
    net._canon_in_epoch = None
    net._dispatched_in_epoch = 0
    if not cur:
        return 0
    pos = int(cur.get("pos", 0) or 0)
    epoch = int(cur.get("epoch", 0) or 0)
    restore = getattr(it, "restore_state", None)
    if restore is None:
        if pos:
            log.warning(
                "restored checkpoint carries a mid-epoch data cursor "
                "(epoch %d, batch %d) but %s has no restore_state(): "
                "resuming with the interrupted epoch replayed "
                "(approximate continuation, not bit-exact)",
                epoch, pos, type(it).__name__)
        return 0
    try:
        restore({"epoch": epoch, "pos": pos})
    except NotImplementedError as e:
        if pos:
            log.warning("data-pipeline cursor restore unsupported (%s); "
                        "approximate continuation", e)
        return 0
    net._dispatched_in_epoch = pos
    canon = cur.get("canon")
    net._canon_in_epoch = None if canon is None else int(canon)
    return pos


def capture_cursor_pass(net, it) -> None:
    """Pin the pass index the upcoming epoch will run (fit-loop setup /
    epoch rollover). Read from the iterator's own cursor when it has one
    — its counter drives the shuffle seed — and held fixed on the net
    for the whole pass, so a save at ANY dispatch boundary (including
    the trailing-group flush, which fires after the generator already
    rolled the iterator's cursor to the next pass) stamps a pass index
    consistent with ``_dispatched_in_epoch``."""
    pass_idx = net.epoch_count
    state_fn = getattr(it, "state", None)
    if state_fn is not None:
        try:
            pass_idx = int(state_fn()["epoch"])
        except Exception:  # noqa: BLE001 — cursor capture is best-effort
            pass
    net._cursor_pass = int(pass_idx)


# ---------------------------------------------------------------------------
# distributed commit protocol
# ---------------------------------------------------------------------------
def shard_dir_name(rank: int) -> str:
    return f"shard_{int(rank)}"


def commit_marker_path(step_dir: str) -> str:
    return os.path.join(step_dir, COMMIT_NAME)


def write_shard(step_dir: str, rank: int, tree: Any,
                extras: Optional[Dict[str, Any]] = None) -> str:
    """Write this worker's shard of a distributed checkpoint (atomic,
    checksummed). The shard dir's existence doubles as the worker's
    arrival marker for the commit barrier."""
    sdir = os.path.join(os.path.abspath(step_dir), shard_dir_name(rank))
    write_checkpoint_dir(sdir, tree, extras=extras)
    return sdir


def publish_commit(step_dir: str, step: int, world: int,
                   timeout: float = 60.0, poll: float = 0.05) -> None:
    """Rank 0's half of the barrier: wait for every shard to be present
    AND intact, then atomically publish the COMMIT marker. A worker that
    died between shard write and barrier → timeout →
    ``CommitTimeoutError`` carrying the step + missing ranks (the
    elastic detector's "who died mid-commit" signal), and the step stays
    uncommitted (resume ignores it)."""
    step_dir = os.path.abspath(step_dir)
    deadline = time.monotonic() + timeout
    missing = list(range(world))
    while missing:
        missing = [r for r in missing
                   if not os.path.exists(os.path.join(
                       step_dir, shard_dir_name(r), MANIFEST_NAME))]
        if not missing:
            break
        if time.monotonic() > deadline:
            declare_checkpoint_series()[5].inc()
            raise CommitTimeoutError(
                f"distributed checkpoint step {step}: shards {missing} "
                f"never arrived within {timeout}s — step NOT committed",
                step=step, missing_ranks=missing, timeout=timeout)
        time.sleep(poll)
    bad = [r for r in range(world)
           if not verify_state_dir(os.path.join(step_dir,
                                                shard_dir_name(r)))]
    if bad:
        raise CheckpointError(
            f"distributed checkpoint step {step}: shards {bad} failed "
            f"integrity verification — step NOT committed")
    atomic_write_json(commit_marker_path(step_dir), {
        "format_version": FORMAT_VERSION, "step": int(step),
        "world": int(world), "shards": [shard_dir_name(r)
                                        for r in range(world)],
    })
    emit_event("resilience", "checkpoint_commit", step=int(step),
               world=int(world))


def wait_commit(step_dir: str, timeout: float = 60.0,
                poll: float = 0.05,
                world: Optional[int] = None) -> Dict[str, Any]:
    """Non-zero ranks' half of the barrier: block until rank 0 published
    the COMMIT marker. Timeout raises ``CommitTimeoutError`` — the
    committer (or a shard-writer it was waiting on) may be dead, which
    an elastic caller distinguishes from slow disk by cross-checking the
    lease ledger. With ``world`` the error names the ranks whose shards
    are absent on disk (rank 0 among the missing ⇒ the committer itself
    never finished its shard)."""
    step_dir = os.path.abspath(step_dir)
    deadline = time.monotonic() + timeout
    while True:
        c = read_commit(step_dir)
        if c is not None:
            return c
        if time.monotonic() > deadline:
            tail = os.path.basename(step_dir).rsplit("_", 1)[-1]
            step = int(tail) if tail.isdigit() else -1
            missing = None
            if world is not None:
                missing = [r for r in range(int(world))
                           if not os.path.exists(os.path.join(
                               step_dir, shard_dir_name(r),
                               MANIFEST_NAME))]
            declare_checkpoint_series()[5].inc()
            raise CommitTimeoutError(
                f"no COMMIT marker appeared under {step_dir} within "
                f"{timeout}s" + (f" (shards absent: {missing})"
                                 if missing else ""),
                step=step, missing_ranks=missing, timeout=timeout)
        time.sleep(poll)


def read_commit(step_dir: str) -> Optional[Dict[str, Any]]:
    try:
        with open(commit_marker_path(step_dir), "r", encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def list_committed_steps(path: str) -> List[int]:
    """Steps under a distributed checkpoint root whose COMMIT marker is
    present and readable, ascending. Uncommitted step dirs (a worker
    died pre-commit) are invisible here by construction."""
    if not os.path.isdir(path):
        return []
    steps = []
    for name in os.listdir(path):
        if not name.startswith("step_"):
            continue
        try:
            s = int(name.split("_", 1)[1])
        except ValueError:
            continue
        if read_commit(os.path.join(path, name)) is not None:
            steps.append(s)
    return sorted(steps)


def latest_committed_step(path: str) -> Optional[int]:
    steps = list_committed_steps(path)
    return steps[-1] if steps else None
