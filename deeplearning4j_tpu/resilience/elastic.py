"""Elastic membership: lease ledger, membership generations, failure
detection, and the re-mesh plan.

PR 7 made multi-host training *durable*: the distributed commit protocol
guarantees that resume only ever sees fully committed steps, whatever a
worker's death tore mid-save. This module is the scheduler half ROADMAP
item 2 names: *detect* the lost host, agree on the new membership, and
hand every survivor a plan it can re-mesh from — without asking the very
coordination service whose death IS the failure mode. jax.distributed's
gRPC coordination service reacts to a lost peer by terminating every
other task (client.h: "Terminating process because the JAX distributed
service detected fatal errors"), so the membership layer must live
OUTSIDE it. It lives on the filesystem instead, on the same atomic
tmp→fsync→rename primitives util/checkpoint.py's crash-consistent
format is built on (the shared dir is the one dependency every host
already has — it is where the checkpoints live):

- **Leases** (``lease_<rank>.json``): every host heartbeats a lease
  under its GLOBAL rank — a stable identity that survives re-meshes,
  unlike the per-generation contiguous process id jax needs. A lease
  older than ``ttl`` is expired; an expired member is a lost host. A
  live lease from a NON-member is a join request (a preempted host came
  back). Both are just membership deltas — scale-in and scale-out
  through one code path.
- **Generations** (``gen_<n>.json``): a monotonically numbered
  membership record: the sorted global-rank member list (list index =
  the member's contiguous jax process id) and the coordinator address
  for ``jax.distributed.initialize``. Generation files are immutable and
  EXCLUSIVE-created (``os.link``, which fails on an existing name,
  unlike the overwriting ``os.replace``): when two survivors race to
  publish generation N+1, exactly one record wins and both adopt it —
  the split-brain tiebreak. Publication order is staggered by survivor
  rank so the LOWEST surviving rank publishes first by construction;
  the link-race is the safety net, not the mechanism.
- **Detection** (``detect_membership``): lost = members whose lease
  expired; joined = live non-members. A hung collective (peer SIGKILLed
  mid-allreduce simply never arrives — the dispatch blocks forever) and
  a peer that died politely both surface the same way: its lease stops.
  The trainer wraps every allreduce dispatch in a watchdog timeout and
  maps BOTH a timeout and a collective error onto a ledger check —
  ``GenerationDead`` only if the ledger confirms a lost member,
  otherwise the error was real and re-raises.

``parallel/elastic.py``'s ``ElasticTrainer`` drives the full loop:
heartbeat → detect → tear down jax.distributed → adopt generation N+1 →
re-initialize → re-mesh → resume every survivor bit-exactly from
``latest_committed_step``.

Telemetry (global registry; declared by ``declare_elastic_series``):

- ``dl4jtpu_elastic_generation`` (gauge): current membership generation
- ``dl4jtpu_elastic_members`` (gauge): live member count
- ``dl4jtpu_elastic_remesh_total`` (counter, labeled cause=scale_in|
  scale_out): completed re-meshes
- ``dl4jtpu_elastic_lost_hosts_total`` (counter): members declared dead
- ``dl4jtpu_elastic_remesh_seconds`` (histogram): detection→resumed
  latency of each re-mesh
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import socket
import threading
import time
from typing import Dict, List, Optional, Sequence

from deeplearning4j_tpu.monitoring.metrics import (
    MetricsRegistry, global_registry)

log = logging.getLogger(__name__)


def _write_json_atomic_nosync(path: str, obj) -> None:
    """tmp → os.replace, NO fsync: a reader never sees a torn file (the
    rename is atomic), but the write is not crash-durable — exactly
    right for a lease, whose only meaning is "I was alive when I wrote
    this". A lease lost to power failure describes a host that is dead
    anyway, while an fsync per heartbeat (~seconds on overlay/network
    filesystems) would starve the beat interval the ttl depends on.
    Generation records — which must never be un-published — go through
    the fsynced exclusive-create in ``publish_generation`` instead."""
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(obj, f, sort_keys=True)
    os.replace(tmp, path)

ELASTIC_GENERATION = "dl4jtpu_elastic_generation"
ELASTIC_MEMBERS = "dl4jtpu_elastic_members"
ELASTIC_REMESH = "dl4jtpu_elastic_remesh_total"
ELASTIC_LOST_HOSTS = "dl4jtpu_elastic_lost_hosts_total"
ELASTIC_REMESH_SECONDS = "dl4jtpu_elastic_remesh_seconds"

_GEN_PREFIX = "gen_"
_LEASE_PREFIX = "lease_"

__all__ = [
    "ELASTIC_GENERATION", "ELASTIC_LOST_HOSTS", "ELASTIC_MEMBERS",
    "ELASTIC_REMESH", "ELASTIC_REMESH_SECONDS", "GenerationDead",
    "GenerationRecord", "LeaseLedger", "MembershipChanged",
    "MembershipDelta",
    "agree_next_generation", "declare_elastic_series", "detect_membership",
    "free_port", "plan_next_generation",
]


def declare_elastic_series(registry: Optional[MetricsRegistry] = None):
    """Get-or-create the elastic telemetry series (schema visible before
    the first re-mesh). Returns (generation, members, remesh_total,
    lost_hosts_total, remesh_seconds)."""
    r = registry or global_registry()
    remesh = r.counter(ELASTIC_REMESH, "Completed re-meshes", ("cause",))
    lost = r.counter(ELASTIC_LOST_HOSTS, "Members declared dead")
    for cause in ("scale_in", "scale_out"):
        # touch both children so the series renders (at 0) on a fleet
        # that has never re-meshed; same for the unlabeled counter
        remesh.labels(cause=cause)
    lost.inc(0)
    return (
        r.gauge(ELASTIC_GENERATION, "Current membership generation"),
        r.gauge(ELASTIC_MEMBERS, "Members in the current generation"),
        remesh,
        lost,
        r.histogram(ELASTIC_REMESH_SECONDS,
                    "Re-mesh latency, detection to resumed"),
    )


class MembershipChanged(RuntimeError):
    """The membership this generation was built on no longer matches the
    ledger: tear down the current world and re-mesh. Scale-in (a lost
    member — see ``GenerationDead``) and scale-out (a join lease from a
    returning host) raise through this one signal so both travel the
    same re-mesh path."""

    def __init__(self, generation: int, reason: str,
                 lost: Sequence[int] = (), joined: Sequence[int] = ()):
        self.generation = int(generation)
        self.lost_ranks = sorted(int(r) for r in lost)
        self.joined_ranks = sorted(int(r) for r in joined)
        self.reason = reason
        parts = []
        if self.lost_ranks:
            parts.append(f"lost ranks {self.lost_ranks}")
        if self.joined_ranks:
            parts.append(f"join requests from ranks {self.joined_ranks}")
        super().__init__(
            f"generation {generation} membership changed: "
            f"{', '.join(parts) or 'no delta'} ({reason})")

    @property
    def cause(self) -> str:
        """Metrics label: losses dominate (a simultaneous loss+join
        re-mesh is a scale-in event that happens to admit someone)."""
        return "scale_in" if self.lost_ranks else "scale_out"


class GenerationDead(MembershipChanged):
    """The current membership generation lost at least one member: every
    survivor must tear down the old world and re-mesh."""

    def __init__(self, generation: int, lost_ranks: Sequence[int],
                 reason: str, joined: Sequence[int] = ()):
        super().__init__(generation, reason, lost=lost_ranks,
                         joined=joined)


@dataclasses.dataclass(frozen=True)
class GenerationRecord:
    """One immutable membership generation. ``members`` is the sorted
    list of GLOBAL ranks; a member's index in the list is its contiguous
    jax process id for this generation (so process 0 — the coordinator —
    is always the lowest surviving global rank)."""

    generation: int
    members: Sequence[int]
    coordinator: str  # "host:port" for jax.distributed.initialize
    published_by: int  # global rank of the publisher

    @property
    def world(self) -> int:
        return len(self.members)

    def contains(self, rank: int) -> bool:
        return int(rank) in self.members

    def process_id_of(self, rank: int) -> int:
        """Contiguous process id of a global rank in this generation."""
        try:
            return self.members.index(int(rank))
        except ValueError:
            raise KeyError(f"rank {rank} is not a member of "
                           f"generation {self.generation}") from None

    def to_dict(self) -> Dict:
        return {"generation": int(self.generation),
                "members": [int(m) for m in self.members],
                "coordinator": self.coordinator,
                "published_by": int(self.published_by)}

    @classmethod
    def from_dict(cls, d: Dict) -> "GenerationRecord":
        members = sorted(int(m) for m in d["members"])
        if not members:
            raise ValueError("generation record with no members")
        return cls(generation=int(d["generation"]), members=members,
                   coordinator=str(d.get("coordinator", "")),
                   published_by=int(d.get("published_by", members[0])))


@dataclasses.dataclass(frozen=True)
class MembershipDelta:
    """What the ledger says changed relative to a generation record."""

    lost: Sequence[int]  # members whose lease expired
    joined: Sequence[int]  # live non-members (join requests)

    def __bool__(self) -> bool:
        return bool(self.lost or self.joined)


def free_port(host: str = "127.0.0.1") -> int:
    """A currently-free TCP port on ``host`` for the next generation's
    coordinator. Best-effort (bind+close race), which is fine: a publish
    that loses the port race fails initialize and triggers the next
    generation bump rather than corrupting anything."""
    s = socket.socket()
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


class LeaseLedger:
    """Filesystem lease ledger + generation log for ONE host (identified
    by its stable global rank) under a shared directory.

    Heartbeats are atomic whole-file writes (tmp→rename — atomic for
    readers, deliberately NOT fsynced: see ``_write_json_atomic_nosync``),
    so a reader never sees a torn lease; the liveness clock is the
    reader's wall clock against the writer's stamped ``ts`` (same-host
    tests are exact; multi-host deployments need the usual loosely-synced
    clocks every lease system assumes, with ``ttl`` >> clock skew).

    ``stall()`` freezes the background heartbeat WITHOUT killing
    anything — the hung-host simulation ``LeaseStallInjector`` drives
    (detection-without-death must be testable separately from death).
    """

    def __init__(self, root: str, rank: int, ttl: float = 5.0,
                 interval: Optional[float] = None,
                 advertise_host: str = "127.0.0.1",
                 role: Optional[str] = None,
                 extra: Optional[Dict] = None):
        if ttl <= 0:
            raise ValueError(f"ttl must be > 0, got {ttl}")
        self.root = os.path.abspath(root)
        self.rank = int(rank)
        #: optional membership role stamped into every beat ("train"
        #: ranks vs "serving" replicas can share one ledger directory;
        #: ``live_ranks(role=...)`` filters to one population so a
        #: serving fleet never counts a training rank as a replica)
        self.role = role
        #: optional JSON-able advertisement merged into every beat —
        #: how a cross-process fleet agent publishes its pid (and any
        #: other discovery payload) to an out-of-process router that
        #: can only observe the shared filesystem
        self.extra = dict(extra) if extra else None
        self.ttl = float(ttl)
        self.interval = float(interval) if interval is not None \
            else self.ttl / 3.0
        self.advertise_host = advertise_host
        self.beat = 0
        self.generation: Optional[int] = None  # stamped into each beat
        self._stalled = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        os.makedirs(self.root, exist_ok=True)

    # -- paths -----------------------------------------------------------
    def _lease_path(self, rank: int) -> str:
        return os.path.join(self.root, f"{_LEASE_PREFIX}{int(rank)}.json")

    def _gen_path(self, generation: int) -> str:
        return os.path.join(self.root, f"{_GEN_PREFIX}{int(generation)}.json")

    # -- heartbeats ------------------------------------------------------
    def heartbeat(self, generation: Optional[int] = None) -> None:
        """Write one lease beat (no-op while stalled). ``generation``
        updates the sticky per-ledger generation stamp the background
        thread keeps beating with — after a re-mesh one
        ``heartbeat(new_gen)`` re-stamps the stream."""
        if generation is not None:
            self.generation = int(generation)
        if self._stalled.is_set():
            return
        self.beat += 1
        lease = {
            "rank": self.rank, "beat": self.beat, "ts": time.time(),
            "generation": self.generation,
            "host": self.advertise_host,
        }
        if self.role is not None:
            lease["role"] = self.role
        if self.extra:
            lease.update(self.extra)
        _write_json_atomic_nosync(self._lease_path(self.rank), lease)

    def start(self, generation: Optional[int] = None) -> "LeaseLedger":
        """Heartbeat immediately, then keep beating from a daemon thread
        every ``interval`` seconds until ``stop()``."""
        self.heartbeat(generation)
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def _run():
            while not self._stop.wait(self.interval):
                try:
                    self.heartbeat()
                except OSError as e:  # pragma: no cover - disk trouble
                    log.warning("lease heartbeat failed: %s", e)

        self._thread = threading.Thread(
            target=_run, daemon=True, name=f"lease-rank{self.rank}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2 * self.interval + 1)
        self._thread = None

    def stall(self) -> None:
        """Freeze heartbeats (the process stays alive — the hung-host
        signal: peers see this rank's lease expire)."""
        self._stalled.set()

    def resume(self) -> None:
        self._stalled.clear()

    @property
    def stalled(self) -> bool:
        return self._stalled.is_set()

    def withdraw(self) -> None:
        """Remove this rank's lease (orderly leave: peers see the rank
        gone at the next check instead of waiting out the ttl)."""
        try:
            os.unlink(self._lease_path(self.rank))
        except OSError:
            pass

    # -- reads -----------------------------------------------------------
    def read_lease(self, rank: int) -> Optional[Dict]:
        try:
            with open(self._lease_path(rank), "r", encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def read_leases(self) -> Dict[int, Dict]:
        out: Dict[int, Dict] = {}
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in names:
            if not (name.startswith(_LEASE_PREFIX) and
                    name.endswith(".json")):
                continue
            try:
                rank = int(name[len(_LEASE_PREFIX):-len(".json")])
            except ValueError:
                continue
            lease = self.read_lease(rank)
            if lease is not None:
                out[rank] = lease
        return out

    def lease_age(self, rank: int,
                  now: Optional[float] = None) -> Optional[float]:
        lease = self.read_lease(rank)
        if lease is None:
            return None
        return (time.time() if now is None else now) - float(lease["ts"])

    def live_ranks(self, now: Optional[float] = None,
                   role: Optional[str] = None) -> List[int]:
        """Ranks whose lease is younger than ttl (a missing lease is
        simply not live). ``role`` restricts to leases stamped with
        that role (pre-role leases carry none and match only the
        unfiltered read) — the serving-replica filter."""
        now = time.time() if now is None else now
        return sorted(r for r, lease in self.read_leases().items()
                      if now - float(lease["ts"]) <= self.ttl
                      and (role is None or lease.get("role") == role))

    def live_leases(self, now: Optional[float] = None,
                    role: Optional[str] = None) -> Dict[int, Dict]:
        """Live ranks WITH their latest beat payloads (role-filtered
        like ``live_ranks``) — the discovery read an out-of-process
        fleet router uses: the beat carries each agent's advertised
        ``extra`` payload (pid etc.) alongside liveness."""
        now = time.time() if now is None else now
        return {r: lease for r, lease in self.read_leases().items()
                if now - float(lease["ts"]) <= self.ttl
                and (role is None or lease.get("role") == role)}

    # -- generations -----------------------------------------------------
    def read_generation(self, generation: int) -> Optional[GenerationRecord]:
        try:
            with open(self._gen_path(generation), "r",
                      encoding="utf-8") as f:
                return GenerationRecord.from_dict(json.load(f))
        except (OSError, ValueError, KeyError):
            return None

    def latest_generation(self) -> Optional[GenerationRecord]:
        try:
            names = os.listdir(self.root)
        except OSError:
            return None
        best = -1
        for name in names:
            if not (name.startswith(_GEN_PREFIX) and
                    name.endswith(".json")):
                continue
            try:
                n = int(name[len(_GEN_PREFIX):-len(".json")])
            except ValueError:
                continue
            best = max(best, n)
        return None if best < 0 else self.read_generation(best)

    def publish_generation(self, record: GenerationRecord
                           ) -> GenerationRecord:
        """EXCLUSIVE-create the generation file; if a record for that
        generation already exists (a concurrent publisher won the race),
        the existing record is returned — callers always converge on the
        single on-disk truth."""
        final = self._gen_path(record.generation)
        tmp = f"{final}.tmp.{os.getpid()}.{threading.get_ident()}"
        payload = (json.dumps(record.to_dict(), sort_keys=True) + "\n"
                   ).encode("utf-8")
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        try:
            os.link(tmp, final)  # atomic, FAILS if final exists
        except FileExistsError:
            existing = self.read_generation(record.generation)
            if existing is not None:
                log.info("generation %d already published by rank %d; "
                         "adopting", existing.generation,
                         existing.published_by)
                return existing
            return record  # torn loser file: our payload is the record
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return record

    def wait_for_generation(self, min_generation: int, timeout: float,
                            poll: float = 0.05) -> GenerationRecord:
        """Block until a generation >= ``min_generation`` is published
        (non-publishers during a re-mesh; joiners waiting for admission)."""
        deadline = time.monotonic() + timeout
        while True:
            rec = self.latest_generation()
            if rec is not None and rec.generation >= min_generation:
                return rec
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no generation >= {min_generation} published under "
                    f"{self.root} within {timeout}s")
            time.sleep(poll)


# ---------------------------------------------------------------------------
# detection + planning
# ---------------------------------------------------------------------------
def detect_membership(ledger: LeaseLedger,
                      record: GenerationRecord) -> MembershipDelta:
    """Compare the lease ledger against a generation record.

    ``lost``: members whose lease expired (or vanished) — the failure
    signal, whether the host died (SIGKILL mid-allreduce), hung (frozen
    heartbeat thread), or left politely (withdrawn lease). ``joined``:
    live leases from non-members — rejoin requests. The caller's own
    rank is never in ``lost`` (a host that can run this code is alive
    even if its own heartbeat thread wedged)."""
    live = set(ledger.live_ranks())
    lost = [r for r in record.members
            if r not in live and r != ledger.rank]
    joined = [r for r in sorted(live) if not record.contains(r)]
    return MembershipDelta(lost=lost, joined=joined)


def plan_next_generation(prev: GenerationRecord, live: Sequence[int],
                         publisher: int,
                         coordinator: Optional[str] = None,
                         advertise_host: str = "127.0.0.1"
                         ) -> GenerationRecord:
    """The re-mesh plan: generation N+1 over the live rank set, with
    contiguous process ids re-assigned by sorted global rank and the
    coordinator on the lowest survivor (= new process 0). Scale-in and
    scale-out are the same computation — ``live`` is just whatever the
    ledger says is alive now."""
    members = sorted(set(int(r) for r in live))
    if not members:
        raise ValueError("cannot plan a generation with no live members")
    if coordinator is None:
        coordinator = f"{advertise_host}:{free_port(advertise_host)}"
    return GenerationRecord(generation=prev.generation + 1,
                            members=members, coordinator=coordinator,
                            published_by=int(publisher))


def agree_next_generation(ledger: LeaseLedger, prev: GenerationRecord,
                          stagger: float = 0.25,
                          timeout: float = 30.0) -> GenerationRecord:
    """Converge every survivor of a dead generation on ONE successor
    record.

    Only surviving MEMBERS of ``prev`` may publish (a joiner waits to be
    admitted — it has no standing to re-plan a membership it never
    belonged to). Each survivor waits ``stagger`` seconds per survivor
    ranked below it, polling for an existing record the whole time, so
    the lowest surviving rank publishes first by construction and higher
    ranks only step up if everything below them died between detection
    and publish. Two survivors racing through the stagger anyway is
    settled by ``publish_generation``'s exclusive create: one record
    wins, both return it.

    The fresh ``live_ranks`` read here (not the one that declared the
    generation dead) is what folds scale-in and scale-out into one step:
    a join lease that appeared during detection rides into the same
    successor generation."""
    if not prev.contains(ledger.rank):
        return ledger.wait_for_generation(prev.generation + 1,
                                          timeout=timeout)
    live = set(ledger.live_ranks())
    live.add(ledger.rank)  # this code running IS liveness
    survivors = sorted(r for r in live if prev.contains(r))
    my_turn = time.monotonic() + stagger * survivors.index(ledger.rank)
    while time.monotonic() < my_turn:
        rec = ledger.read_generation(prev.generation + 1)
        if rec is not None:
            return rec
        time.sleep(min(0.05, stagger))
    rec = ledger.read_generation(prev.generation + 1)
    if rec is not None:
        return rec
    # the new process 0 is the lowest live rank: the coordinator must
    # live on ITS host (from its lease). The port is picked by the
    # publisher — correct when publisher and lowest rank share a host
    # (always true on the test fleet); multi-host deployments should
    # derive a deterministic per-generation port instead.
    lease = ledger.read_lease(min(live)) or {}
    plan = plan_next_generation(
        prev, sorted(live), ledger.rank,
        advertise_host=lease.get("host") or ledger.advertise_host)
    # single attempt: publish_generation always returns the on-disk
    # truth — our plan if the exclusive create won, the racing winner's
    # record otherwise
    return ledger.publish_generation(plan)
