from deeplearning4j_tpu.modelimport.keras import KerasModelImport  # noqa: F401
