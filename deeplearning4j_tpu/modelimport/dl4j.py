"""DL4J checkpoint (zip) importer/exporter.

TPU-native reader for the reference's ModelSerializer format
(deeplearning4j-nn/src/main/java/org/deeplearning4j/util/ModelSerializer.java:90-137):
a zip holding

- ``configuration.json`` — MultiLayerConfiguration / ComputationGraphConfiguration
  Jackson JSON (MultiLayerConfiguration.java:120 toJson)
- ``coefficients.bin`` — ONE flat parameter row-vector written with
  ``Nd4j.write`` (shape-info int buffer + data buffer, big-endian)
- ``updaterState.bin`` — flat updater state view (optional)

The flat view ordering is the part "where parity dies" (SURVEY §7 hard parts);
per-layer layouts are taken from the reference param initializers:

- Dense/Output/Embedding (DefaultParamInitializer.java): ``W`` reshaped
  'f'-order [nIn, nOut], then ``b`` [nOut].
- AutoEncoder/RBM (PretrainParamInitializer.java:42-63): W, b, then visible
  bias ``vb`` [nIn].
- Convolution (ConvolutionParamInitializer.java:118-121): bias FIRST [nOut],
  then ``W`` reshaped 'c'-order [nOut, nIn, kH, kW] — identical to our OIHW.
- BatchNormalization (BatchNormalizationParamInitializer.java:88-110):
  gamma, beta (unless lockGammaBeta), then running mean, running var.
- LSTM (LSTMParamInitializer.java:119-150): ``W`` 'f' [nIn, 4nL], ``RW`` 'f'
  [nL, 4nL], ``b`` [4nL]. DL4J column blocks are "IFOG" = (i, f, o, g) where
  the "i" block takes the LAYER activation (tanh — i.e. it is the candidate)
  and the "g" block takes the GATE activation (sigmoid — i.e. it is the real
  input gate); see LSTMHelpers.java:214-305. Our (i, f, c, o) convention is
  the standard/Keras labelling of the same math, so the block permutation is
  ours[i] = theirs[g], ours[f] = theirs[f], ours[c] = theirs[i],
  ours[o] = theirs[o].
- GravesLSTM (GravesLSTMParamInitializer.java:147-150): as LSTM but RW is
  'f' [nL, 4nL+3]; the 3 extra columns are peepholes wFF, wOO, wGG
  (LSTMHelpers.java:101-121). wFF multiplies c_prev into the forget gate,
  wOO multiplies c_new into the output gate, wGG multiplies c_prev into
  DL4J's "g" block = our input gate — so our P rows (pI, pF, pO) =
  (wGG, wFF, wOO).
- GravesBidirectionalLSTM (GravesBidirectionalLSTMParamInitializer.java:139+):
  WF, RWF, bF, WB, RWB, bB sequential, each as GravesLSTM.

ComputationGraph flat params follow the vertex topological order
(ComputationGraph.java:418-479).

The ``Nd4j.write`` wire format (ND4J 0.9.x BaseDataBuffer.write): for each of
the shape-info buffer and the data buffer — java writeUTF(allocation mode
name), writeInt(length), writeUTF(data type name), then the values
big-endian. Shape info for rank r is ints [r, shape…, stride…, offset,
elementWiseStride, order-char].

Updater-state import (``updaterState.bin``): the reference lays the flat
updater view out per UpdaterBlock (BaseMultiLayerUpdater.java:72-121) —
contiguous (layer, variable) pairs with identical updater configuration
combine into one block, and each block's view is [state0 | state1] where
each state tensor spans the block's params in view order (the ND4J
GradientUpdater contract, e.g. AdamUpdater: m = first half, v = second
half; applied per block at UpdaterBlock.java:104-142). BatchNormalization's
global mean/var use a NoOp updater (BatchNormalization.java:144-151,
stateSize 0) and therefore BREAK blocks. Both directions are implemented
here (`updater_state_from_flat` / `updater_state_to_flat`), mapping into
our updater pytrees ({"m": tree, "v": tree, "t": n} etc.) with the same
per-variable reshapes/gate permutations as the params themselves.
"""

from __future__ import annotations

import io
import json
import struct
import zipfile
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# Nd4j.write / Nd4j.read binary codec
# ---------------------------------------------------------------------------

_DTYPES = {"FLOAT": ("f", 4), "DOUBLE": ("d", 8), "INT": ("i", 4),
           "HALF": ("e", 2), "LONG": ("q", 8)}


def _read_utf(buf: io.BytesIO) -> str:
    (n,) = struct.unpack(">H", buf.read(2))
    return buf.read(n).decode("utf-8")


def _write_utf(buf: io.BytesIO, s: str) -> None:
    raw = s.encode("utf-8")
    buf.write(struct.pack(">H", len(raw)))
    buf.write(raw)


def _read_data_buffer(buf: io.BytesIO) -> Tuple[str, np.ndarray]:
    alloc = _read_utf(buf)  # HEAP / JAVACPP / DIRECT / MIXED_DATA_TYPES
    (length,) = struct.unpack(">i", buf.read(4))
    dtype = _read_utf(buf)
    if dtype not in _DTYPES:
        raise ValueError(f"unsupported ND4J data type {dtype!r}")
    code, width = _DTYPES[dtype]
    raw = buf.read(length * width)
    if len(raw) != length * width:
        raise ValueError("truncated ND4J data buffer")
    arr = np.frombuffer(raw, dtype=">" + code, count=length)
    return alloc, arr.astype(code if code != "e" else "f4")


def _write_data_buffer(buf: io.BytesIO, arr: np.ndarray, dtype: str) -> None:
    code, _ = _DTYPES[dtype]
    _write_utf(buf, "HEAP")
    buf.write(struct.pack(">i", arr.size))
    _write_utf(buf, dtype)
    buf.write(np.ascontiguousarray(arr.ravel()).astype(">" + code).tobytes())


def read_nd4j_array(data: bytes) -> np.ndarray:
    """Read an Nd4j.write()-format array: shape-info buffer + data buffer."""
    buf = io.BytesIO(data)
    _, shape_info = _read_data_buffer(buf)
    shape_info = shape_info.astype(np.int64)
    rank = int(shape_info[0])
    shape = tuple(int(s) for s in shape_info[1:1 + rank])
    order = chr(int(shape_info[3 + 2 * rank])) if len(shape_info) > 3 + 2 * rank \
        else "c"
    _, flat = _read_data_buffer(buf)
    if int(np.prod(shape)) != flat.size:
        raise ValueError(f"shape {shape} does not match {flat.size} elements")
    return flat.reshape(shape, order=order if order in ("c", "f") else "c")


def write_nd4j_array(arr: np.ndarray, dtype: str = "FLOAT") -> bytes:
    """Write an array in Nd4j.write() format ('c' order row vector layout),
    used to build DL4J-format checkpoints (fixtures + export-to-DL4J)."""
    arr = np.asarray(arr)
    if arr.ndim == 1:
        arr = arr[None, :]  # ND4J params() is a [1, N] row vector
    rank = arr.ndim
    shape = arr.shape
    strides = []
    s = 1
    for dim in reversed(shape):
        strides.insert(0, s)
        s *= dim
    shape_info = np.array([rank, *shape, *strides, 0, 1, ord("c")], np.int32)
    buf = io.BytesIO()
    _write_data_buffer(buf, shape_info, "INT")
    _write_data_buffer(buf, arr, dtype)
    return buf.getvalue()


# ---------------------------------------------------------------------------
# DL4J JSON → our confs
# ---------------------------------------------------------------------------

# IActivation wrapper-object names (nd4j linalg activations) → our names
_ACTIVATIONS = {
    "relu": "relu", "rectifiedlinear": "relu", "sigmoid": "sigmoid",
    "tanh": "tanh", "softmax": "softmax", "identity": "identity",
    "leakyrelu": "leakyrelu", "cube": "cube", "elu": "elu",
    "hardsigmoid": "hardsigmoid", "hardtanh": "hardtanh",
    "rationaltanh": "rationaltanh", "rectifiedtanh": "rectifiedtanh",
    "selu": "selu", "softplus": "softplus", "softsign": "softsign",
    "swish": "swish", "gelu": "gelu", "thresholdedrelu": "thresholdedrelu",
}

# ILossFunction wrapper names (LossMCXENT etc.) → our names
_LOSSES = {
    "lossmcxent": "mcxent", "lossmse": "mse", "lossl1": "l1", "lossl2": "l2",
    "lossbinaryxent": "xent", "lossnegativeloglikelihood":
        "negativeloglikelihood", "losskld": "kl_divergence",
    "losshinge": "hinge", "losssquaredhinge": "squared_hinge",
    "losspoisson": "poisson", "lossmape": "mape", "lossmsle": "msle",
    "losscosineproximity": "cosine_proximity",
    # LossFunctions.LossFunction enum spellings (older configs)
    "mcxent": "mcxent", "mse": "mse", "xent": "xent",
    "negativeloglikelihood": "negativeloglikelihood",
    "squared_loss": "mse", "kl_divergence": "kl_divergence",
}


def _unwrap(obj: Any) -> Tuple[Optional[str], dict]:
    """Jackson WRAPPER_OBJECT: {"TypeName": {...fields}} → (name, fields).
    Also accepts a bare string enum."""
    if isinstance(obj, str):
        return obj, {}
    if isinstance(obj, dict) and len(obj) == 1:
        (name, fields), = obj.items()
        if isinstance(fields, dict):
            return name, fields
    return None, obj if isinstance(obj, dict) else {}


def _activation_name(obj: Any, default: str = "identity") -> str:
    if obj is None:
        return default
    name, _ = _unwrap(obj)
    if name is None:
        return default
    key = name.lower().replace("activation", "")
    return _ACTIVATIONS.get(key, key)


def _loss_name(obj: Any, default: str = "mse") -> str:
    if obj is None:
        return default
    name, fields = _unwrap(obj)
    if name is None:
        return default
    return _LOSSES.get(name.lower(), name.lower())


def _updater_from_dl4j(obj: Any):
    """IUpdater wrapper object → our Updater (nd4j learning config classes)."""
    from deeplearning4j_tpu.nn import updater as U

    if obj is None:
        return U.Sgd(0.1)
    name, f = _unwrap(obj)
    name = (name or "Sgd").lower()
    lr = float(f.get("learningRate", f.get("lr", 0.1)))
    if name == "sgd":
        return U.Sgd(lr)
    if name == "nesterovs":
        return U.Nesterovs(lr, momentum=float(f.get("momentum", 0.9)))
    if name == "adam":
        return U.Adam(lr, beta1=float(f.get("beta1", 0.9)),
                      beta2=float(f.get("beta2", 0.999)),
                      epsilon=float(f.get("epsilon", 1e-8)))
    if name == "adamax":
        return U.AdaMax(lr, beta1=float(f.get("beta1", 0.9)),
                        beta2=float(f.get("beta2", 0.999)),
                        epsilon=float(f.get("epsilon", 1e-8)))
    if name == "nadam":
        return U.Nadam(lr, beta1=float(f.get("beta1", 0.9)),
                       beta2=float(f.get("beta2", 0.999)),
                       epsilon=float(f.get("epsilon", 1e-8)))
    if name == "rmsprop":
        return U.RmsProp(lr, rms_decay=float(f.get("rmsDecay", 0.95)),
                         epsilon=float(f.get("epsilon", 1e-8)))
    if name == "adagrad":
        return U.AdaGrad(lr, epsilon=float(f.get("epsilon", 1e-6)))
    if name == "adadelta":
        return U.AdaDelta(rho=float(f.get("rho", 0.95)),
                          epsilon=float(f.get("epsilon", 1e-6)))
    if name == "noop":
        return U.NoOp()
    return U.Sgd(lr)


def _get(f: dict, *names, default=None):
    """Fetch a field under any of Jackson's manglings (nin/nIn etc.)."""
    lower = {k.lower(): v for k, v in f.items()}
    for n in names:
        if n in f:
            return f[n]
        if n.lower() in lower:
            return lower[n.lower()]
    return default


def _pair(v, default=(1, 1)):
    if v is None:
        return list(default)
    if isinstance(v, (int, float)):
        return [int(v), int(v)]
    return [int(x) for x in v]


def _conv_mode(f: dict) -> str:
    m = _get(f, "convolutionMode", default=None)
    return {"Same": "same", "Truncate": "truncate", "Strict": "strict"}.get(
        m, "truncate") if isinstance(m, str) else "truncate"


def layer_from_dl4j(type_name: str, f: dict):
    """One DL4J layer JSON (unwrapped) → our LayerConf.

    Type names are the @JsonSubTypes registry in
    deeplearning4j-nn/.../conf/layers/Layer.java:49-73."""
    from deeplearning4j_tpu.nn.conf import layers as L

    t = type_name
    common = dict(
        name=_get(f, "layerName"),
        n_in=_get(f, "nin", "nIn"),
        n_out=_get(f, "nout", "nOut"),
    )
    common = {k: (int(v) if isinstance(v, (int, float)) and k != "name" else v)
              for k, v in common.items() if v is not None}
    act = _activation_name(_get(f, "activationFn", "activationFunction"),
                           "sigmoid")
    reg = dict(
        l1=float(_get(f, "l1", default=0.0) or 0.0),
        l2=float(_get(f, "l2", default=0.0) or 0.0),
        bias_init=float(_get(f, "biasInit", default=0.0) or 0.0),
    )
    wi = _get(f, "weightInit")
    if isinstance(wi, str):
        reg["weight_init"] = wi.lower()

    if t == "dense":
        return L.DenseLayer(activation=act, **common, **reg)
    if t == "output":
        return L.OutputLayer(activation=act,
                             loss=_loss_name(_get(f, "lossFn", "lossFunction")),
                             **common, **reg)
    if t == "rnnoutput":
        return L.RnnOutputLayer(activation=act,
                                loss=_loss_name(_get(f, "lossFn", "lossFunction")),
                                **common, **reg)
    if t == "loss":
        return L.LossLayer(activation=act,
                           loss=_loss_name(_get(f, "lossFn", "lossFunction")),
                           **common)
    if t == "convolution":
        return L.ConvolutionLayer(
            activation=act,
            kernel=_pair(_get(f, "kernelSize"), (3, 3)),
            stride=_pair(_get(f, "stride"), (1, 1)),
            padding=_pair(_get(f, "padding"), (0, 0)),
            dilation=_pair(_get(f, "dilation"), (1, 1)),
            convolution_mode=_conv_mode(f),
            has_bias=bool(_get(f, "hasBias", default=True)),
            **common, **reg)
    if t == "subsampling":
        pool, _ = _unwrap(_get(f, "poolingType", default="MAX"))
        return L.SubsamplingLayer(
            pooling_type=(pool or "MAX").lower().replace("pooling", ""),
            kernel=_pair(_get(f, "kernelSize"), (2, 2)),
            stride=_pair(_get(f, "stride"), (2, 2)),
            padding=_pair(_get(f, "padding"), (0, 0)),
            convolution_mode=_conv_mode(f),
            name=common.get("name"))
    if t == "batchNormalization":
        return L.BatchNormalization(
            eps=float(_get(f, "eps", default=1e-5)),
            decay=float(_get(f, "decay", default=0.9)),
            gamma=float(_get(f, "gamma", default=1.0)),
            beta=float(_get(f, "beta", default=0.0)),
            lock_gamma_beta=bool(_get(f, "lockGammaBeta", default=False)),
            activation=_activation_name(_get(f, "activationFn"), "identity"),
            name=common.get("name"))
    if t == "localResponseNormalization":
        return L.LocalResponseNormalization(
            k=float(_get(f, "k", default=2.0)),
            n=int(_get(f, "n", default=5)),
            alpha=float(_get(f, "alpha", default=1e-4)),
            beta=float(_get(f, "beta", default=0.75)),
            name=common.get("name"))
    if t in ("LSTM", "gravesLSTM"):
        cls = L.LSTM if t == "LSTM" else L.GravesLSTM
        return cls(activation=_activation_name(_get(f, "activationFn"), "tanh"),
                   gate_activation=_activation_name(
                       _get(f, "gateActivationFn"), "sigmoid"),
                   forget_gate_bias_init=float(
                       _get(f, "forgetGateBiasInit", default=1.0)),
                   **common, **reg)
    if t == "gravesBidirectionalLSTM":
        return L.GravesBidirectionalLSTM(
            activation=_activation_name(_get(f, "activationFn"), "tanh"),
            gate_activation=_activation_name(
                _get(f, "gateActivationFn"), "sigmoid"),
            forget_gate_bias_init=float(
                _get(f, "forgetGateBiasInit", default=1.0)),
            **common, **reg)
    if t == "embedding":
        return L.EmbeddingLayer(activation=act, **common, **reg)
    if t == "activation":
        return L.ActivationLayer(activation=act, name=common.get("name"))
    if t == "dropout":
        return L.DropoutLayer(name=common.get("name"))
    if t == "autoEncoder":
        return L.AutoEncoder(activation=act,
                             corruption_level=float(
                                 _get(f, "corruptionLevel", default=0.3)),
                             **common, **reg)
    if t == "RBM":
        hu, _ = _unwrap(_get(f, "hiddenUnit", default="BINARY"))
        vu, _ = _unwrap(_get(f, "visibleUnit", default="BINARY"))
        return L.RBM(activation=act,
                     hidden_unit=(hu or "BINARY").lower(),
                     visible_unit=(vu or "BINARY").lower(),
                     k=int(_get(f, "k", default=1)),
                     sparsity=float(_get(f, "sparsity", default=0.0)),
                     **common, **reg)
    if t == "GlobalPooling":
        pool, _ = _unwrap(_get(f, "poolingType", default="MAX"))
        return L.GlobalPoolingLayer(
            pooling_type=(pool or "MAX").lower().replace("pooling", ""),
            name=common.get("name"))
    if t == "zeroPadding":
        pad = _get(f, "padding", default=[0, 0, 0, 0])
        return L.ZeroPaddingLayer(padding=[int(p) for p in pad],
                                  name=common.get("name"))
    if t == "Upsampling2D":
        return L.Upsampling2DLayer(size=int(_pair(_get(f, "size"), (2, 2))[0]),
                                   name=common.get("name"))
    raise ValueError(f"unsupported DL4J layer type {type_name!r}")


def multi_layer_configuration_from_dl4j(json_str: str):
    """DL4J MultiLayerConfiguration JSON → our MultiLayerConfiguration
    (ref: MultiLayerConfiguration.fromJson :138)."""
    from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration

    d = json.loads(json_str)
    layers = []
    updater = None
    seed = 12345
    for conf in d.get("confs", []):
        layer_obj = conf.get("layer")
        tname, fields = _unwrap(layer_obj)
        if tname is None:
            raise ValueError("conf without wrapped layer object")
        layers.append(layer_from_dl4j(tname, fields))
        seed = int(conf.get("seed", seed))
        if updater is None and (fields.get("iUpdater") or fields.get("iupdater")):
            updater = _updater_from_dl4j(fields.get("iUpdater") or
                                         fields.get("iupdater"))
    mlc = MultiLayerConfiguration(
        layers=layers,
        seed=seed,
        backprop=bool(d.get("backprop", True)),
        pretrain=bool(d.get("pretrain", False)),
        tbptt_fwd_length=int(d.get("tbpttFwdLength", 20)),
        tbptt_back_length=int(d.get("tbpttBackLength", 20)),
        tbptt=d.get("backpropType") == "TruncatedBPTT",
    )
    if updater is not None:
        mlc.updater = updater
    # our exporter stows the InputType (real DL4J JSON carries only
    # inputPreProcessors; unknown keys are ignored by DL4J's Jackson too)
    if d.get("inputType"):
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        mlc.input_type = InputType.from_dict(d["inputType"])
    elif layers and getattr(layers[0], "n_in", None):
        # DL4J configs carry only nIn; recover the network InputType for
        # dense/recurrent-first nets (conv-first needs the caller to supply
        # spatial dims via restore_multi_layer_network(input_type=...))
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        first = type(layers[0]).__name__
        if first in ("LSTM", "GravesLSTM", "GravesBidirectionalLSTM",
                     "SimpleRnn"):
            mlc.input_type = InputType.recurrent(layers[0].n_in)
        elif first not in ("ConvolutionLayer", "SubsamplingLayer"):
            mlc.input_type = InputType.feed_forward(layers[0].n_in)
    return mlc


# ---------------------------------------------------------------------------
# flat param vector ↔ per-layer pytrees
# ---------------------------------------------------------------------------

def _lstm_perm(h: int) -> np.ndarray:
    """Column index map DL4J [i,f,o,g] blocks → our (i,f,c,o) blocks:
    ours = [theirs_g, theirs_f, theirs_i, theirs_o]."""
    i = np.arange(h)
    return np.concatenate([3 * h + i, h + i, i, 2 * h + i])


def _take(flat: np.ndarray, pos: int, n: int) -> Tuple[np.ndarray, int]:
    if pos + n > flat.size:
        raise ValueError(
            f"flat param vector too short: need {pos + n}, have {flat.size}")
    return flat[pos:pos + n], pos + n


def _lstm_block_from_flat(flat, pos, n_in, h, peephole):
    import jax.numpy as jnp
    perm = _lstm_perm(h)
    w, pos = _take(flat, pos, n_in * 4 * h)
    w = w.reshape((n_in, 4 * h), order="F")[:, perm]
    rw_cols = 4 * h + (3 if peephole else 0)
    rw_full, pos = _take(flat, pos, h * rw_cols)
    rw_full = rw_full.reshape((h, rw_cols), order="F")
    rw = rw_full[:, :4 * h][:, perm]
    b, pos = _take(flat, pos, 4 * h)
    b = b[perm]
    p = {"W": jnp.asarray(w), "RW": jnp.asarray(rw), "b": jnp.asarray(b)}
    if peephole:
        wff, woo, wgg = (rw_full[:, 4 * h], rw_full[:, 4 * h + 1],
                         rw_full[:, 4 * h + 2])
        p["P"] = jnp.stack([jnp.asarray(wgg), jnp.asarray(wff),
                            jnp.asarray(woo)])  # (pI, pF, pO)
    return p, pos


def _lstm_block_to_flat(p: dict, peephole: bool) -> np.ndarray:
    w = np.asarray(p["W"], np.float64)
    rw = np.asarray(p["RW"], np.float64)
    b = np.asarray(p["b"], np.float64)
    h = rw.shape[0]
    perm = _lstm_perm(h)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(4 * h)
    w_d = w[:, inv]
    rw_d = rw[:, inv]
    b_d = b[inv]
    if peephole:
        pI, pF, pO = np.asarray(p["P"], np.float64)
        rw_d = np.concatenate([rw_d, pF[:, None], pO[:, None], pI[:, None]],
                              axis=1)
    return np.concatenate([w_d.ravel(order="F"), rw_d.ravel(order="F"), b_d])


def _layer_items_mln(conf):
    """(key, layer, input_type) triplets in the MLN flat-view order."""
    its = conf.layer_input_types()
    return [(str(i), layer, it)
            for i, (layer, it) in enumerate(zip(conf.layers, its))]


def _layer_items_cg(conf, vertex_input_types: Dict[str, List]):
    """(key, layer, input_type) triplets for a ComputationGraph: LAYER
    vertices in topological order (the reference's flat param order,
    ComputationGraph.java:418-479 walks topologicalOrder). Non-layer
    vertices carry no params. `vertex_input_types` maps vertex name ->
    its input InputTypes (ComputationGraph._infer_types populates it)."""
    items = []
    for name in conf.topological_order():
        v = conf.vertices[name]
        layer = getattr(v, "layer", None)
        if layer is None:
            continue
        its = vertex_input_types.get(name, [])
        it = its[0] if its else None
        pre = getattr(v, "preprocessor", None)
        if pre is not None and it is not None:
            # LayerVertex.init sizes params on the POST-preprocessor type
            it = pre.output_type(it)
        items.append((name, layer, it))
    return items


def params_from_flat(conf, flat: np.ndarray) -> Tuple[Dict[str, dict],
                                                      Dict[str, dict]]:
    """Slice a DL4J flat parameter vector into our per-layer param/state
    pytrees, following each reference ParamInitializer's view layout.

    Returns (params, state) keyed by layer index strings (our MLN layout);
    state carries BN running mean/var (stored as params in DL4J)."""
    return params_from_flat_items(_layer_items_mln(conf), flat)


def params_from_flat_items(items, flat: np.ndarray
                           ) -> Tuple[Dict[str, dict], Dict[str, dict]]:
    """params_from_flat over explicit (key, layer, input_type) items —
    shared by the MLN (index-keyed) and CG (vertex-name-keyed) paths."""
    import jax.numpy as jnp

    flat = np.asarray(flat, np.float64).ravel()
    params: Dict[str, dict] = {}
    state: Dict[str, dict] = {}
    pos = 0
    for key, layer, it in items:
        t = type(layer).__name__
        if t in ("DenseLayer", "OutputLayer", "RnnOutputLayer",
                 "EmbeddingLayer", "CenterLossOutputLayer"):
            n_in = layer.n_in if layer.n_in else it.flat_size()
            n_out = layer.n_out
            w, pos = _take(flat, pos, n_in * n_out)
            p = {"W": jnp.asarray(w.reshape((n_in, n_out), order="F"))}
            if getattr(layer, "has_bias", True):
                b, pos = _take(flat, pos, n_out)
                p["b"] = jnp.asarray(b)
            params[key] = p
        elif t in ("AutoEncoder", "RBM"):
            n_in = layer.n_in if layer.n_in else it.flat_size()
            n_out = layer.n_out
            w, pos = _take(flat, pos, n_in * n_out)
            b, pos = _take(flat, pos, n_out)
            vb, pos = _take(flat, pos, n_in)
            params[key] = {"W": jnp.asarray(w.reshape((n_in, n_out), order="F")),
                           "b": jnp.asarray(b), "vb": jnp.asarray(vb)}
        elif t in ("ConvolutionLayer", "Deconvolution2DLayer"):
            n_in = layer.n_in if layer.n_in else it.channels
            n_out = layer.n_out
            kh, kw = (layer.kernel if isinstance(layer.kernel, (list, tuple))
                      else (layer.kernel, layer.kernel))
            p = {}
            if getattr(layer, "has_bias", True):
                b, pos = _take(flat, pos, n_out)  # conv: bias FIRST
                p["b"] = jnp.asarray(b)
            w, pos = _take(flat, pos, n_out * n_in * kh * kw)
            p["W"] = jnp.asarray(w.reshape((n_out, n_in, kh, kw), order="C"))
            params[key] = p
        elif t == "BatchNormalization":
            nf = it.channels if it.kind == "cnn" else it.flat_size()
            p = {}
            if not layer.lock_gamma_beta:
                g, pos = _take(flat, pos, nf)
                bta, pos = _take(flat, pos, nf)
                p["gamma"], p["beta"] = jnp.asarray(g), jnp.asarray(bta)
            mean, pos = _take(flat, pos, nf)
            var, pos = _take(flat, pos, nf)
            params[key] = p
            state[key] = {"mean": jnp.asarray(mean), "var": jnp.asarray(var)}
        elif t in ("LSTM", "GravesLSTM"):
            n_in = layer.n_in if layer.n_in else it.size
            h = layer.n_out
            p, pos = _lstm_block_from_flat(flat, pos, n_in, h,
                                           t == "GravesLSTM")
            params[key] = p
        elif t == "GravesBidirectionalLSTM":
            n_in = layer.n_in if layer.n_in else it.size
            h = layer.n_out
            pf, pos = _lstm_block_from_flat(flat, pos, n_in, h, True)
            pb, pos = _lstm_block_from_flat(flat, pos, n_in, h, True)
            params[key] = {"WF": pf["W"], "RWF": pf["RW"], "bF": pf["b"],
                           "PF": pf["P"], "WB": pb["W"], "RWB": pb["RW"],
                           "bB": pb["b"], "PB": pb["P"]}
        else:
            params[key] = {}  # parameterless layer
    if pos != flat.size:
        raise ValueError(
            f"flat vector has {flat.size} values but layout consumed {pos}")
    return params, state


def params_to_flat(conf, params: Dict[str, dict],
                   state: Dict[str, dict]) -> np.ndarray:
    """Inverse of params_from_flat: our pytrees → the DL4J flat row vector."""
    return params_to_flat_items(_layer_items_mln(conf), params, state)


def params_to_flat_items(items, params: Dict[str, dict],
                         state: Dict[str, dict]) -> np.ndarray:
    """params_to_flat over explicit (key, layer, input_type) items."""
    chunks: List[np.ndarray] = []
    for key, layer, it in items:
        t = type(layer).__name__
        p = params.get(key, {})
        if t in ("DenseLayer", "OutputLayer", "RnnOutputLayer",
                 "EmbeddingLayer", "CenterLossOutputLayer"):
            chunks.append(np.asarray(p["W"], np.float64).ravel(order="F"))
            if "b" in p:
                chunks.append(np.asarray(p["b"], np.float64).ravel())
        elif t in ("AutoEncoder", "RBM"):
            chunks.append(np.asarray(p["W"], np.float64).ravel(order="F"))
            chunks.append(np.asarray(p["b"], np.float64).ravel())
            chunks.append(np.asarray(p["vb"], np.float64).ravel())
        elif t in ("ConvolutionLayer", "Deconvolution2DLayer"):
            if "b" in p:
                chunks.append(np.asarray(p["b"], np.float64).ravel())
            chunks.append(np.asarray(p["W"], np.float64).ravel(order="C"))
        elif t == "BatchNormalization":
            if "gamma" in p:
                chunks.append(np.asarray(p["gamma"], np.float64).ravel())
                chunks.append(np.asarray(p["beta"], np.float64).ravel())
            st = state.get(key, {})
            nf = it.channels if it.kind == "cnn" else it.flat_size()
            chunks.append(np.asarray(st.get("mean", np.zeros(nf)),
                                     np.float64).ravel())
            chunks.append(np.asarray(st.get("var", np.ones(nf)),
                                     np.float64).ravel())
        elif t in ("LSTM", "GravesLSTM"):
            chunks.append(_lstm_block_to_flat(p, t == "GravesLSTM"))
        elif t == "GravesBidirectionalLSTM":
            chunks.append(_lstm_block_to_flat(
                {"W": p["WF"], "RW": p["RWF"], "b": p["bF"], "P": p["PF"]},
                True))
            chunks.append(_lstm_block_to_flat(
                {"W": p["WB"], "RW": p["RWB"], "b": p["bB"], "P": p["PB"]},
                True))
    if not chunks:
        return np.zeros((0,), np.float64)
    return np.concatenate(chunks)


# ---------------------------------------------------------------------------
# updater state (updaterState.bin) <-> our updater pytrees
# ---------------------------------------------------------------------------

#: our updater state-tree keys, in the reference's view order (ND4J
#: GradientUpdater.setStateViewArray layouts: AdamUpdater m|v, NadamUpdater
#: m|v, AdaMaxUpdater m|u, AdaDeltaUpdater msg|msdx, NesterovsUpdater v,
#: RmsPropUpdater lastGradient, AdaGradUpdater historicalGradient)
_UPDATER_STATE_KEYS = {
    "Adam": ("m", "v"), "Nadam": ("m", "v"), "AdaMax": ("m", "u"),
    "AdaDelta": ("g2", "dx2"), "Nesterovs": ("v",), "RmsProp": ("g2",),
    "AdaGrad": ("h",), "Sgd": (), "NoOp": (),
}


def _variable_layout(conf, items=None
                     ) -> List[Tuple[str, str, int, int, bool]]:
    """The (layer_key, var, view_offset, size, has_updater_state) sequence
    of the flat param view, mirroring params_from_flat exactly. Variables
    with has_updater_state=False (BN global mean/var — NoOp updater per
    BatchNormalization.java:144-151) occupy no updater-state view and break
    updater blocks (BaseMultiLayerUpdater.java:95-99 block combining).
    `items` overrides the (key, layer, input_type) walk (CG vertex order);
    default is the MLN layer order."""
    if items is None:
        items = _layer_items_mln(conf)
    out: List[Tuple[str, str, int, int, bool]] = []
    pos = 0

    def add(key, var, size, stateful=True):
        nonlocal pos
        out.append((key, var, pos, int(size), stateful))
        pos += int(size)

    for key, layer, it in items:
        t = type(layer).__name__
        if t in ("DenseLayer", "OutputLayer", "RnnOutputLayer",
                 "EmbeddingLayer", "CenterLossOutputLayer"):
            n_in = layer.n_in if layer.n_in else it.flat_size()
            add(key, "W", n_in * layer.n_out)
            if getattr(layer, "has_bias", True):
                add(key, "b", layer.n_out)
        elif t in ("AutoEncoder", "RBM"):
            n_in = layer.n_in if layer.n_in else it.flat_size()
            add(key, "W", n_in * layer.n_out)
            add(key, "b", layer.n_out)
            add(key, "vb", n_in)
        elif t in ("ConvolutionLayer", "Deconvolution2DLayer"):
            n_in = layer.n_in if layer.n_in else it.channels
            kh, kw = (layer.kernel if isinstance(layer.kernel, (list, tuple))
                      else (layer.kernel, layer.kernel))
            if getattr(layer, "has_bias", True):
                add(key, "b", layer.n_out)  # conv: bias FIRST
            add(key, "W", layer.n_out * n_in * kh * kw)
        elif t == "BatchNormalization":
            nf = it.channels if it.kind == "cnn" else it.flat_size()
            if not layer.lock_gamma_beta:
                add(key, "gamma", nf)
                add(key, "beta", nf)
            add(key, "mean", nf, stateful=False)
            add(key, "var", nf, stateful=False)
        elif t in ("LSTM", "GravesLSTM"):
            n_in = layer.n_in if layer.n_in else it.size
            h = layer.n_out
            rw_cols = 4 * h + (3 if t == "GravesLSTM" else 0)
            add(key, "W", n_in * 4 * h)
            add(key, "RW", h * rw_cols)
            add(key, "b", 4 * h)
        elif t == "GravesBidirectionalLSTM":
            n_in = layer.n_in if layer.n_in else it.size
            h = layer.n_out
            for d in ("F", "B"):
                add(key, "W" + d, n_in * 4 * h)
                add(key, "RW" + d, h * (4 * h + 3))
                add(key, "b" + d, 4 * h)
    return out


def _stateful_runs(layout):
    """Maximal contiguous runs of stateful variables == updater blocks for
    a uniform network-wide updater config (our conf model; the reference
    additionally splits on per-layer LR/updater differences)."""
    runs, cur = [], []
    for entry in layout:
        if entry[4]:
            cur.append(entry)
        elif cur:
            runs.append(cur)
            cur = []
    if cur:
        runs.append(cur)
    return runs


def updater_state_from_flat(conf, flat: np.ndarray, params: Dict[str, dict],
                            iteration_count: int = 0, items=None):
    """Decode a DL4J ``updaterState.bin`` flat view into our updater state
    pytree (ref layout: BaseMultiLayerUpdater.java:72-121 blocks, each
    [state0 | state1] over the block's params in view order).

    `params` supplies the target structure/dtypes (our restored pytree;
    entries absent from the flat view — parameterless vertices — are
    zero-filled to keep the pytree structures aligned);
    returns None for stateless updaters (Sgd/NoOp). The iteration counter
    (DL4J passes the model's iterationCount into applyUpdater,
    UpdaterBlock.java:104) seeds the Adam-family "t"."""
    import jax
    import jax.numpy as jnp

    updater = conf.updater
    keys = _UPDATER_STATE_KEYS.get(type(updater).__name__)
    if keys is None:
        raise ValueError(
            f"no DL4J updater-state layout for {type(updater).__name__}")
    if not keys:
        return None
    k = len(keys)
    flat = np.asarray(flat, np.float64).ravel()
    if items is None:
        items = _layer_items_mln(conf)
    layout = _variable_layout(conf, items)
    view_len = sum(e[3] for e in layout)

    # per-variable slices of each state tensor, block-interleaved
    slices: Dict[Tuple[str, str, int], np.ndarray] = {}
    pos = 0
    for run in _stateful_runs(layout):
        for j in range(k):
            for (key, var, off, size, _) in run:
                slices[(key, var, j)] = flat[pos:pos + size]
                pos += size
    if pos != flat.size:
        raise ValueError(
            f"updater state has {flat.size} values but the block layout "
            f"consumed {pos} (updater {type(updater).__name__})")

    # k synthetic param-view vectors -> params_from_flat applies the same
    # per-variable reshapes/gate permutations as the params themselves
    trees = []
    for j in range(k):
        synth = np.zeros((view_len,), np.float64)
        for (key, var, off, size, stateful) in layout:
            if stateful:
                synth[off:off + size] = slices[(key, var, j)]
        tree, _bn = params_from_flat_items(items, synth)
        cast = {
            lk: {pk: jnp.asarray(pv, params.get(lk, {}).get(
                pk, np.zeros(1, np.float32)).dtype)
                 for pk, pv in lp.items()}
            for lk, lp in tree.items()}
        # parameterless vertices/layers (merge, elementwise, ...) carry
        # empty entries in the params pytree — mirror the structure or
        # tree_map in the updater step fails on key mismatch
        for lk, lp in params.items():
            if lk not in cast:
                cast[lk] = jax.tree_util.tree_map(jnp.zeros_like, lp)
        trees.append(cast)

    state = dict(zip(keys, trees))
    if type(updater).__name__ in ("Adam", "Nadam", "AdaMax"):
        state["t"] = jnp.asarray(int(iteration_count), jnp.int32)
    return state


def updater_state_to_flat(conf, updater_state,
                          items=None) -> Optional[np.ndarray]:
    """Inverse of updater_state_from_flat: our updater pytree -> the DL4J
    flat updater view (block-interleaved state tensors)."""
    updater = conf.updater
    keys = _UPDATER_STATE_KEYS.get(type(updater).__name__, None)
    if not keys or not updater_state:
        return None
    if items is None:
        items = _layer_items_mln(conf)
    fulls = [params_to_flat_items(items, updater_state[key], {})
             for key in keys]
    layout = _variable_layout(conf, items)
    view_len = sum(e[3] for e in layout)
    for full in fulls:
        if full.size != view_len:
            raise ValueError(
                f"updater layout drift: param view is {full.size} values "
                f"but _variable_layout declares {view_len}")
    chunks: List[np.ndarray] = []
    for run in _stateful_runs(layout):
        for full in fulls:
            for (key, var, off, size, _) in run:
                chunks.append(full[off:off + size])
    if not chunks:
        return None
    return np.concatenate(chunks)


# ---------------------------------------------------------------------------
# zip-level import / export
# ---------------------------------------------------------------------------

def restore_multi_layer_network(path: str, input_type=None):
    """Import a DL4J MultiLayerNetwork zip
    (ref: ModelSerializer.restoreMultiLayerNetwork :137).

    `input_type` pins the network InputType when the config alone cannot
    determine it (conv-first networks: DL4J stores only nIn/nOut, not the
    spatial dims — callers know the intended input shape)."""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    with zipfile.ZipFile(path) as zf:
        names = set(zf.namelist())
        if "configuration.json" not in names:
            raise ValueError("not a DL4J checkpoint: no configuration.json")
        conf_json = zf.read("configuration.json").decode()
        coeffs = (read_nd4j_array(zf.read("coefficients.bin"))
                  if "coefficients.bin" in names else None)
        upd_flat = (read_nd4j_array(zf.read("updaterState.bin"))
                    if "updaterState.bin" in names else None)

    conf = multi_layer_configuration_from_dl4j(conf_json)
    iteration_count = int(json.loads(conf_json).get("iterationCount", 0))
    if input_type is not None:
        conf.input_type = input_type
    net = MultiLayerNetwork(conf)
    net.init()
    if coeffs is not None:
        params, bn_state = params_from_flat(conf, coeffs)
        cast = net.params  # preserve our dtypes
        import jax.numpy as jnp
        net.params = {
            k: {pk: jnp.asarray(pv, cast[k][pk].dtype if pk in cast.get(k, {})
                                else jnp.float32)
                for pk, pv in v.items()}
            for k, v in params.items()}
        for k, st in bn_state.items():
            net.state.setdefault(k, {}).update(
                {sk: jnp.asarray(sv, jnp.float32) for sk, sv in st.items()})
        if upd_flat is not None:
            restored = updater_state_from_flat(conf, upd_flat, net.params,
                                               iteration_count)
            if restored is not None:
                net.updater_state = restored
    net.iteration_count = iteration_count
    return net


def save_dl4j_format(net, path: str) -> None:
    """Write a MultiLayerNetwork OR ComputationGraph in the DL4J zip
    format (configuration.json in the reference's Jackson shape +
    coefficients.bin flat vector + updaterState.bin). Used for zoo
    pretrained fixtures and export-to-DL4J."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    if isinstance(net, ComputationGraph):
        net._infer_types()
        items = _layer_items_cg(net.conf, net._vertex_input_types)
        conf_d = cg_to_dl4j_json(net.conf)
    else:
        items = _layer_items_mln(net.conf)
        conf_d = mlc_to_dl4j_json(net.conf)
    flat = params_to_flat_items(items, net.params, net.state)
    conf_d["iterationCount"] = int(net.iteration_count)
    # atomic: zip assembled at a tmp path, renamed onto `path` on success
    from deeplearning4j_tpu.resilience.durable import atomic_replace_path
    with atomic_replace_path(path) as _tmp, \
            zipfile.ZipFile(_tmp, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("configuration.json", json.dumps(conf_d, indent=2))
        zf.writestr("coefficients.bin",
                    write_nd4j_array(flat.astype(np.float32)))
        upd = updater_state_to_flat(net.conf, net.updater_state, items)
        if upd is not None:
            zf.writestr("updaterState.bin",
                        write_nd4j_array(upd.astype(np.float32)))


def _activation_to_dl4j(name: str) -> dict:
    table = {"relu": "ReLU", "sigmoid": "Sigmoid", "tanh": "TanH",
             "softmax": "Softmax", "identity": "Identity",
             "leakyrelu": "LReLU", "elu": "ELU", "cube": "Cube",
             "hardsigmoid": "HardSigmoid", "hardtanh": "HardTanh",
             "softplus": "SoftPlus", "softsign": "SoftSign", "selu": "SELU",
             "rationaltanh": "RationalTanh", "rectifiedtanh": "RectifiedTanh"}
    return {f"Activation{table.get(name, name.title())}": {}}


def _loss_to_dl4j(name: str) -> dict:
    table = {"mcxent": "LossMCXENT", "mse": "LossMSE", "l1": "LossL1",
             "l2": "LossL2", "xent": "LossBinaryXENT",
             "negativeloglikelihood": "LossNegativeLogLikelihood",
             "kl_divergence": "LossKLD", "hinge": "LossHinge",
             "squared_hinge": "LossSquaredHinge", "poisson": "LossPoisson",
             "mape": "LossMAPE", "msle": "LossMSLE",
             "cosine_proximity": "LossCosineProximity"}
    return {table.get(name, "LossMSE"): {}}


def _updater_to_dl4j(u) -> Optional[dict]:
    """Our Updater → the nd4j IUpdater wrapper object (inverse of
    _updater_from_dl4j; ref: config classes in org.nd4j.linalg.learning.config
    serialized per-layer as the BaseLayer "iUpdater" field)."""
    t = type(u).__name__
    lr = {"learningRate": float(getattr(u, "learning_rate", 0.1))}
    if t == "Sgd":
        return {"Sgd": lr}
    if t == "Nesterovs":
        return {"Nesterovs": {**lr, "momentum": float(u.momentum)}}
    if t in ("Adam", "AdaMax", "Nadam"):
        return {t: {**lr, "beta1": float(u.beta1), "beta2": float(u.beta2),
                    "epsilon": float(getattr(u, "epsilon", 1e-8))}}
    if t == "RmsProp":
        return {"RmsProp": {**lr, "rmsDecay": float(u.rms_decay),
                            "epsilon": float(u.epsilon)}}
    if t == "AdaGrad":
        return {"AdaGrad": {**lr, "epsilon": float(u.epsilon)}}
    if t == "AdaDelta":
        return {"AdaDelta": {"rho": float(u.rho),
                             "epsilon": float(u.epsilon)}}
    if t == "NoOp":
        return {"NoOp": {}}
    return None


def _layer_to_dl4j(layer, updater=None) -> dict:
    """Our LayerConf → a DL4J layer JSON wrapper object (subset of fields:
    enough for round-trip through layer_from_dl4j and real-DL4J loading)."""
    t = type(layer).__name__
    base = {"layerName": layer.name}
    if updater is not None:
        iu = _updater_to_dl4j(updater)
        if iu is not None:
            base["iUpdater"] = iu
    act = getattr(layer, "activation", None)
    if act:
        base["activationFn"] = _activation_to_dl4j(act)
    if getattr(layer, "n_in", None) is not None:
        base["nin"] = int(layer.n_in)
    if getattr(layer, "n_out", None) is not None:
        base["nout"] = int(layer.n_out)
    for src, dst in (("l1", "l1"), ("l2", "l2"), ("bias_init", "biasInit")):
        if getattr(layer, src, None):
            base[dst] = float(getattr(layer, src))
    if t == "DenseLayer":
        return {"dense": base}
    if t == "OutputLayer":
        base["lossFn"] = _loss_to_dl4j(layer.loss)
        return {"output": base}
    if t == "RnnOutputLayer":
        base["lossFn"] = _loss_to_dl4j(layer.loss)
        return {"rnnoutput": base}
    if t == "LossLayer":
        base["lossFn"] = _loss_to_dl4j(layer.loss)
        return {"loss": base}
    if t == "ConvolutionLayer":
        base.update(kernelSize=list(layer.kernel), stride=list(layer.stride),
                    padding=list(layer.padding),
                    hasBias=bool(layer.has_bias),
                    convolutionMode=layer.convolution_mode.title())
        return {"convolution": base}
    if t == "SubsamplingLayer":
        base.update(poolingType=layer.pooling_type.upper(),
                    kernelSize=list(layer.kernel), stride=list(layer.stride),
                    padding=list(layer.padding))
        return {"subsampling": base}
    if t == "BatchNormalization":
        base.update(eps=layer.eps, decay=layer.decay, gamma=layer.gamma,
                    beta=layer.beta, lockGammaBeta=layer.lock_gamma_beta)
        return {"batchNormalization": base}
    if t == "LocalResponseNormalization":
        base.update(k=layer.k, n=layer.n, alpha=layer.alpha, beta=layer.beta)
        return {"localResponseNormalization": base}
    if t == "LSTM":
        base["forgetGateBiasInit"] = layer.forget_gate_bias_init
        return {"LSTM": base}
    if t == "GravesLSTM":
        base["forgetGateBiasInit"] = layer.forget_gate_bias_init
        return {"gravesLSTM": base}
    if t == "GravesBidirectionalLSTM":
        base["forgetGateBiasInit"] = layer.forget_gate_bias_init
        return {"gravesBidirectionalLSTM": base}
    if t == "EmbeddingLayer":
        return {"embedding": base}
    if t == "ActivationLayer":
        return {"activation": base}
    if t == "DropoutLayer":
        return {"dropout": base}
    if t == "AutoEncoder":
        base["corruptionLevel"] = layer.corruption_level
        return {"autoEncoder": base}
    if t == "RBM":
        return {"RBM": base}
    if t == "GlobalPoolingLayer":
        base["poolingType"] = layer.pooling_type.upper()
        return {"GlobalPooling": base}
    raise ValueError(f"cannot export layer type {t} to DL4J JSON")


def mlc_to_dl4j_json(conf) -> dict:
    """Our MultiLayerConfiguration → DL4J MultiLayerConfiguration JSON dict."""
    d = {
        "backprop": conf.backprop,
        "backpropType": "TruncatedBPTT" if conf.tbptt else "Standard",
        "pretrain": conf.pretrain,
        "tbpttFwdLength": conf.tbptt_fwd_length,
        "tbpttBackLength": conf.tbptt_back_length,
        "confs": [{"seed": conf.seed,
                   "layer": _layer_to_dl4j(l, updater=conf.updater)}
                  for l in conf.layers],
    }
    if conf.input_type is not None:
        d["inputType"] = conf.input_type.to_dict()
    return d




# ---------------------------------------------------------------------------
# DL4J ComputationGraph JSON <-> our ComputationGraphConfiguration
# ---------------------------------------------------------------------------

def _preprocessor_from_dl4j(obj):
    """DL4J InputPreProcessor wrapper object -> ours (ref: the
    nn/conf/preprocessor classes; Jackson field names inputHeight/
    inputWidth/numChannels). `timesteps` is OUR extension field (DL4J
    reshapes from runtime miniBatchSize; our static-shape jit needs it
    declared) — round-tripped so restored graphs keep their time dim."""
    from deeplearning4j_tpu.nn.conf import preprocessors as PP

    name, f = _unwrap(obj)
    if name is None:
        return None
    t = name.lower().replace("preprocessor", "")
    h = int(f.get("inputHeight", 0))
    w = int(f.get("inputWidth", 0))
    c = int(f.get("numChannels", 0))
    ts = int(f.get("timesteps", 1))
    if t == "cnntofeedforward":
        return PP.CnnToFeedForwardPreProcessor(h, w, c)
    if t == "feedforwardtocnn":
        return PP.FeedForwardToCnnPreProcessor(h, w, c)
    if t == "rnntofeedforward":
        return PP.RnnToFeedForwardPreProcessor()
    if t == "feedforwardtornn":
        return PP.FeedForwardToRnnPreProcessor(timesteps=ts)
    if t == "cnntornn":
        return PP.CnnToRnnPreProcessor(h, w, c, timesteps=ts)
    if t == "rnntocnn":
        return PP.RnnToCnnPreProcessor(h, w, c)
    raise ValueError(f"unsupported DL4J input preprocessor {name!r}")


def _preprocessor_to_dl4j(p):
    t = type(p).__name__  # spelling matches DL4J's class names
    d = {}
    for src, dst in (("height", "inputHeight"), ("width", "inputWidth"),
                     ("channels", "numChannels"), ("timesteps",
                                                   "timesteps")):
        v = getattr(p, src, None)
        if v and not (src == "timesteps" and v == 1):
            d[dst] = int(v)
    return {t: d}


def _vertex_from_dl4j(tname: str, f: dict):
    """One DL4J GraphVertex wrapper object -> our GraphVertexConf (type
    names are the @JsonSubTypes registry in conf/graph/GraphVertex.java:40-52;
    field names are each vertex's @JsonProperty constructor args)."""
    from deeplearning4j_tpu.nn.conf import graph_conf as G

    t = tname.lower()
    if t == "layervertex":
        layer_obj = (f.get("layerConf") or {}).get("layer")
        ln, lf = _unwrap(layer_obj)
        if ln is None:
            raise ValueError("LayerVertex without wrapped layer object")
        pre = (_preprocessor_from_dl4j(f["preProcessor"])
               if f.get("preProcessor") else None)
        return G.LayerVertex(layer=layer_from_dl4j(ln, lf),
                             preprocessor=pre), lf
    if t == "mergevertex":
        return G.MergeVertex(), None
    if t == "elementwisevertex":
        op, _ = _unwrap(f.get("op", "Add"))
        return G.ElementWiseVertex(op=(op or "Add").lower()), None
    if t == "subsetvertex":
        return G.SubsetVertex(from_index=int(f.get("from", 0)),
                              to_index=int(f.get("to", 0))), None
    if t == "stackvertex":
        return G.StackVertex(), None
    if t == "unstackvertex":
        return G.UnstackVertex(from_index=int(f.get("from", 0)),
                               stack_size=int(f.get("stackSize", 1))), None
    if t == "lasttimestepvertex":
        return G.LastTimeStepVertex(
            mask_input=f.get("maskArrayInputName")), None
    if t == "duplicatetotimeseriesvertex":
        return G.DuplicateToTimeSeriesVertex(
            ts_input=f.get("inputName")), None
    if t == "scalevertex":
        return G.ScaleVertex(scale=float(f.get("scaleFactor", 1.0))), None
    if t == "shiftvertex":
        return G.ShiftVertex(shift=float(f.get("shiftFactor", 0.0))), None
    if t == "l2normalizevertex":
        return G.L2NormalizeVertex(), None
    if t == "l2vertex":
        return G.L2Vertex(), None
    if t == "poolhelpervertex":
        return G.PoolHelperVertex(), None
    raise ValueError(f"unsupported DL4J graph vertex type {tname!r}")


def _vertex_to_dl4j(v, updater=None) -> dict:
    """Our GraphVertexConf -> the DL4J wrapper object (inverse of
    _vertex_from_dl4j; layer vertices nest the layer under layerConf like
    ComputationGraphConfiguration JSON does). `updater` rides on each
    layer as iUpdater like the MLN exporter."""
    t = type(v).__name__
    if t == "LayerVertex":
        d = {"layerConf": {"layer": _layer_to_dl4j(v.layer,
                                                   updater=updater)}}
        if v.preprocessor is not None:
            d["preProcessor"] = _preprocessor_to_dl4j(v.preprocessor)
        return {"LayerVertex": d}
    if t == "MergeVertex":
        return {"MergeVertex": {}}
    if t == "ElementWiseVertex":
        return {"ElementWiseVertex": {"op": v.op.title()}}
    if t == "SubsetVertex":
        return {"SubsetVertex": {"from": v.from_index, "to": v.to_index}}
    if t == "StackVertex":
        return {"StackVertex": {}}
    if t == "UnstackVertex":
        return {"UnstackVertex": {"from": v.from_index,
                                  "stackSize": v.stack_size}}
    if t == "LastTimeStepVertex":
        return {"LastTimeStepVertex": {"maskArrayInputName": v.mask_input}}
    if t == "DuplicateToTimeSeriesVertex":
        return {"DuplicateToTimeSeriesVertex": {"inputName": v.ts_input}}
    if t == "ScaleVertex":
        return {"ScaleVertex": {"scaleFactor": v.scale}}
    if t == "ShiftVertex":
        return {"ShiftVertex": {"shiftFactor": v.shift}}
    if t == "L2NormalizeVertex":
        return {"L2NormalizeVertex": {}}
    if t == "L2Vertex":
        return {"L2Vertex": {}}
    if t == "PoolHelperVertex":
        return {"PoolHelperVertex": {}}
    raise ValueError(f"cannot export graph vertex type {t} to DL4J JSON")


def computation_graph_configuration_from_dl4j(json_str: str,
                                              input_types=None):
    """DL4J ComputationGraphConfiguration JSON -> our
    ComputationGraphConfiguration (ref: fromJson at
    ComputationGraphConfiguration.java:150-218; structure fields
    vertices/vertexInputs/networkInputs/networkOutputs :62-85).

    `input_types`: {input name -> InputType} when the JSON does not carry
    them (real DL4J files store only per-layer nIn/nOut; our exporter
    stows inputTypes the way the MLN exporter stows inputType)."""
    from deeplearning4j_tpu.nn.conf.network import (
        ComputationGraphConfiguration)
    from deeplearning4j_tpu.nn.conf.inputs import InputType

    d = json.loads(json_str)
    if "vertices" not in d:
        raise ValueError("not a ComputationGraph configuration "
                         "(no 'vertices' map)")
    vertices = {}
    updater = None
    for name, obj in d["vertices"].items():
        tname, fields = _unwrap(obj)
        v, layer_fields = _vertex_from_dl4j(tname, fields)
        vertices[name] = v
        if updater is None and layer_fields:
            iu = layer_fields.get("iUpdater") or layer_fields.get("iupdater")
            if iu:
                updater = _updater_from_dl4j(iu)
    conf = ComputationGraphConfiguration(
        vertices=vertices,
        vertex_inputs={k: list(v) for k, v in d.get("vertexInputs",
                                                    {}).items()},
        network_inputs=list(d.get("networkInputs", [])),
        network_outputs=list(d.get("networkOutputs", [])),
        seed=int(d.get("seed", 12345)),
        tbptt_fwd_length=int(d.get("tbpttFwdLength", 20)),
        tbptt_back_length=int(d.get("tbpttBackLength", 20)),
    )
    if updater is not None:
        conf.updater = updater
    its = d.get("inputTypes") or {}
    if its:
        conf.input_types = {k: InputType.from_dict(v)
                            for k, v in its.items()}
    elif input_types:
        conf.input_types = dict(input_types)
    else:
        raise ValueError(
            "DL4J ComputationGraph JSON carries no input types — pass "
            "input_types={input name: InputType} to the importer")
    return conf


def cg_to_dl4j_json(conf) -> dict:
    """Our ComputationGraphConfiguration -> DL4J JSON dict (the inverse
    direction; inputTypes stowed like the MLN exporter's inputType)."""
    return {
        "vertices": {name: _vertex_to_dl4j(v, updater=conf.updater)
                     for name, v in conf.vertices.items()},
        "vertexInputs": {k: list(v) for k, v in conf.vertex_inputs.items()},
        "networkInputs": list(conf.network_inputs),
        "networkOutputs": list(conf.network_outputs),
        "seed": conf.seed,
        "tbpttFwdLength": conf.tbptt_fwd_length,
        "tbpttBackLength": conf.tbptt_back_length,
        "inputTypes": {k: t.to_dict() for k, t in conf.input_types.items()},
        "confs": None,  # marks CG vs MLN for sniffers expecting the key
    }


def restore_computation_graph(path: str, input_types=None):
    """Import a DL4J ComputationGraph zip (ref:
    ModelSerializer.restoreComputationGraph :137-214). Flat params follow
    the vertex topological order (ComputationGraph.java:418-479); where
    several topological orders are valid ours must match the writer's —
    true for our own exports and for linear-ish reference graphs."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    with zipfile.ZipFile(path) as zf:
        names = set(zf.namelist())
        if "configuration.json" not in names:
            raise ValueError("not a DL4J checkpoint: no configuration.json")
        conf_json = zf.read("configuration.json").decode()
        coeffs = (read_nd4j_array(zf.read("coefficients.bin"))
                  if "coefficients.bin" in names else None)
        upd_flat = (read_nd4j_array(zf.read("updaterState.bin"))
                    if "updaterState.bin" in names else None)

    conf_dict = json.loads(conf_json)
    conf = computation_graph_configuration_from_dl4j(conf_json, input_types)
    iteration_count = int(conf_dict.get("iterationCount", 0))
    net = ComputationGraph(conf)
    net.init()
    if coeffs is not None:
        items = _layer_items_cg(conf, net._vertex_input_types)
        params, bn_state = params_from_flat_items(items, coeffs)
        import jax.numpy as jnp
        cast = net.params
        for k, v in params.items():
            net.params[k] = {
                pk: jnp.asarray(pv, cast.get(k, {}).get(pk, pv).dtype
                                if pk in cast.get(k, {}) else jnp.float32)
                for pk, pv in v.items()}
        for k, st in bn_state.items():
            net.state.setdefault(k, {}).update(
                {sk: jnp.asarray(sv, jnp.float32) for sk, sv in st.items()})
        if upd_flat is not None:
            restored = updater_state_from_flat(
                conf, upd_flat, net.params, iteration_count, items=items)
            if restored is not None:
                net.updater_state = restored
    net.iteration_count = iteration_count
    return net



def restore_model(path: str, input_types=None):
    """Sniff + restore a DL4J checkpoint (ref: core ModelGuesser):
    MultiLayerNetwork zips ("confs" list) and ComputationGraph zips
    ("vertices" map) both restore."""
    with zipfile.ZipFile(path) as zf:
        conf = json.loads(zf.read("configuration.json").decode())
    if "vertices" in conf:
        return restore_computation_graph(path, input_types=input_types)
    if "confs" not in conf or conf.get("confs") is None:
        raise ValueError(
            "configuration.json has neither a 'confs' list (MLN) nor a "
            "'vertices' map (ComputationGraph) — not a DL4J checkpoint")
    return restore_multi_layer_network(path)
