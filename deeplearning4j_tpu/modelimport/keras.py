"""Keras HDF5 model import.

TPU-native equivalent of deeplearning4j-modelimport (SURVEY §2.7):
KerasModelImport.java:41-123 (Sequential → MultiLayerNetwork :74-87,
Functional → ComputationGraph :50-123), KerasModel.java:57-379 (config JSON
from HDF5 attrs :109, build graph conf :276, import weights :166),
KerasLayer registry + per-layer mapping in layers/{core,convolutional,...}.

The reference reads HDF5 through the JavaCPP-wrapped C library
(Hdf5Archive.java); here h5py is the idiomatic equivalent binding of the same
C library (SURVEY §2.1 table).

Weight layout notes (SURVEY §7 "hard parts"):
- Keras Dense kernel [in, out] → ours [in, out] (direct).
- Keras Conv2D kernel HWIO [kh, kw, in, out] → ours OIHW.
- Keras LSTM kernel [in, 4H] gate order (i, f, c, o) → ours is ALSO
  (i, f, c, o) (chosen for this reason, nn/layers/recurrent.py) — direct copy.
- Keras 1 stores conv kernels OIHW already (th ordering) — both handled.

Supported layer set mirrors config/KerasLayerConfiguration.java:266:
Activation, Input, Dropout, Dense, LSTM, SimpleRNN, Max/AvgPooling1D/2D,
GlobalMax/AvgPooling1D/2D, ZeroPadding1D/2D, Flatten, Reshape, Merge/
Add/Concatenate, BatchNormalization, TimeDistributed(Dense), Embedding,
Convolution1D/2D, LeakyReLU, Upsampling1D/2D.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.graph_conf import (ElementWiseVertex,
                                                   LayerVertex, MergeVertex)
from deeplearning4j_tpu.nn.conf.network import (ComputationGraphConfiguration,
                                                MultiLayerConfiguration)
from deeplearning4j_tpu.nn.conf.preprocessors import (
    CnnToFeedForwardPreProcessor)
from deeplearning4j_tpu.nn.updater import Sgd

_KERAS_ACT = {
    "linear": "identity", "relu": "relu", "sigmoid": "sigmoid",
    "softmax": "softmax", "tanh": "tanh", "softplus": "softplus",
    "softsign": "softsign", "hard_sigmoid": "hardsigmoid", "elu": "elu",
    "selu": "selu", "swish": "swish", "gelu": "gelu", "relu6": "relu6",
}


def _act(name):
    return _KERAS_ACT.get(name, name)


def _cfg(layer_cfg: dict) -> dict:
    c = layer_cfg.get("config", layer_cfg)
    return c


def _pair(v):
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _keras3_history_names(obj) -> List[str]:
    """Recursively collect source-layer names from Keras-3 serialized call
    args (each tensor dict carries config.keras_history = [layer, node,
    tensor_index])."""
    out: List[str] = []
    if isinstance(obj, dict):
        hist = obj.get("config", {}).get("keras_history") \
            if isinstance(obj.get("config"), dict) else None
        if isinstance(hist, list) and hist and isinstance(hist[0], str):
            out.append(hist[0])
        else:
            for v in obj.values():
                out.extend(_keras3_history_names(v))
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            out.extend(_keras3_history_names(v))
    return out


class KerasLayerMapper:
    """Maps one Keras layer config to a LayerConf (+ required info)
    (ref: KerasLayer.java registry + layers/* mapping classes)."""

    def __init__(self, keras_version: int = 2):
        self.keras_version = keras_version

    def map(self, kcls: str, cfg: dict) -> Optional[L.LayerConf]:
        m = getattr(self, f"_map_{kcls.lower()}", None)
        if m is None:
            raise ValueError(f"Unsupported Keras layer type: {kcls}")
        return m(cfg)

    # --- core ---
    def _map_dense(self, c):
        # keras 1: output_dim / "bias"; keras 2: units / use_bias
        n_out = c.get("units", c.get("output_dim"))
        has_bias = c.get("use_bias", c.get("bias", True))
        return L.DenseLayer(n_out=int(n_out),
                            activation=_act(c.get("activation", "linear")),
                            has_bias=bool(has_bias),
                            name=c.get("name"))

    def _map_activation(self, c):
        return L.ActivationLayer(activation=_act(c.get("activation", "linear")),
                                 name=c.get("name"))

    def _map_leakyrelu(self, c):
        # keras default alpha=0.3 (ours is 0.01) — carry it explicitly
        alpha = float(c.get("alpha", c.get("negative_slope", 0.3)))
        return L.ActivationLayer(activation=f"leakyrelu({alpha})",
                                 name=c.get("name"))

    def _map_dropout(self, c):
        # Keras rate = DROP prob; our field = RETAIN prob (DL4J semantics)
        return L.DropoutLayer(dropout=1.0 - float(c.get("rate", 0.5)),
                              name=c.get("name"))

    def _map_flatten(self, c):
        return None  # handled as preprocessor

    def _map_reshape(self, c):
        return None  # shape adapters handled via preprocessors

    def _map_embedding(self, c):
        return L.EmbeddingLayer(n_in=int(c["input_dim"]),
                                n_out=int(c["output_dim"]), has_bias=False,
                                name=c.get("name"))

    # --- conv ---
    def _map_conv2d(self, c):
        k = _pair(c["kernel_size"] if "kernel_size" in c
                  else (c["nb_row"], c["nb_col"]))
        s = _pair(c.get("strides", c.get("subsample", (1, 1))))
        mode = "same" if c.get("padding", c.get("border_mode")) == "same" \
            else "truncate"
        n_out = int(c.get("filters", c.get("nb_filter")))
        # Keras 2 "dilation_rate" / Keras 1 atrous "atrous_rate"
        # (ref: KerasConvolutionUtils.getDilationRate, field names
        # Keras2LayerConfiguration:72 / Keras1LayerConfiguration:73)
        d = _pair(c.get("dilation_rate", c.get("atrous_rate", (1, 1))))
        return L.ConvolutionLayer(n_out=n_out, kernel=k, stride=s,
                                  padding=(0, 0), dilation=d,
                                  convolution_mode=mode,
                                  activation=_act(c.get("activation", "linear")),
                                  has_bias=c.get("use_bias", True),
                                  name=c.get("name"))

    _map_convolution2d = _map_conv2d
    # Keras 1 AtrousConvolution2D: a Convolution2D whose dilation comes
    # from "atrous_rate" (ref: KerasAtrousConvolution2D.java:44-138)
    _map_atrousconvolution2d = _map_conv2d

    def _map_conv1d(self, c):
        mode = "same" if c.get("padding", c.get("border_mode")) == "same" \
            else "truncate"
        d = _pair(c.get("dilation_rate", c.get("atrous_rate", 1)))[0]
        return L.Convolution1DLayer(
            n_out=int(c.get("filters", c.get("nb_filter"))),
            kernel=int(c["kernel_size"][0] if isinstance(c.get("kernel_size"),
                                                         (list, tuple))
                       else c.get("kernel_size", c.get("filter_length"))),
            stride=int((c.get("strides") or [1])[0]
                       if isinstance(c.get("strides"), (list, tuple))
                       else c.get("strides", c.get("subsample_length", 1))),
            dilation=int(d),
            convolution_mode=mode,
            activation=_act(c.get("activation", "linear")),
            name=c.get("name"))

    _map_convolution1d = _map_conv1d
    # Keras 1 AtrousConvolution1D (ref: KerasAtrousConvolution1D.java)
    _map_atrousconvolution1d = _map_conv1d

    def _map_maxpooling2d(self, c):
        k = _pair(c.get("pool_size", (2, 2)))
        s = _pair(c.get("strides") or k)
        mode = "same" if c.get("padding", c.get("border_mode")) == "same" \
            else "truncate"
        return L.SubsamplingLayer(pooling_type="max", kernel=k, stride=s,
                                  convolution_mode=mode, name=c.get("name"))

    def _map_averagepooling2d(self, c):
        l = self._map_maxpooling2d(c)
        l.pooling_type = "avg"
        return l

    def _map_globalmaxpooling2d(self, c):
        return L.GlobalPoolingLayer(pooling_type="max", name=c.get("name"))

    def _map_globalaveragepooling2d(self, c):
        return L.GlobalPoolingLayer(pooling_type="avg", name=c.get("name"))

    _map_globalmaxpooling1d = _map_globalmaxpooling2d
    _map_globalaveragepooling1d = _map_globalaveragepooling2d

    def _map_maxpooling1d(self, c):
        return L.Subsampling1DLayer(
            pooling_type="max",
            kernel=int(c.get("pool_size", [2])[0]
                       if isinstance(c.get("pool_size"), (list, tuple))
                       else c.get("pool_size", c.get("pool_length", 2))),
            stride=int(c.get("strides", [2])[0]
                       if isinstance(c.get("strides"), (list, tuple))
                       else c.get("strides") or 2),
            name=c.get("name"))

    def _map_averagepooling1d(self, c):
        l = self._map_maxpooling1d(c)
        l.pooling_type = "avg"
        return l

    def _map_zeropadding2d(self, c):
        p = c.get("padding", (1, 1))
        if isinstance(p[0], (list, tuple)):
            pads = [int(p[0][0]), int(p[0][1]), int(p[1][0]), int(p[1][1])]
        else:
            pads = [int(p[0]), int(p[0]), int(p[1]), int(p[1])]
        return L.ZeroPaddingLayer(padding=pads, name=c.get("name"))

    def _map_upsampling2d(self, c):
        return L.Upsampling2DLayer(size=_pair(c.get("size", (2, 2))),
                                   name=c.get("name"))

    def _map_zeropadding1d(self, c):
        p = c.get("padding", 1)
        if isinstance(p, (list, tuple)):
            pads = (int(p[0]), int(p[1] if len(p) > 1 else p[0]))
        else:
            pads = (int(p), int(p))
        return L.ZeroPadding1DLayer(padding=pads, name=c.get("name"))

    def _map_upsampling1d(self, c):
        size = c.get("size", c.get("length", 2))
        if isinstance(size, (list, tuple)):
            size = size[0]
        return L.Upsampling1DLayer(size=int(size), name=c.get("name"))

    # --- norm ---
    def _map_batchnormalization(self, c):
        return L.BatchNormalization(eps=float(c.get("epsilon", 1e-3)),
                                    decay=float(c.get("momentum", 0.99)),
                                    name=c.get("name"))

    def _map_layernormalization(self, c):
        # keras normalizes the LAST axis (features); our LayerNormalization
        # normalizes the feature axis in both [N,F] and [N,F,T] layouts, so
        # the semantics line up after the importer's layout conversion.
        # Saved configs carry either -1 or the POSITIVE last-axis index
        # (keras >= 2.4 serializes e.g. axis=[2] for 3-D input) — a single
        # axis is accepted as the feature axis; multi-axis LN is not
        # representable here.
        axis = c.get("axis", -1)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        if len(axes) != 1:
            raise ValueError(
                f"LayerNormalization over multiple axes {axis!r} "
                "unsupported (single feature axis only)")
        return L.LayerNormalization(eps=float(c.get("epsilon", 1e-3)),
                                    name=c.get("name"))

    # --- recurrent ---
    def _map_lstm(self, c):
        return L.LSTM(n_out=int(c.get("units", c.get("output_dim"))),
                      activation=_act(c.get("activation", "tanh")),
                      gate_activation=_act(c.get("recurrent_activation",
                                                 c.get("inner_activation",
                                                       "hard_sigmoid"))),
                      name=c.get("name"))

    def _map_simplernn(self, c):
        return L.SimpleRnn(n_out=int(c.get("units", c.get("output_dim"))),
                           activation=_act(c.get("activation", "tanh")),
                           name=c.get("name"))

    def _map_timedistributed(self, c):
        inner = c["layer"]
        mapped = self.map(inner["class_name"], _cfg(inner))
        mapped.name = c.get("name")
        return mapped


class KerasModelImport:
    """Entry points mirroring KerasModelImport.java."""

    @staticmethod
    def import_keras_sequential_model_and_weights(path: str,
                                                  enforce_training_config=False):
        """ref: importKerasSequentialModelAndWeights :74-87."""
        model = _KerasH5(path)
        try:
            return model.to_multi_layer_network()
        finally:
            model.close()

    @staticmethod
    def import_keras_model_and_weights(path: str, enforce_training_config=False):
        """ref: importKerasModelAndWeights :103-123. Sniffs Sequential vs
        Functional like KerasModel.java."""
        model = _KerasH5(path)
        try:
            if model.model_class == "Sequential":
                return model.to_multi_layer_network()
            return model.to_computation_graph()
        finally:
            model.close()


class _KerasH5:
    """HDF5 reader + config parser (ref: KerasModel.java + Hdf5Archive.java)."""

    def __init__(self, path: str):
        import h5py
        self.f = h5py.File(path, "r")
        raw = self.f.attrs.get("model_config")
        if raw is None:
            raise ValueError("HDF5 file has no model_config attribute "
                             "(weights-only files need a model config)")
        if isinstance(raw, bytes):
            raw = raw.decode()
        self.config = json.loads(raw)
        self.model_class = self.config.get("class_name", "Sequential")
        kv = self.f.attrs.get("keras_version", b"2")
        if isinstance(kv, bytes):
            kv = kv.decode()
        self.keras_version = 1 if str(kv).startswith("1") else 2
        self.mapper = KerasLayerMapper(self.keras_version)
        # channels_first models need different input interpretation + no
        # HWC→CHW flatten permutation (kernel layout is HWIO either way)
        self.channels_first = '"channels_first"' in json.dumps(self.config) \
            or '"dim_ordering": "th"' in json.dumps(self.config)

    def close(self) -> None:
        try:
            self.f.close()
        except Exception:
            pass

    # ------------------------------------------------------------------
    def _layer_configs(self) -> List[dict]:
        cfg = self.config["config"]
        if isinstance(cfg, dict):
            return cfg["layers"]
        return cfg  # keras 1 sequential: list directly

    def _input_type_from_shape(self, shape) -> InputType:
        """Keras per-example input shape → our InputType. Positional: a rank-3
        shape is an image (layout per data_format), rank-2 is (timesteps,
        features) — interior None (variable timesteps) is preserved, not
        stripped (ref: KerasInput.java shape handling)."""
        shape = list(shape)
        if len(shape) == 3:
            if self.channels_first:  # C, H, W
                c, h, w = shape
            else:                    # H, W, C (channels_last default)
                h, w, c = shape
            return InputType.convolutional(h, w, c)
        if len(shape) == 2:  # T, F — T may be None (variable length)
            t, f = shape
            return InputType.recurrent(int(f), t)
        return InputType.feed_forward(int(shape[0]))

    # ------------------------------------------------------------------
    def to_multi_layer_network(self):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        layer_cfgs = self._layer_configs()
        conf = MultiLayerConfiguration(updater=Sgd(0.01))
        input_type = None
        for lc in layer_cfgs:
            kcls = lc["class_name"]
            c = _cfg(lc)
            if input_type is None:
                shape = c.get("batch_input_shape") or c.get("input_shape")
                if shape is not None:
                    input_type = self._input_type_from_shape(
                        shape[1:] if shape[0] is None else shape)
            if kcls == "InputLayer":
                continue
            mapped = self.mapper.map(kcls, c)
            if mapped is None:  # Flatten/Reshape -> preprocessor inserted later
                continue
            conf.layers.append(mapped)
        conf.input_type = input_type
        net = MultiLayerNetwork(conf)
        net.init()
        self._import_sequential_weights(net)
        return net

    def to_computation_graph(self):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        cfg = self.config["config"]
        layer_cfgs = cfg["layers"]
        g_conf = ComputationGraphConfiguration(updater=Sgd(0.01))
        inbound: Dict[str, List[str]] = {}
        for lc in layer_cfgs:
            kcls = lc["class_name"]
            c = _cfg(lc)
            name = lc.get("name", c.get("name"))
            ib = lc.get("inbound_nodes") or []
            src: List[str] = []
            if ib:
                node = ib[0]
                if isinstance(node, list):
                    for conn in node:
                        src.append(conn[0] if isinstance(conn, list) else conn)
                elif isinstance(node, dict):
                    # keras 3 style: tensors serialized as dicts carrying
                    # {"config": {"keras_history": [layer_name, node, tensor]}}
                    src.extend(_keras3_history_names(node.get("args", [])))
            inbound[name] = src
            if kcls == "InputLayer":
                g_conf.network_inputs.append(name)
                shape = c.get("batch_input_shape") or c.get("batch_shape")
                if shape is not None:
                    g_conf.input_types[name] = self._input_type_from_shape(shape[1:])
                continue
            if kcls in ("Merge", "Concatenate"):
                g_conf.vertices[name] = MergeVertex()
                g_conf.vertex_inputs[name] = src
                continue
            if kcls == "Add":
                g_conf.vertices[name] = ElementWiseVertex(op="add")
                g_conf.vertex_inputs[name] = src
                continue
            if kcls in ("Flatten",):
                g_conf.vertices[name] = LayerVertex(
                    layer=L.ActivationLayer(activation="identity"),
                    preprocessor=CnnToFeedForwardPreProcessor())
                g_conf.vertex_inputs[name] = src
                continue
            mapped = self.mapper.map(kcls, c)
            if mapped is None:
                raise ValueError(
                    f"Keras layer {kcls} ('{name}') has no graph-vertex "
                    "mapping (shape adapters beyond Flatten are unsupported "
                    "in functional-model import)")
            g_conf.vertices[name] = LayerVertex(layer=mapped)
            g_conf.vertex_inputs[name] = src
        outs = cfg.get("output_layers", [])
        g_conf.network_outputs = [o[0] if isinstance(o, list) else o for o in outs]
        net = ComputationGraph(g_conf)
        net.init()
        self._import_graph_weights(net)
        return net

    # ------------------------------------------------------------------
    # weights (ref: KerasModelUtils.importWeights)
    # ------------------------------------------------------------------
    def _weight_group(self):
        return self.f["model_weights"] if "model_weights" in self.f else self.f

    def _layer_weights(self, lname: str) -> List[np.ndarray]:
        return [a for _, a in self._layer_weights_named(lname)]

    def _layer_weights_named(self, lname: str) -> List[Tuple[str, np.ndarray]]:
        """(weight_name, array) pairs in the file's declared order."""
        g = self._weight_group()
        if lname not in g:
            return []
        lg = g[lname]
        wn = lg.attrs.get("weight_names")
        pairs: List[Tuple[str, np.ndarray]] = []
        if wn is not None:
            for n in wn:
                n = n.decode() if isinstance(n, bytes) else n
                short = n.split("/", 1)[-1]
                arr = np.asarray(lg[short] if short in lg else lg[n])
                pairs.append((n, arr))
        else:
            def visit(vname, obj):
                import h5py
                if isinstance(obj, h5py.Dataset):
                    pairs.append((vname, np.asarray(obj)))
            lg.visititems(visit)
        return pairs

    def _assign(self, layer: L.LayerConf, params: dict,
                weights: List[np.ndarray],
                names: Optional[List[str]] = None):
        """Map Keras weight arrays into our named params (layout conversions
        documented in the module docstring). `names` (parallel to `weights`)
        disambiguates optional slots like BN gamma/beta."""
        import jax.numpy as jnp
        if isinstance(layer, L.ConvolutionLayer) and not isinstance(
                layer, L.Convolution1DLayer):
            k = weights[0]
            if k.ndim == 4:
                if k.shape[:2] == tuple(params["W"].shape[2:]):  # HWIO (keras2)
                    k = np.transpose(k, (3, 2, 0, 1))
                # else assume already OIHW (keras1 th)
            params["W"] = jnp.asarray(k)
            if len(weights) > 1 and "b" in params:
                params["b"] = jnp.asarray(weights[1])
        elif isinstance(layer, L.Convolution1DLayer):
            k = weights[0]  # keras: [kw, in, out] -> ours [out, in, kw]
            if k.ndim == 3:
                k = np.transpose(k, (2, 1, 0))
            params["W"] = jnp.asarray(k)
            if len(weights) > 1 and "b" in params:
                params["b"] = jnp.asarray(weights[1])
        elif isinstance(layer, L.BatchNormalization):
            # keras order: gamma, beta, moving_mean, moving_var — but
            # scale=False / center=False omit gamma / beta, so map by the
            # declared weight names when available (names parallel `weights`)
            slots = {"gamma": "gamma", "beta": "beta",
                     "moving_mean": "__mean__", "moving_variance": "__var__",
                     "running_mean": "__mean__", "running_std": "__var__"}
            assigned = False
            if names and len(names) == len(weights):
                for n, w in zip(names, weights):
                    base = n.rsplit("/", 1)[-1].split(":")[0]
                    if base in slots:
                        params[slots[base]] = jnp.asarray(w)
                        assigned = True
            if not assigned:
                if len(weights) != 4:
                    raise ValueError(
                        "BatchNormalization with %d weight arrays and no "
                        "recognizable weight names — cannot infer layout"
                        % len(weights))
                params["gamma"] = jnp.asarray(weights[0])
                params["beta"] = jnp.asarray(weights[1])
                params["__mean__"] = jnp.asarray(weights[2])
                params["__var__"] = jnp.asarray(weights[3])
        elif isinstance(layer, L.LayerNormalization):
            slots = {"gamma": "gamma", "beta": "beta"}
            assigned = False
            if names and len(names) == len(weights):
                for n, w in zip(names, weights):
                    base = n.rsplit("/", 1)[-1].split(":")[0]
                    if base in slots:
                        params[slots[base]] = jnp.asarray(w)
                        assigned = True
            if not assigned and len(weights) >= 2:
                params["gamma"] = jnp.asarray(weights[0])
                params["beta"] = jnp.asarray(weights[1])
            elif not assigned and len(weights) == 1:
                params["gamma"] = jnp.asarray(weights[0])
        elif isinstance(layer, L.LSTM):
            # keras: kernel [in,4H], recurrent_kernel [H,4H], bias [4H]
            # gate order (i,f,c,o) == ours: direct copy
            params["W"] = jnp.asarray(weights[0])
            params["RW"] = jnp.asarray(weights[1])
            if len(weights) > 2:
                params["b"] = jnp.asarray(weights[2])
        elif isinstance(layer, L.SimpleRnn):
            params["W"] = jnp.asarray(weights[0])
            params["RW"] = jnp.asarray(weights[1])
            if len(weights) > 2:
                params["b"] = jnp.asarray(weights[2])
        elif isinstance(layer, (L.DenseLayer, L.OutputLayer, L.EmbeddingLayer)):
            params["W"] = jnp.asarray(weights[0])
            if len(weights) > 1 and "b" in params:
                params["b"] = jnp.asarray(weights[1])
        return params

    def _import_sequential_weights(self, net):
        layer_cfgs = [lc for lc in self._layer_configs()
                      if lc["class_name"] != "InputLayer"]
        li = 0
        for lc in layer_cfgs:
            kcls = lc["class_name"]
            c = _cfg(lc)
            if kcls in ("Flatten", "Reshape"):
                continue
            layer = net.layers[li]
            lname = lc.get("name", c.get("name"))
            named = self._layer_weights_named(lname)
            wnames = [n for n, _ in named]
            weights = [a for _, a in named]
            if weights:
                # Dense directly after a conv flatten: Keras flattened HWC
                # (channels_last) but our CnnToFeedForward flattens CHW —
                # permute kernel rows (ref: KerasModelUtils / the reference's
                # preprocessor-aware weight mapping; SURVEY §7 hard parts)
                # channels_first models already flatten CHW like we do
                pre = net.conf.preprocessors.get(li)
                if not self.channels_first and \
                        isinstance(layer, (L.DenseLayer, L.OutputLayer)) and \
                        isinstance(pre, CnnToFeedForwardPreProcessor) and \
                        pre.height and weights[0].ndim == 2:
                    h_, w_, c_ = pre.height, pre.width, pre.channels
                    k = weights[0].reshape(h_, w_, c_, -1)
                    weights = [k.transpose(2, 0, 1, 3).reshape(h_ * w_ * c_, -1)
                               ] + list(weights[1:])
                p = dict(net.params[str(li)])
                p = self._assign(layer, p, weights, wnames)
                mean = p.pop("__mean__", None)
                var = p.pop("__var__", None)
                net.params[str(li)] = p
                if mean is not None:
                    net.state[str(li)] = {"mean": mean, "var": var}
            li += 1

    def _import_graph_weights(self, net):
        for name, v in net.conf.vertices.items():
            if not isinstance(v, LayerVertex) or v.layer is None:
                continue
            named = self._layer_weights_named(name)
            if not named:
                continue
            weights = [a for _, a in named]
            p = dict(net.params[name])
            p = self._assign(v.layer, p, weights, [n for n, _ in named])
            mean = p.pop("__mean__", None)
            var = p.pop("__var__", None)
            net.params[name] = p
            if mean is not None:
                net.state[name] = {"mean": mean, "var": var}
