"""TextGenerationLSTM (ref: zoo/model/TextGenerationLSTM.java — stacked
GravesLSTM character model with softmax-over-vocab output, tBPTT)."""

from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer
from deeplearning4j_tpu.nn.updater import RmsProp
from deeplearning4j_tpu.zoo.base import ZooModel, register_model


@register_model
class TextGenerationLSTM(ZooModel):
    def __init__(self, vocab_size: int = 77, seed: int = 12345,
                 hidden: int = 256, layers: int = 2, max_length: int = 40, **kw):
        super().__init__(vocab_size, seed, **kw)
        self.vocab_size = vocab_size
        self.hidden = hidden
        self.layers = layers
        self.max_length = max_length

    def conf(self):
        b = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(self.kwargs.get("updater", RmsProp(1e-2)))
             .weight_init("xavier")
             .gradient_normalization("clipelementwiseabsolutevalue", 1.0)
             .list())
        for _ in range(self.layers):
            b.layer(GravesLSTM(n_out=self.hidden, activation="tanh"))
        b.layer(RnnOutputLayer(n_out=self.vocab_size, loss="mcxent",
                               activation="softmax"))
        return (b.set_input_type(InputType.recurrent(self.vocab_size,
                                                     self.max_length))
                .tbptt(self.max_length)
                .build())

    def sample_stream(self, net, seed_ids, steps: int,
                      vocab_size: int = None,
                      rng=None, temperature: float = 1.0,
                      prime_padded: bool = False,
                      top_k: int = None, top_p: float = None,
                      stop_tokens=()):
        """Temperature sampling through the stored-state rnnTimeStep path
        (the reference's character-generation loop; shared implementation
        util/decoding.sample_stream; unbounded length). `prime_padded=True`
        primes the prompt in ONE left-padded dispatch (masked pad steps
        pass h/c through unchanged); `top_k`/`top_p` filter each draw."""
        from deeplearning4j_tpu.util.decoding import sample_stream
        return sample_stream(net, seed_ids, steps,
                             vocab_size or self.vocab_size,
                             temperature=temperature, rng=rng,
                             max_length=None, prime_padded=prime_padded,
                             top_k=top_k, top_p=top_p,
                             stop_tokens=stop_tokens)

    def sample_stream_batch(self, net, prompts, steps: int,
                            vocab_size: int = None, rng=None,
                            temperature: float = 1.0,
                            top_k: int = None, top_p: float = None,
                            stop_tokens=()):
        """Decode a batch of prompts in lockstep (shared implementation
        util/decoding.sample_stream_batch) — mixed lengths are exact for
        LSTMs: masked left-pad steps pass h/c through unchanged."""
        from deeplearning4j_tpu.util.decoding import sample_stream_batch
        return sample_stream_batch(net, prompts, steps,
                                   vocab_size or self.vocab_size,
                                   temperature=temperature, rng=rng,
                                   max_length=None,
                                   top_k=top_k, top_p=top_p,
                                   stop_tokens=stop_tokens)

    def beam_search(self, net, seed_ids, steps: int, beam_width: int = 4,
                    vocab_size: int = None, prime_padded: bool = False,
                    stop_tokens=()):
        """Beam-search decoding over the stored-state rnnTimeStep path
        (shared implementation: util/decoding.beam_search; LSTM h/c is
        the carried state). Generation length is unbounded — recurrent
        state has no positional capacity."""
        from deeplearning4j_tpu.util.decoding import beam_search
        return beam_search(net, seed_ids, steps,
                           vocab_size or self.vocab_size,
                           beam_width=beam_width, max_length=None,
                           prime_padded=prime_padded,
                           stop_tokens=stop_tokens)
