"""LeNet (ref: deeplearning4j-zoo/.../zoo/model/LeNet.java — conv5x5(20) →
maxpool2 → conv5x5(50) → maxpool2 → dense(500,relu) → softmax). The first
BASELINE config (LeNet MNIST MultiLayerNetwork)."""

from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer, DenseLayer,
                                               OutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.nn.updater import Adam
from deeplearning4j_tpu.zoo.base import ZooModel, register_model


@register_model
class LeNet(ZooModel):
    def __init__(self, num_classes: int = 10, seed: int = 12345,
                 height: int = 28, width: int = 28, channels: int = 1, **kw):
        super().__init__(num_classes, seed, **kw)
        self.height, self.width, self.channels = height, width, channels

    def conf(self):
        return (NeuralNetConfiguration.Builder()
                .seed(self.seed)
                .updater(self.kwargs.get("updater", Adam(1e-3)))
                .weight_init("xavier")
                .list()
                .layer(ConvolutionLayer(n_out=20, kernel=(5, 5), stride=(1, 1),
                                        activation="identity"))
                .layer(SubsamplingLayer(pooling_type="max", kernel=(2, 2),
                                        stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=50, kernel=(5, 5), stride=(1, 1),
                                        activation="identity"))
                .layer(SubsamplingLayer(pooling_type="max", kernel=(2, 2),
                                        stride=(2, 2)))
                .layer(DenseLayer(n_out=500, activation="relu"))
                .layer(OutputLayer(n_out=self.num_classes, loss="mcxent",
                                   activation="softmax"))
                .set_input_type(InputType.convolutional(self.height, self.width,
                                                        self.channels))
                .build())
