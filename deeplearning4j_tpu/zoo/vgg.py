"""VGG16 / VGG19 (ref: zoo/model/VGG16.java, VGG19.java — 3x3 conv blocks
with 2x2 max pools, two 4096 dense layers, softmax). BASELINE config[1]."""

from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer, DenseLayer,
                                               OutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.nn.updater import Nesterovs
from deeplearning4j_tpu.zoo.base import ZooModel, register_model

VGG16_BLOCKS = [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]
VGG19_BLOCKS = [(2, 64), (2, 128), (4, 256), (4, 512), (4, 512)]


class _VGG(ZooModel):
    blocks = VGG16_BLOCKS

    def __init__(self, num_classes: int = 1000, seed: int = 12345,
                 height: int = 224, width: int = 224, channels: int = 3, **kw):
        super().__init__(num_classes, seed, **kw)
        self.height, self.width, self.channels = height, width, channels

    def conf(self):
        b = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(self.kwargs.get("updater", Nesterovs(1e-2, momentum=0.9)))
             .weight_init("relu")
             .list())
        for n_convs, ch in self.blocks:
            for _ in range(n_convs):
                b.layer(ConvolutionLayer(n_out=ch, kernel=(3, 3), stride=(1, 1),
                                         padding=(1, 1), activation="relu"))
            b.layer(SubsamplingLayer(pooling_type="max", kernel=(2, 2),
                                     stride=(2, 2)))
        b.layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
        b.layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
        b.layer(OutputLayer(n_out=self.num_classes, loss="mcxent",
                            activation="softmax"))
        return (b.set_input_type(InputType.convolutional(
            self.height, self.width, self.channels)).build())


@register_model
class VGG16(_VGG):
    blocks = VGG16_BLOCKS


@register_model
class VGG19(_VGG):
    blocks = VGG19_BLOCKS
