"""GoogLeNet / Inception-v1 (ref: zoo/model/GoogLeNet.java — inception
modules with 1x1/3x3/5x5 branches + pool branch merged on depth)."""

from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph_conf import MergeVertex
from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer, DenseLayer,
                                               GlobalPoolingLayer,
                                               LocalResponseNormalization,
                                               OutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.nn.updater import Nesterovs
from deeplearning4j_tpu.zoo.base import ZooModel, register_model


@register_model
class GoogLeNet(ZooModel):
    def __init__(self, num_classes: int = 1000, seed: int = 12345,
                 height: int = 224, width: int = 224, channels: int = 3, **kw):
        super().__init__(num_classes, seed, **kw)
        self.height, self.width, self.channels = height, width, channels

    def _inception(self, g, name, inp, c1, c3r, c3, c5r, c5, pp):
        """Inception module (ref: GoogLeNet.java inception builder)."""
        g.add_layer(f"{name}_1x1",
                    ConvolutionLayer(n_out=c1, kernel=(1, 1), activation="relu"),
                    inp)
        g.add_layer(f"{name}_3x3r",
                    ConvolutionLayer(n_out=c3r, kernel=(1, 1), activation="relu"),
                    inp)
        g.add_layer(f"{name}_3x3",
                    ConvolutionLayer(n_out=c3, kernel=(3, 3), padding=(1, 1),
                                     activation="relu"), f"{name}_3x3r")
        g.add_layer(f"{name}_5x5r",
                    ConvolutionLayer(n_out=c5r, kernel=(1, 1), activation="relu"),
                    inp)
        g.add_layer(f"{name}_5x5",
                    ConvolutionLayer(n_out=c5, kernel=(5, 5), padding=(2, 2),
                                     activation="relu"), f"{name}_5x5r")
        g.add_layer(f"{name}_pool",
                    SubsamplingLayer(pooling_type="max", kernel=(3, 3),
                                     stride=(1, 1), padding=(1, 1)), inp)
        g.add_layer(f"{name}_poolproj",
                    ConvolutionLayer(n_out=pp, kernel=(1, 1), activation="relu"),
                    f"{name}_pool")
        g.add_vertex(f"{name}", MergeVertex(), f"{name}_1x1", f"{name}_3x3",
                     f"{name}_5x5", f"{name}_poolproj")
        return name

    def conf(self):
        g = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(self.kwargs.get("updater", Nesterovs(1e-2, momentum=0.9)))
             .weight_init("relu")
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(self.height, self.width,
                                                      self.channels)))
        g.add_layer("c1", ConvolutionLayer(n_out=64, kernel=(7, 7), stride=(2, 2),
                                           padding=(3, 3), activation="relu"),
                    "input")
        g.add_layer("p1", SubsamplingLayer(pooling_type="max", kernel=(3, 3),
                                           stride=(2, 2), padding=(1, 1)), "c1")
        g.add_layer("lrn1", LocalResponseNormalization(), "p1")
        g.add_layer("c2r", ConvolutionLayer(n_out=64, kernel=(1, 1),
                                            activation="relu"), "lrn1")
        g.add_layer("c2", ConvolutionLayer(n_out=192, kernel=(3, 3),
                                           padding=(1, 1), activation="relu"),
                    "c2r")
        g.add_layer("lrn2", LocalResponseNormalization(), "c2")
        g.add_layer("p2", SubsamplingLayer(pooling_type="max", kernel=(3, 3),
                                           stride=(2, 2), padding=(1, 1)), "lrn2")
        x = self._inception(g, "i3a", "p2", 64, 96, 128, 16, 32, 32)
        x = self._inception(g, "i3b", x, 128, 128, 192, 32, 96, 64)
        g.add_layer("p3", SubsamplingLayer(pooling_type="max", kernel=(3, 3),
                                           stride=(2, 2), padding=(1, 1)), x)
        x = self._inception(g, "i4a", "p3", 192, 96, 208, 16, 48, 64)
        x = self._inception(g, "i4b", x, 160, 112, 224, 24, 64, 64)
        x = self._inception(g, "i4c", x, 128, 128, 256, 24, 64, 64)
        x = self._inception(g, "i4d", x, 112, 144, 288, 32, 64, 64)
        x = self._inception(g, "i4e", x, 256, 160, 320, 32, 128, 128)
        g.add_layer("p4", SubsamplingLayer(pooling_type="max", kernel=(3, 3),
                                           stride=(2, 2), padding=(1, 1)), x)
        x = self._inception(g, "i5a", "p4", 256, 160, 320, 32, 128, 128)
        x = self._inception(g, "i5b", x, 384, 192, 384, 48, 128, 128)
        g.add_layer("gap", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("output",
                    OutputLayer(n_out=self.num_classes, loss="mcxent",
                                activation="softmax", dropout=0.6), "gap")
        return g.set_outputs("output").build()
