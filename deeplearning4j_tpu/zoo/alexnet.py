"""AlexNet (ref: zoo/model/AlexNet.java — the 2-pool LRN variant: conv11x11/4
→ LRN → pool → conv5x5 → LRN → pool → 3×conv3x3 → pool → 2×dense4096 w/
dropout → softmax)."""

from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer, DenseLayer,
                                               LocalResponseNormalization,
                                               OutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.nn.updater import Nesterovs
from deeplearning4j_tpu.zoo.base import ZooModel, register_model


@register_model
class AlexNet(ZooModel):
    def __init__(self, num_classes: int = 1000, seed: int = 12345,
                 height: int = 224, width: int = 224, channels: int = 3, **kw):
        super().__init__(num_classes, seed, **kw)
        self.height, self.width, self.channels = height, width, channels

    def conf(self):
        return (NeuralNetConfiguration.Builder()
                .seed(self.seed)
                .updater(self.kwargs.get("updater",
                                         Nesterovs(1e-2, momentum=0.9)))
                .weight_init("relu")
                .list()
                .layer(ConvolutionLayer(n_out=96, kernel=(11, 11), stride=(4, 4),
                                        padding=(3, 3), activation="relu"))
                .layer(LocalResponseNormalization())
                .layer(SubsamplingLayer(pooling_type="max", kernel=(3, 3),
                                        stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=256, kernel=(5, 5), stride=(1, 1),
                                        padding=(2, 2), activation="relu"))
                .layer(LocalResponseNormalization())
                .layer(SubsamplingLayer(pooling_type="max", kernel=(3, 3),
                                        stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=384, kernel=(3, 3), padding=(1, 1),
                                        activation="relu"))
                .layer(ConvolutionLayer(n_out=384, kernel=(3, 3), padding=(1, 1),
                                        activation="relu"))
                .layer(ConvolutionLayer(n_out=256, kernel=(3, 3), padding=(1, 1),
                                        activation="relu"))
                .layer(SubsamplingLayer(pooling_type="max", kernel=(3, 3),
                                        stride=(2, 2)))
                .layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
                .layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
                .layer(OutputLayer(n_out=self.num_classes, loss="mcxent",
                                   activation="softmax"))
                .set_input_type(InputType.convolutional(self.height, self.width,
                                                        self.channels))
                .build())
