"""ResNet50 (ref: zoo/model/ResNet50.java — bottleneck residual blocks as a
ComputationGraph; conv/identity blocks with BN, ElementWiseVertex(Add) skip
connections). The BASELINE north-star model.

TPU notes: the whole graph compiles to one XLA program; BN+ReLU fuse into
the convs; on real runs prefer bf16 params via the network dtype (fp32
accumulation is XLA's default for bf16 convs on MXU).
"""

from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph_conf import ElementWiseVertex
from deeplearning4j_tpu.nn.conf.layers import (ActivationLayer,
                                               BatchNormalization,
                                               ConvolutionLayer, DenseLayer,
                                               GlobalPoolingLayer, OutputLayer,
                                               SubsamplingLayer,
                                               ZeroPaddingLayer)
from deeplearning4j_tpu.nn.updater import Nesterovs
from deeplearning4j_tpu.zoo.base import ZooModel, register_model


@register_model
class ResNet50(ZooModel):
    def __init__(self, num_classes: int = 1000, seed: int = 12345,
                 height: int = 224, width: int = 224, channels: int = 3, **kw):
        # fused bn→relu→1×1-conv execution for the bottleneck chains
        # (nn/layers/fused.py) is OPT-IN: on a real v5e the fused plan
        # measured ~2.0-2.1k img/s vs ~2.6k unfused (B=128, bf16) — XLA's
        # own fusion of the unfused graph beats the hand prologue/kernel,
        # whose pallas_call boundary blocks cross-op fusion (PERF.md r3).
        # Equivalence stays pinned by tests/test_fused.py; pass fuse=True
        # to enable. The production switch is execution_plan=
        # "auto"|"fused"|"xla" (tuning/plan.py): "fused" runs the full
        # bottleneck kernel cascade (nn/layers/bottleneck.py) + the
        # store-gated space-to-depth stem, "auto" resolves per shape
        # from the measured kernel-crossover store.
        kw.setdefault("fuse", False)
        super().__init__(num_classes, seed, **kw)
        self.height, self.width, self.channels = height, width, channels

    # -- block builders (ref: ResNet50.java convBlock/identityBlock) --------
    def _conv_bn(self, g, name, n_out, kernel, stride, pad, inp,
                 activation="relu"):
        g.add_layer(f"{name}_conv",
                    ConvolutionLayer(n_out=n_out, kernel=kernel, stride=stride,
                                     padding=pad, activation="identity",
                                     has_bias=False),
                    inp)
        g.add_layer(f"{name}_bn", BatchNormalization(), f"{name}_conv")
        if activation:
            g.add_layer(f"{name}_act", ActivationLayer(activation=activation),
                        f"{name}_bn")
            return f"{name}_act"
        return f"{name}_bn"

    def _bottleneck(self, g, name, inp, filters, stride=(1, 1), downsample=False):
        f1, f2, f3 = filters
        x = self._conv_bn(g, f"{name}_a", f1, (1, 1), stride, (0, 0), inp)
        x = self._conv_bn(g, f"{name}_b", f2, (3, 3), (1, 1), (1, 1), x)
        x = self._conv_bn(g, f"{name}_c", f3, (1, 1), (1, 1), (0, 0), x,
                          activation=None)
        if downsample:
            skip = self._conv_bn(g, f"{name}_skip", f3, (1, 1), stride, (0, 0),
                                 inp, activation=None)
        else:
            skip = inp
        g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), x, skip)
        g.add_layer(f"{name}_out", ActivationLayer(activation="relu"),
                    f"{name}_add")
        return f"{name}_out"

    def conf(self):
        g = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(self.kwargs.get("updater", Nesterovs(1e-1, momentum=0.9)))
             .weight_init("relu")
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(self.height, self.width,
                                                      self.channels)))
        # stem: 7x7/2 conv + BN + relu + 3x3/2 maxpool (ref stem)
        g.add_layer("stem_pad", ZeroPaddingLayer(padding=(3, 3, 3, 3)), "input")
        x = self._conv_bn(g, "stem", 64, (7, 7), (2, 2), (0, 0), "stem_pad")
        g.add_layer("stem_pool",
                    SubsamplingLayer(pooling_type="max", kernel=(3, 3),
                                     stride=(2, 2), padding=(1, 1)), x)
        x = "stem_pool"
        # stages (ref: 3,4,6,3 bottlenecks)
        stages = [
            ("s2", [64, 64, 256], 3, (1, 1)),
            ("s3", [128, 128, 512], 4, (2, 2)),
            ("s4", [256, 256, 1024], 6, (2, 2)),
            ("s5", [512, 512, 2048], 3, (2, 2)),
        ]
        for sname, filters, reps, stride in stages:
            x = self._bottleneck(g, f"{sname}b0", x, filters, stride=stride,
                                 downsample=True)
            for r in range(1, reps):
                x = self._bottleneck(g, f"{sname}b{r}", x, filters)
        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("output",
                    OutputLayer(n_out=self.num_classes, loss="mcxent",
                                activation="softmax"), "avgpool")
        return g.set_outputs("output").build()
