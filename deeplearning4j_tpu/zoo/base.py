"""ZooModel base.

TPU-native equivalent of zoo/ZooModel.java:28-81: `init()` builds the fresh
network; `init_pretrained()` downloads a checkpoint zip with checksum
validation then restores (ref :52-81 pretrainedUrl + ModelSerializer.restore).
In a zero-egress environment the download path raises a clear error; local
checkpoint paths are always accepted.
"""

from __future__ import annotations

import hashlib
import os
import urllib.request
from typing import Dict, Optional, Type

MODEL_REGISTRY: Dict[str, Type["ZooModel"]] = {}


def register_model(cls):
    MODEL_REGISTRY[cls.__name__.lower()] = cls
    return cls


def get_model(name: str) -> Type["ZooModel"]:
    return MODEL_REGISTRY[name.lower()]


class ZooModel:
    """Base for zoo models (ref: InstantiableModel)."""

    #: override: url + sha256 per pretrained flavor (ref: pretrainedUrl /
    #: pretrainedChecksum in each zoo model)
    pretrained: Dict[str, Dict[str, str]] = {}

    def __init__(self, num_classes: int = 1000, seed: int = 12345, **kwargs):
        self.num_classes = num_classes
        self.seed = seed
        self.kwargs = kwargs

    def conf(self):
        raise NotImplementedError

    def init(self):
        """Build + initialize the network (ref: ZooModel.init()).

        Pass `data_format="NHWC"` to the model constructor to run the CNN
        stack in the TPU-fast internal layout (public API stays NCHW).
        Pass `execution_plan="auto"|"fused"|"xla"` to resolve the fused
        training-kernel plan at build time (tuning/plan.py — the same
        seam `net.fit(..., execution_plan=...)` resolves per fit), so a
        zoo model and a bench model run the SAME code path."""
        conf = self.conf()
        fmt = self.kwargs.get("data_format")
        if fmt:
            conf.use_cnn_data_format(fmt)
        from deeplearning4j_tpu.nn.conf.network import (
            ComputationGraphConfiguration, MultiLayerConfiguration)
        if isinstance(conf, MultiLayerConfiguration):
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
            if self.kwargs.get("fuse", False):
                raise ValueError(
                    f"{type(self).__name__}: fuse=True needs a "
                    "ComputationGraph model (the bn→act→conv fusion plan "
                    "is a graph execution feature)")
            return self._maybe_fuse(MultiLayerNetwork(conf).init())
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        return self._maybe_fuse(ComputationGraph(conf).init())

    def _maybe_fuse(self, net):
        """Apply the model's fuse/execution_plan kwargs to a freshly
        built/restored net (restore paths must honor them too). fuse=True
        selects the bn→act→conv plan, fuse="bottleneck" the full
        fused-bottleneck plan (nn/layers/bottleneck.py) — the legacy
        direct switches. execution_plan goes through the plan-resolution
        seam (tuning/plan.py) instead: "fused" engages every eligible
        chain, "auto" resolves per shape from the measured crossover
        store, "xla" pins the unfused graph."""
        level = self.kwargs.get("fuse", False)
        plan = self.kwargs.get("execution_plan")
        if level and plan:
            raise ValueError(
                f"{type(self).__name__}: fuse= and execution_plan= are "
                "mutually exclusive (execution_plan supersedes fuse)")
        if level:
            if not hasattr(net, "set_fusion"):
                raise ValueError(
                    f"{type(self).__name__}: fuse={level!r} needs a "
                    "ComputationGraph model (restored checkpoint is a "
                    f"{type(net).__name__})")
            net.set_fusion(level)
        elif plan:
            from deeplearning4j_tpu.tuning.plan import apply_execution_plan
            apply_execution_plan(net, plan)
        return net

    def init_pretrained(self, flavor: str = "imagenet",
                        cache_dir: Optional[str] = None,
                        local_path: Optional[str] = None):
        """Load pretrained weights (ref: ZooModel.initPretrained :40-81).

        Accepts both our native checkpoint zips and DL4J-format zips
        (configuration.json + coefficients.bin), sniffed by content. A
        pretrained spec may carry "url" (downloaded + checksummed, ref
        ZooModel.java:52-81) or "file" (a locally generated fixture)."""
        if local_path:
            return self._maybe_fuse(_restore_any(local_path))
        if flavor not in self.pretrained:
            raise ValueError(f"{type(self).__name__} has no pretrained '{flavor}'")
        spec = self.pretrained[flavor]
        if "file" in spec:
            fname = spec["file"]
        else:
            cache_dir = cache_dir or os.path.expanduser("~/.dl4jtpu/models")
            os.makedirs(cache_dir, exist_ok=True)
            fname = os.path.join(cache_dir,
                                 f"{type(self).__name__.lower()}_{flavor}.zip")
            if not os.path.exists(fname):
                urllib.request.urlretrieve(spec["url"], fname)  # zero-egress envs raise here
        if "sha256" in spec:
            h = hashlib.sha256(open(fname, "rb").read()).hexdigest()
            if h != spec["sha256"]:
                if "url" in spec:
                    os.remove(fname)  # our cached download — refetch next call
                raise IOError(f"checksum mismatch for {fname}")
        return self._maybe_fuse(_restore_any(fname))

    def save_pretrained_fixture(self, path: str,
                                flavor: str = "local") -> Dict[str, str]:
        """Initialize this model, write its checkpoint to `path`, and register
        it as a loadable pretrained flavor (checksummed like the reference's
        download path). Stands in for hosted checkpoint zips in a zero-egress
        environment so the restore+inference path is exercised end to end."""
        net = self.init()
        from deeplearning4j_tpu.util.model_serializer import write_model
        write_model(net, path)
        sha = hashlib.sha256(open(path, "rb").read()).hexdigest()
        spec = {"file": path, "sha256": sha}
        # per-instance registration (class attr stays the shared default)
        self.pretrained = {**self.pretrained, flavor: spec}
        return spec


def _restore_any(path: str):
    """Sniff checkpoint flavor: DL4J zip (coefficients.bin) vs native."""
    import zipfile as _zf
    with _zf.ZipFile(path) as z:
        names = set(z.namelist())
    if "coefficients.bin" in names:
        from deeplearning4j_tpu.modelimport.dl4j import restore_model
        return restore_model(path)
    from deeplearning4j_tpu.util.model_serializer import restore_model
    return restore_model(path)
