"""TextGenerationTransformer: a decoder-only character/token LM.

Post-parity zoo model (the 2017 reference's sequence model is
TextGenerationLSTM; this is its modern long-context counterpart built
from the same config DSL): pre-LN transformer blocks —
LN → causal multi-head SelfAttentionLayer → residual add →
LN → position-wise FFN (Convolution1D kernel=1) → residual add —
over RNN-format [N, V, T] one-hot input, RnnOutputLayer softmax head.
The attention core is the flash-style blockwise kernel, so contexts of
tens of thousands of tokens train on a single chip; sequence sharding
over a mesh uses ring/Ulysses attention on the same math
(parallel/sequence.py).
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.nn.conf.graph_conf import ElementWiseVertex
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    Convolution1DLayer, LayerNormalization, PositionalEmbeddingLayer,
    RnnOutputLayer, SelfAttentionLayer,
)
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.updater import Adam
from deeplearning4j_tpu.zoo.base import ZooModel, register_model


from deeplearning4j_tpu.util.decoding import draw as _draw


@register_model
class TextGenerationTransformer(ZooModel):
    def __init__(self, vocab_size: int = 128, seed: int = 12345,
                 embed_dim: int = 256, n_heads: int = 8, n_layers: int = 4,
                 ffn_mult: int = 4, max_length: int = 1024,
                 block_size: int = 512, positional: str = "learned",
                 n_kv_heads=None, window=None, **kw):
        super().__init__(vocab_size, seed, **kw)
        if embed_dim % n_heads:
            raise ValueError("embed_dim must divide by n_heads")
        self.vocab_size = vocab_size
        self.embed_dim = embed_dim
        self.n_heads = n_heads
        self.n_layers = n_layers
        self.ffn_mult = ffn_mult
        self.max_length = max_length
        self.block_size = block_size
        if positional not in ("learned", "rope"):
            raise ValueError(f"unknown positional {positional!r}")
        self.positional = positional
        self.n_kv_heads = n_kv_heads
        self.window = window

    def conf(self):
        E = self.embed_dim
        g = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(self.kwargs.get("updater", Adam(3e-4)))
             .weight_init("xavier")
             .graph_builder()
             .add_inputs("in")
             .set_input_types(InputType.recurrent(self.vocab_size,
                                                  self.max_length)))
        # token projection: one-hot [N,V,T] -> [N,E,T] (kernel-1 conv =
        # position-wise embedding matmul)
        g.add_layer("embed", Convolution1DLayer(
            n_out=E, kernel=1, convolution_mode="same",
            activation="identity"), "in")
        if self.positional == "learned":
            g.add_layer("pos", PositionalEmbeddingLayer(
                max_length=self.max_length), "embed")
            prev = "pos"
        else:  # rope: positions enter inside attention, no table
            prev = "embed"
        for i in range(self.n_layers):
            g.add_layer(f"ln{i}a", LayerNormalization(), prev)
            g.add_layer(f"attn{i}", SelfAttentionLayer(
                n_out=E, n_heads=self.n_heads, causal=True,
                block_size=self.block_size, activation="identity",
                cache_length=self.max_length,
                n_kv_heads=self.n_kv_heads, window=self.window,
                rope=self.positional == "rope"), f"ln{i}a")
            g.add_vertex(f"res{i}a", ElementWiseVertex(op="add"),
                         prev, f"attn{i}")
            g.add_layer(f"ln{i}b", LayerNormalization(), f"res{i}a")
            g.add_layer(f"ffn{i}a", Convolution1DLayer(
                n_out=E * self.ffn_mult, kernel=1,
                convolution_mode="same", activation="gelu"), f"ln{i}b")
            g.add_layer(f"ffn{i}b", Convolution1DLayer(
                n_out=E, kernel=1, convolution_mode="same",
                activation="identity"), f"ffn{i}a")
            g.add_vertex(f"res{i}b", ElementWiseVertex(op="add"),
                         f"res{i}a", f"ffn{i}b")
            prev = f"res{i}b"
        g.add_layer("ln_f", LayerNormalization(), prev)
        g.add_layer("out", RnnOutputLayer(
            n_out=self.vocab_size, loss="mcxent", activation="softmax"),
            "ln_f")
        return g.set_outputs("out").build()

    # -- convenience: sampling (ref TextGenerationLSTM usage pattern) ------
    def sample(self, net, seed_ids, steps: int, vocab_size: int = None,
               rng: np.random.Generator = None, temperature: float = 1.0,
               top_k: int = None, top_p: float = None):
        """Autoregressive sampling from a trained net. The input is padded
        to max_length so XLA compiles ONE shape (causal attention + the
        per-position layers make trailing zero padding inert for the
        position being read). `top_k`/`top_p` filter each draw exactly
        as in sample_stream."""
        V = vocab_size or self.vocab_size
        L = self.max_length
        rng = rng or np.random.default_rng(0)
        ids = list(seed_ids)
        x = np.zeros((1, V, L), np.float32)
        x[0, ids, np.arange(len(ids))] = 1.0
        for _ in range(steps):
            pos = len(ids) - 1
            if pos + 1 >= L:
                break
            out = net.output(x)
            probs = np.asarray(out[0] if isinstance(out, (list, tuple))
                               else out)[0, :, pos]
            nxt = _draw(probs, temperature, rng, top_k=top_k, top_p=top_p)
            ids.append(nxt)
            x[0, nxt, len(ids) - 1] = 1.0
        return ids

    def sample_stream(self, net, seed_ids, steps: int,
                      vocab_size: int = None,
                      rng: np.random.Generator = None,
                      temperature: float = 1.0,
                      prime_padded: bool = False,
                      top_k: int = None, top_p: float = None,
                      stop_tokens=()):
        """KV-cache incremental decoding (shared implementation:
        util/decoding.sample_stream) — O(steps) single-position forwards
        instead of the padded full-forward-per-token of `sample`, with an
        identical sampling distribution (tested). `prime_padded=True`
        primes the prompt in ONE left-padded dispatch; `top_k`/`top_p`
        filter each draw (top_k=1 is greedy)."""
        from deeplearning4j_tpu.util.decoding import sample_stream
        return sample_stream(net, seed_ids, steps,
                             vocab_size or self.vocab_size,
                             temperature=temperature, rng=rng,
                             max_length=self.max_length,
                             prime_padded=prime_padded,
                             top_k=top_k, top_p=top_p,
                             stop_tokens=stop_tokens)

    def sample_stream_batch(self, net, prompts, steps: int,
                            vocab_size: int = None,
                            rng: np.random.Generator = None,
                            temperature: float = 1.0,
                            top_k: int = None, top_p: float = None,
                            stop_tokens=()):
        """Decode a batch of prompts in lockstep — one dispatch advances
        every row (shared implementation
        util/decoding.sample_stream_batch). Mixed lengths left-pad and
        need rope positions (positional='rope'); learned-positional
        models require equal-length prompts."""
        from deeplearning4j_tpu.util.decoding import sample_stream_batch
        return sample_stream_batch(net, prompts, steps,
                                   vocab_size or self.vocab_size,
                                   temperature=temperature, rng=rng,
                                   max_length=self.max_length,
                                   top_k=top_k, top_p=top_p,
                                   stop_tokens=stop_tokens)

    def speculative_sample(self, net, draft, seed_ids, steps: int,
                           gamma: int = 4, vocab_size: int = None,
                           rng: np.random.Generator = None,
                           temperature: float = 1.0,
                           top_k: int = None, top_p: float = None,
                           prime_padded: bool = False, stop_tokens=()):
        """Speculative decoding: `draft` proposes `gamma` tokens, this
        model verifies them in ONE forward (shared implementation
        util/decoding.speculative_sample — the target distribution is
        exactly preserved; top_k=1 reproduces greedy decoding
        bit-for-bit). `draft` is a same-vocab streaming net (typically a
        smaller/quantized TextGenerationTransformer) or a host proposer
        callable such as decoding.prompt_lookup_proposer()."""
        from deeplearning4j_tpu.util.decoding import speculative_sample
        return speculative_sample(net, draft, seed_ids, steps,
                                  vocab_size or self.vocab_size,
                                  gamma=gamma, temperature=temperature,
                                  rng=rng, max_length=self.max_length,
                                  top_k=top_k, top_p=top_p,
                                  prime_padded=prime_padded,
                                  stop_tokens=stop_tokens)

    def speculative_sample_batch(self, net, draft, prompts, steps: int,
                                 gamma: int = 4, vocab_size: int = None,
                                 rngs=None, temperature: float = 1.0,
                                 top_k: int = None, top_p: float = None,
                                 stop_tokens=()):
        """Batched speculative decoding with per-row acceptance (shared
        implementation util/decoding.speculative_sample_batch): one
        batched verify dispatch serves every prompt's speculation round,
        each row rewinding only its own rejections. top_k=1 reproduces
        per-prompt speculative_sample exactly. Needs rope/position-free
        attention (per-row rewind is attention-only)."""
        from deeplearning4j_tpu.util.decoding import speculative_sample_batch
        return speculative_sample_batch(net, draft, prompts, steps,
                                        vocab_size or self.vocab_size,
                                        gamma=gamma, rngs=rngs,
                                        temperature=temperature,
                                        max_length=self.max_length,
                                        top_k=top_k, top_p=top_p,
                                        stop_tokens=stop_tokens)

    def beam_search_batch(self, net, prompts, steps: int,
                          beam_width: int = 4, vocab_size: int = None,
                          stop_tokens=()):
        """Beam search over a batch of prompts — the [prompts x beams]
        grid rides the batch axis, one dispatch per step for the whole
        batch (shared implementation util/decoding.beam_search_batch).
        Returns [(best_sequence, log_prob)] per prompt."""
        from deeplearning4j_tpu.util.decoding import beam_search_batch
        return beam_search_batch(net, prompts, steps,
                                 vocab_size or self.vocab_size,
                                 beam_width=beam_width,
                                 max_length=self.max_length,
                                 stop_tokens=stop_tokens)

    def beam_search(self, net, seed_ids, steps: int, beam_width: int = 4,
                    vocab_size: int = None, prime_padded: bool = False,
                    stop_tokens=()):
        """Beam-search decoding on the streaming KV-cache machinery
        (shared implementation: util/decoding.beam_search — beams ride
        the batch dimension, pruning gathers the carried state). Returns
        (best token sequence, its log-probability)."""
        from deeplearning4j_tpu.util.decoding import beam_search
        return beam_search(net, seed_ids, steps,
                           vocab_size or self.vocab_size,
                           beam_width=beam_width,
                           max_length=self.max_length,
                           prime_padded=prime_padded,
                           stop_tokens=stop_tokens)
