"""InceptionResNetV1 + FaceNetNN4Small2 (ref: zoo/model/InceptionResNetV1.java,
FaceNetNN4Small2.java with helper/{InceptionResNetHelper,FaceNetHelper}.java —
face-embedding networks trained with center loss / triplet-style objectives,
L2-normalized embedding output).

The builders here produce faithful-capability (stem + residual-inception
blocks + embedding head) graphs scaled by `blocks_per_stage` so tests can
instantiate small variants; defaults give the full-size networks.
"""

from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph_conf import (ElementWiseVertex,
                                                   L2NormalizeVertex,
                                                   MergeVertex, ScaleVertex)
from deeplearning4j_tpu.nn.conf.layers import (ActivationLayer,
                                               BatchNormalization,
                                               CenterLossOutputLayer,
                                               ConvolutionLayer, DenseLayer,
                                               GlobalPoolingLayer, OutputLayer,
                                               SubsamplingLayer)
from deeplearning4j_tpu.nn.updater import Adam
from deeplearning4j_tpu.zoo.base import ZooModel, register_model


@register_model
class InceptionResNetV1(ZooModel):
    def __init__(self, num_classes: int = 1000, seed: int = 12345,
                 height: int = 160, width: int = 160, channels: int = 3,
                 embedding_size: int = 128, blocks_per_stage=(5, 10, 5), **kw):
        super().__init__(num_classes, seed, **kw)
        self.height, self.width, self.channels = height, width, channels
        self.embedding_size = embedding_size
        self.blocks = blocks_per_stage

    def _conv_bn(self, g, name, inp, n_out, kernel, stride=(1, 1), pad=(0, 0)):
        g.add_layer(f"{name}_c",
                    ConvolutionLayer(n_out=n_out, kernel=kernel, stride=stride,
                                     padding=pad, activation="identity",
                                     has_bias=False), inp)
        g.add_layer(f"{name}_bn", BatchNormalization(), f"{name}_c")
        g.add_layer(f"{name}", ActivationLayer(activation="relu"), f"{name}_bn")
        return name

    def _res_block(self, g, name, inp, branch_defs, n_channels, scale=0.17):
        """Residual inception block (ref: InceptionResNetHelper block35/17/8):
        parallel conv branches → merge → 1x1 up-proj → scaled residual add."""
        outs = []
        for bi, defs in enumerate(branch_defs):
            x = inp
            for li, (n_out, kernel, pad) in enumerate(defs):
                x = self._conv_bn(g, f"{name}_b{bi}l{li}", x, n_out, kernel,
                                  pad=pad)
            outs.append(x)
        g.add_vertex(f"{name}_merge", MergeVertex(), *outs)
        g.add_layer(f"{name}_up",
                    ConvolutionLayer(n_out=n_channels, kernel=(1, 1),
                                     activation="identity"), f"{name}_merge")
        g.add_vertex(f"{name}_scale", ScaleVertex(scale=scale), f"{name}_up")
        g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), inp,
                     f"{name}_scale")
        g.add_layer(f"{name}", ActivationLayer(activation="relu"), f"{name}_add")
        return name

    def conf(self):
        g = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(self.kwargs.get("updater", Adam(1e-3)))
             .weight_init("relu")
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(self.height, self.width,
                                                      self.channels)))
        # stem (ref: InceptionResNetV1.java stem)
        x = self._conv_bn(g, "stem1", "input", 32, (3, 3), stride=(2, 2))
        x = self._conv_bn(g, "stem2", x, 32, (3, 3))
        x = self._conv_bn(g, "stem3", x, 64, (3, 3), pad=(1, 1))
        g.add_layer("stem_pool", SubsamplingLayer(pooling_type="max",
                                                  kernel=(3, 3), stride=(2, 2)),
                    x)
        x = self._conv_bn(g, "stem4", "stem_pool", 80, (1, 1))
        x = self._conv_bn(g, "stem5", x, 192, (3, 3))
        x = self._conv_bn(g, "stem6", x, 256, (3, 3), stride=(2, 2))
        # stage A: block35-style
        for i in range(self.blocks[0]):
            x = self._res_block(
                g, f"a{i}", x,
                [[(32, (1, 1), (0, 0))],
                 [(32, (1, 1), (0, 0)), (32, (3, 3), (1, 1))],
                 [(32, (1, 1), (0, 0)), (32, (3, 3), (1, 1)),
                  (32, (3, 3), (1, 1))]],
                n_channels=256, scale=0.17)
        # reduction A
        g.add_layer("redA_pool", SubsamplingLayer(pooling_type="max",
                                                  kernel=(3, 3), stride=(2, 2)),
                    x)
        ra = self._conv_bn(g, "redA_c", x, 384, (3, 3), stride=(2, 2))
        g.add_vertex("redA", MergeVertex(), "redA_pool", ra)
        x = "redA"
        # stage B: block17-style
        for i in range(self.blocks[1]):
            x = self._res_block(
                g, f"b{i}", x,
                [[(128, (1, 1), (0, 0))],
                 [(128, (1, 1), (0, 0)), (128, (1, 7), (0, 3)),
                  (128, (7, 1), (3, 0))]],
                n_channels=640, scale=0.10)
        # reduction B
        g.add_layer("redB_pool", SubsamplingLayer(pooling_type="max",
                                                  kernel=(3, 3), stride=(2, 2)),
                    x)
        rb = self._conv_bn(g, "redB_c", x, 256, (3, 3), stride=(2, 2))
        g.add_vertex("redB", MergeVertex(), "redB_pool", rb)
        x = "redB"
        # stage C: block8-style
        for i in range(self.blocks[2]):
            x = self._res_block(
                g, f"c{i}", x,
                [[(192, (1, 1), (0, 0))],
                 [(192, (1, 1), (0, 0)), (192, (1, 3), (0, 1)),
                  (192, (3, 1), (1, 0))]],
                n_channels=896, scale=0.20)
        g.add_layer("gap", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("emb", DenseLayer(n_out=self.embedding_size,
                                      activation="identity"), "gap")
        g.add_vertex("emb_norm", L2NormalizeVertex(), "emb")
        g.add_layer("output",
                    CenterLossOutputLayer(n_out=self.num_classes, loss="mcxent",
                                          activation="softmax"), "emb_norm")
        return g.set_outputs("output").build()


@register_model
class FaceNetNN4Small2(InceptionResNetV1):
    """Compact face-embedding variant (ref: zoo/model/FaceNetNN4Small2.java —
    nn4.small2 architecture; here realized as a reduced InceptionResNet with
    96x96 input and the same L2-normalized embedding + center-loss head)."""

    def __init__(self, num_classes: int = 1000, seed: int = 12345, **kw):
        kw.setdefault("height", 96)
        kw.setdefault("width", 96)
        super().__init__(num_classes, seed, blocks_per_stage=(2, 4, 2),
                         embedding_size=kw.pop("embedding_size", 128), **kw)
