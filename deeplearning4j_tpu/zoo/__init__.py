"""Model zoo.

TPU-native equivalent of deeplearning4j-zoo (SURVEY §2.8): each model is a
config-builder factory (ref: InstantiableModel iface / ZooModel.java:28-81)
producing a MultiLayerNetwork or ComputationGraph. The model set mirrors
zoo/model/*: LeNet, AlexNet, VGG16, VGG19, ResNet50, GoogLeNet,
InceptionResNetV1, FaceNetNN4Small2, SimpleCNN, TextGenerationLSTM, plus
TinyYOLO-style Darknet (ref objdetect).
"""

from deeplearning4j_tpu.zoo.base import ZooModel, MODEL_REGISTRY, get_model  # noqa: F401
from deeplearning4j_tpu.zoo.lenet import LeNet  # noqa: F401
from deeplearning4j_tpu.zoo.alexnet import AlexNet  # noqa: F401
from deeplearning4j_tpu.zoo.simple_cnn import SimpleCNN  # noqa: F401
from deeplearning4j_tpu.zoo.vgg import VGG16, VGG19  # noqa: F401
from deeplearning4j_tpu.zoo.resnet import ResNet50  # noqa: F401
from deeplearning4j_tpu.zoo.googlenet import GoogLeNet  # noqa: F401
from deeplearning4j_tpu.zoo.inception_resnet import InceptionResNetV1, FaceNetNN4Small2  # noqa: F401
from deeplearning4j_tpu.zoo.text_lstm import TextGenerationLSTM
from deeplearning4j_tpu.zoo.transformer import TextGenerationTransformer  # noqa: F401
from deeplearning4j_tpu.zoo.imagenet import ImageNetLabels  # noqa: F401
