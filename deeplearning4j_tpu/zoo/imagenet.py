"""ImageNet label helper (ref: zoo/util/imagenet/ImageNetLabels.java).

The reference fetches `imagenet_class_index.json` (the Keras-style
{"0": ["n01440764", "tench"], ...} map) from a blob URL at construction
and exposes `getLabel(n)` / `decodePredictions(output)`. Same contract
here, with zero-egress-friendly sources: a local JSON path or file:// URL
works exactly like the hosted blob (the download itself is plain urllib,
cached like the zoo checkpoints)."""

from __future__ import annotations

import hashlib
import json
import os
import urllib.request
from typing import List, Optional

import numpy as np

#: the reference's hosted class-index blob (ImageNetLabels.java jsonUrl);
#: any mirror serving the standard Keras imagenet_class_index.json works
DEFAULT_URL = "http://blob.deeplearning4j.org/utils/imagenet_class_index.json"


class ImageNetLabels:
    """1000-class ImageNet label table + top-k prediction decoding."""

    def __init__(self, source: Optional[str] = None,
                 cache_dir: Optional[str] = None):
        """`source`: local path, file:// URL, or http(s) URL of a
        class-index JSON ({"idx": [wnid, label], ...}); defaults to the
        reference's hosted blob (requires egress; downloads are cached
        under `cache_dir`, default ~/.dl4jtpu/labels)."""
        src = source or DEFAULT_URL
        if os.path.exists(src):
            with open(src, encoding="utf-8") as f:
                raw = json.load(f)
        elif src.startswith(("http://", "https://")):
            cache_dir = cache_dir or os.path.expanduser("~/.dl4jtpu/labels")
            os.makedirs(cache_dir, exist_ok=True)
            # url-hashed cache name: mirrors with identical basenames (or
            # trailing-slash urls) must not collide on one entry
            fname = os.path.join(
                cache_dir,
                hashlib.sha256(src.encode()).hexdigest()[:16] + ".json")
            if os.path.exists(fname):
                with open(fname, encoding="utf-8") as f:
                    raw = json.load(f)
            else:
                # download (bounded timeout) to a temp name, VALIDATE,
                # then atomically move into the cache — an interrupted/
                # truncated download must not poison later constructions
                tmp = fname + ".tmp"
                with urllib.request.urlopen(src, timeout=60) as r, \
                        open(tmp, "wb") as f:
                    f.write(r.read())
                try:
                    with open(tmp, encoding="utf-8") as f:
                        raw = json.load(f)
                except ValueError:
                    os.remove(tmp)
                    raise IOError(
                        f"downloaded class index from {src} is not "
                        "valid JSON (truncated download?)")
                os.replace(tmp, fname)
        else:  # file:// and friends — stream through urllib
            with urllib.request.urlopen(src, timeout=60) as r:
                raw = json.loads(r.read().decode("utf-8"))
        if not isinstance(raw, dict):
            raise ValueError(
                f"class index from {src} must be a JSON object "
                '{"0": [wnid, label], ...}, got ' + type(raw).__name__)
        n = len(raw)
        self._labels: List[str] = [""] * n
        self._wnids: List[str] = [""] * n
        for k, (wnid, label) in raw.items():
            i = int(k)
            if not 0 <= i < n:
                raise ValueError(
                    f"class index from {src} has non-dense key {k!r} "
                    f"(expected 0..{n - 1})")
            self._wnids[i] = wnid
            self._labels[i] = label

    def __len__(self) -> int:
        return len(self._labels)

    def get_label(self, n: int) -> str:
        """ref: getLabel(n)."""
        return self._labels[n]

    def get_wnid(self, n: int) -> str:
        return self._wnids[n]

    def decode_predictions(self, predictions, top: int = 5) -> str:
        """Top-`top` classes + probabilities per batch row, formatted like
        the reference's decodePredictions (ref :57-81)."""
        p = np.asarray(predictions)
        if p.ndim == 1:
            p = p[None, :]
        lines = []
        for row in p:
            order = np.argsort(row)[::-1][:top]
            lines.append("Predictions for batch :")
            lines.append(", ".join(
                f"{float(row[i]) * 100:.3f}% {self._labels[i]}"
                for i in order))
        return "\n".join(lines)

    def top_k(self, predictions, k: int = 5) -> List[List[str]]:
        """Structured variant: label names of the k most probable classes
        per row."""
        p = np.asarray(predictions)
        if p.ndim == 1:
            p = p[None, :]
        return [[self._labels[i] for i in np.argsort(row)[::-1][:k]]
                for row in p]
