"""SimpleCNN (ref: zoo/model/SimpleCNN.java — small conv stack with
batch norm, for quick experiments)."""

from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (BatchNormalization,
                                               ConvolutionLayer, DenseLayer,
                                               OutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.nn.updater import Adam
from deeplearning4j_tpu.zoo.base import ZooModel, register_model


@register_model
class SimpleCNN(ZooModel):
    def __init__(self, num_classes: int = 10, seed: int = 12345,
                 height: int = 48, width: int = 48, channels: int = 3, **kw):
        super().__init__(num_classes, seed, **kw)
        self.height, self.width, self.channels = height, width, channels

    def conf(self):
        return (NeuralNetConfiguration.Builder()
                .seed(self.seed)
                .updater(self.kwargs.get("updater", Adam(1e-3)))
                .weight_init("relu")
                .list()
                .layer(ConvolutionLayer(n_out=16, kernel=(3, 3), padding=(1, 1),
                                        activation="identity"))
                .layer(BatchNormalization())
                .layer(ConvolutionLayer(n_out=16, kernel=(3, 3), padding=(1, 1),
                                        activation="relu"))
                .layer(SubsamplingLayer(pooling_type="max", kernel=(2, 2),
                                        stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=32, kernel=(3, 3), padding=(1, 1),
                                        activation="relu"))
                .layer(SubsamplingLayer(pooling_type="max", kernel=(2, 2),
                                        stride=(2, 2)))
                .layer(DenseLayer(n_out=128, activation="relu", dropout=0.5))
                .layer(OutputLayer(n_out=self.num_classes, loss="mcxent",
                                   activation="softmax"))
                .set_input_type(InputType.convolutional(self.height, self.width,
                                                        self.channels))
                .build())
