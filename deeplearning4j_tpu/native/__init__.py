"""Native C++ IO runtime bindings.

The reference reaches native code through JavaCPP JNI (SURVEY §2.1); here the
host-side data-pipeline hot loops (IDX/CSV decode, u8→f32 normalization,
batch gather) live in C++ (native/src/io.cpp) behind a flat C ABI loaded via
ctypes. ctypes releases the GIL during calls, so decode overlaps Python-side
work and XLA compute. Everything has a numpy fallback — the native lib is an
accelerator, not a dependency.
"""

from deeplearning4j_tpu.native.io import (  # noqa: F401
    native_available, read_idx, read_csv, u8_to_f32, gather_rows,
)
