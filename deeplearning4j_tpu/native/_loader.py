"""Shared native-library loader: locate the .so under native/build/,
rebuild via make when the source is newer, fall back to None (callers use
numpy fallbacks) when the toolchain is unavailable."""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Callable, Optional

log = logging.getLogger(__name__)

NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "native")

_build_lock = threading.Lock()
_build_attempted = False


class NativeLib:
    """Lazily-loaded native library handle."""

    def __init__(self, so_name: str, src_name: str,
                 configure: Callable[[ctypes.CDLL], None]):
        self.so_path = os.path.join(NATIVE_DIR, "build", so_name)
        self.src_path = os.path.join(NATIVE_DIR, "src", src_name)
        self._configure = configure
        self._lib: Optional[ctypes.CDLL] = None
        self._lock = threading.Lock()

    def load(self) -> Optional[ctypes.CDLL]:
        global _build_attempted
        if self._lib is not None:
            return self._lib
        with self._lock:
            if self._lib is not None:
                return self._lib
            stale = (os.path.exists(self.so_path) and
                     os.path.exists(self.src_path) and
                     os.path.getmtime(self.src_path) >
                     os.path.getmtime(self.so_path))
            if not os.path.exists(self.so_path) or stale:
                with _build_lock:
                    if not _build_attempted:
                        _build_attempted = True
                        try:
                            subprocess.run(["make", "-C", NATIVE_DIR],
                                           check=True, capture_output=True,
                                           timeout=120)
                        except Exception as e:  # noqa: BLE001
                            log.info("native build unavailable (%s); "
                                     "using numpy fallbacks", e)
            if not os.path.exists(self.so_path):
                return None
            try:
                lib = ctypes.CDLL(self.so_path)
            except OSError as e:
                log.info("native lib %s load failed (%s); numpy fallbacks",
                         self.so_path, e)
                return None
            self._configure(lib)
            self._lib = lib
            return self._lib

    def available(self) -> bool:
        return self.load() is not None
