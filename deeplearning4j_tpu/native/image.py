"""ctypes bindings for the native image pipeline (native/src/image.cpp):
batch bilinear resize, crop+flip augmentation, fused u8 NHWC -> f32 NCHW
per-channel normalization. Numpy fallbacks keep behavior identical when
the toolchain is absent.
"""

from __future__ import annotations

import ctypes
import logging
from typing import Optional

import numpy as np

from deeplearning4j_tpu.native._loader import NativeLib

log = logging.getLogger(__name__)


def _configure(lib: ctypes.CDLL) -> None:
    u8p = ctypes.POINTER(ctypes.c_ubyte)
    f32p = ctypes.POINTER(ctypes.c_float)
    lp = ctypes.POINTER(ctypes.c_long)
    lib.dl4j_resize_bilinear_u8.argtypes = [
        u8p, ctypes.c_long, ctypes.c_long, ctypes.c_long, ctypes.c_long,
        u8p, ctypes.c_long, ctypes.c_long, ctypes.c_int]
    lib.dl4j_resize_bilinear_u8.restype = ctypes.c_int
    lib.dl4j_crop_flip_u8.argtypes = [
        u8p, ctypes.c_long, ctypes.c_long, ctypes.c_long, ctypes.c_long,
        u8p, ctypes.c_long, ctypes.c_long, lp, lp, u8p, ctypes.c_int]
    lib.dl4j_crop_flip_u8.restype = ctypes.c_int
    lib.dl4j_u8hwc_to_f32chw.argtypes = [
        u8p, ctypes.c_long, ctypes.c_long, ctypes.c_long, ctypes.c_long,
        f32p, ctypes.c_float, f32p, f32p, ctypes.c_int]
    lib.dl4j_u8hwc_to_f32chw.restype = ctypes.c_int


_NATIVE = NativeLib("libdl4jtpu_image.so", "image.cpp", _configure)


def _load():
    return _NATIVE.load()


def native_available() -> bool:
    return _NATIVE.available()


def _as_u8_nhwc(imgs: np.ndarray) -> np.ndarray:
    a = np.ascontiguousarray(imgs)
    if a.dtype != np.uint8 or a.ndim != 4:
        raise ValueError("expected uint8 [N,H,W,C] batch")
    return a


def resize_bilinear(imgs: np.ndarray, out_h: int, out_w: int,
                    nthreads: int = 0) -> np.ndarray:
    """Batch bilinear resize, uint8 [N,H,W,C] -> [N,out_h,out_w,C]
    (half-pixel centers, edge clamp)."""
    a = _as_u8_nhwc(imgs)
    n, h, w, c = a.shape
    lib = _load()
    out = np.empty((n, out_h, out_w, c), np.uint8)
    if lib is not None:
        rc = lib.dl4j_resize_bilinear_u8(
            a.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)), n, h, w, c,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
            out_h, out_w, nthreads)
        if rc == 0:
            return out
    # numpy fallback: identical sampling
    sy = h / out_h
    sx = w / out_w
    fy = np.clip((np.arange(out_h) + 0.5) * sy - 0.5, 0, None)
    fx = np.clip((np.arange(out_w) + 0.5) * sx - 0.5, 0, None)
    y0 = fy.astype(np.int64)
    x0 = fx.astype(np.int64)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (fy - y0)[None, :, None, None]
    wx = (fx - x0)[None, None, :, None]
    af = a.astype(np.float64)
    top = af[:, y0][:, :, x0] * (1 - wx) + af[:, y0][:, :, x1] * wx
    bot = af[:, y1][:, :, x0] * (1 - wx) + af[:, y1][:, :, x1] * wx
    return (top * (1 - wy) + bot * wy + 0.5).astype(np.uint8)


def crop_flip(imgs: np.ndarray, crop_h: int, crop_w: int,
              offsets_y: np.ndarray, offsets_x: np.ndarray,
              flips: Optional[np.ndarray] = None,
              nthreads: int = 0) -> np.ndarray:
    """Batch crop to [crop_h, crop_w] at per-image offsets with optional
    per-image horizontal flip (uint8 NHWC)."""
    a = _as_u8_nhwc(imgs)
    n, h, w, c = a.shape
    oy = np.ascontiguousarray(offsets_y, np.int64)
    ox = np.ascontiguousarray(offsets_x, np.int64)
    if oy.shape != (n,) or ox.shape != (n,):
        raise ValueError("offsets must be [N]")
    if np.any(oy < 0) or np.any(oy + crop_h > h) or np.any(ox < 0) or \
            np.any(ox + crop_w > w):
        raise ValueError("crop window out of bounds")
    fl = None if flips is None else np.ascontiguousarray(flips, np.uint8)
    lib = _load()
    out = np.empty((n, crop_h, crop_w, c), np.uint8)
    if lib is not None:
        u8p = ctypes.POINTER(ctypes.c_ubyte)
        rc = lib.dl4j_crop_flip_u8(
            a.ctypes.data_as(u8p), n, h, w, c, out.ctypes.data_as(u8p),
            crop_h, crop_w,
            oy.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
            ox.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
            None if fl is None else fl.ctypes.data_as(u8p), nthreads)
        if rc == 0:
            return out
    for i in range(n):
        win = a[i, oy[i]:oy[i] + crop_h, ox[i]:ox[i] + crop_w]
        out[i] = win[:, ::-1] if (fl is not None and fl[i]) else win
    return out


def u8hwc_to_f32chw(imgs: np.ndarray, scale: float = 1.0 / 255.0,
                    mean: Optional[np.ndarray] = None,
                    std: Optional[np.ndarray] = None,
                    nthreads: int = 0) -> np.ndarray:
    """Fused uint8 [N,H,W,C] -> float32 [N,C,H,W]:
    (x*scale - mean[c]) / std[c]."""
    a = _as_u8_nhwc(imgs)
    n, h, w, c = a.shape
    m = None if mean is None else np.ascontiguousarray(mean, np.float32)
    s = None if std is None else np.ascontiguousarray(std, np.float32)
    if m is not None and m.shape != (c,):
        raise ValueError(f"mean must be [{c}]")
    if s is not None and s.shape != (c,):
        raise ValueError(f"std must be [{c}]")
    lib = _load()
    out = np.empty((n, c, h, w), np.float32)
    if lib is not None:
        f32p = ctypes.POINTER(ctypes.c_float)
        rc = lib.dl4j_u8hwc_to_f32chw(
            a.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)), n, h, w, c,
            out.ctypes.data_as(f32p), scale,
            None if m is None else m.ctypes.data_as(f32p),
            None if s is None else s.ctypes.data_as(f32p), nthreads)
        if rc == 0:
            return out
    x = a.astype(np.float32) * scale
    if m is not None:
        x = x - m
    if s is not None:
        x = x / np.where(s == 0, 1.0, s)
    return np.ascontiguousarray(x.transpose(0, 3, 1, 2))
