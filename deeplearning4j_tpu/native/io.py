"""ctypes bindings for native/src/io.cpp with numpy fallbacks."""

from __future__ import annotations

import ctypes
import logging
import os
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.native import _loader as _loader_mod

log = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "build", "libdl4jtpu_io.so")

_IDX_DTYPES = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.dtype(">i2"),
               0x0C: np.dtype(">i4"), 0x0D: np.dtype(">f4"),
               0x0E: np.dtype(">f8")}
_IDX_HOST = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.int16,
             0x0C: np.int32, 0x0D: np.float32, 0x0E: np.float64}


def _configure(lib):
    lib.dl4j_idx_info.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_int)]
    lib.dl4j_idx_read.argtypes = [
        ctypes.c_char_p, ctypes.c_void_p, ctypes.c_long, ctypes.c_int]
    lib.dl4j_csv_count_rows.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.dl4j_csv_count_rows.restype = ctypes.c_long
    lib.dl4j_csv_read.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char,
        ctypes.POINTER(ctypes.c_float), ctypes.c_long, ctypes.c_long,
        ctypes.c_int]
    lib.dl4j_native_version.restype = ctypes.c_int
    lib.dl4j_u8_to_f32.argtypes = [
        ctypes.POINTER(ctypes.c_ubyte), ctypes.POINTER(ctypes.c_float),
        ctypes.c_long, ctypes.c_float, ctypes.c_int]
    lib.dl4j_gather_rows_f32.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_long),
        ctypes.POINTER(ctypes.c_float), ctypes.c_long, ctypes.c_long,
        ctypes.c_int]


_NATIVE = _loader_mod.NativeLib("libdl4jtpu_io.so", "io.cpp", _configure)


def _load():
    return _NATIVE.load()


def native_available() -> bool:
    return _NATIVE.available()


# ---------------------------------------------------------------------------

def read_idx(path: str, nthreads: int = 0) -> np.ndarray:
    """Decode an IDX file (MNIST family) into a host-order numpy array."""
    lib = _load()
    if lib is None:
        return _read_idx_numpy(path)
    ndim = ctypes.c_int()
    dtype = ctypes.c_int()
    dims = (ctypes.c_long * 8)()
    rc = lib.dl4j_idx_info(path.encode(), ctypes.byref(ndim), dims,
                           ctypes.byref(dtype))
    if rc != 0:
        raise IOError(f"bad IDX file {path!r} (code {rc})")
    shape = tuple(dims[i] for i in range(ndim.value))
    out = np.empty(shape, dtype=_IDX_HOST[dtype.value])
    rc = lib.dl4j_idx_read(path.encode(),
                           out.ctypes.data_as(ctypes.c_void_p),
                           out.nbytes, nthreads)
    if rc != 0:
        raise IOError(f"IDX read failed for {path!r} (code {rc})")
    return out


def _read_idx_numpy(path: str) -> np.ndarray:
    with open(path, "rb") as f:
        magic = f.read(4)
        if len(magic) != 4 or magic[0] != 0 or magic[1] != 0:
            raise IOError(f"bad IDX file {path!r}")
        dtype, nd = magic[2], magic[3]
        if dtype not in _IDX_DTYPES or not (1 <= nd <= 8):
            raise IOError(f"bad IDX file {path!r}")
        shape = tuple(int.from_bytes(f.read(4), "big") for _ in range(nd))
        data = np.frombuffer(f.read(), dtype=_IDX_DTYPES[dtype])
        expect = int(np.prod(shape))
        if data.size != expect:
            raise IOError(f"IDX payload mismatch in {path!r}")
    return data.reshape(shape).astype(_IDX_HOST[dtype], copy=False)


def read_csv(path: str, skip_header=False, delimiter: str = ",",
             nthreads: int = 0) -> np.ndarray:
    """Parse a numeric CSV into a [rows, cols] float32 array.

    skip_header: bool (skip one line) or int (skip that many lines).
    """
    skip = int(skip_header)
    lib = _load()
    if lib is None:
        return np.loadtxt(path, delimiter=delimiter, dtype=np.float32,
                          skiprows=skip, ndmin=2)
    rows = lib.dl4j_csv_count_rows(path.encode(), skip)
    if rows < 0:
        raise IOError(f"cannot read {path!r}")
    if rows == 0:
        return np.empty((0, 0), np.float32)
    cols = 0
    with open(path) as f:
        skipped = 0
        for line in f:
            if not line.strip():
                continue  # row counter ignores blank lines; sniff must too
            if skipped < skip:
                skipped += 1
                continue
            cols = len([t for t in line.replace(delimiter, " ").split()
                        if t])
            break
    if cols == 0:
        return np.empty((0, 0), np.float32)
    out = np.empty((rows, cols), np.float32)
    rc = lib.dl4j_csv_read(
        path.encode(), skip, delimiter.encode()[:1],
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), rows, cols,
        nthreads)
    if rc != 0:
        raise IOError(f"CSV parse failed for {path!r} (code {rc})")
    return out


def u8_to_f32(arr: np.ndarray, scale: float = 1.0 / 255.0,
              nthreads: int = 0) -> np.ndarray:
    """Normalize uint8 image data to float32 (threaded in C++)."""
    arr = np.ascontiguousarray(arr, np.uint8)
    lib = _load()
    if lib is None:
        return arr.astype(np.float32) * np.float32(scale)
    out = np.empty(arr.shape, np.float32)
    lib.dl4j_u8_to_f32(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        arr.size, scale, nthreads)
    return out


def gather_rows(arr: np.ndarray, indices: np.ndarray,
                nthreads: int = 0) -> np.ndarray:
    """out[i] = arr[indices[i]] — shuffled minibatch assembly."""
    arr = np.ascontiguousarray(arr, np.float32)
    idx = np.ascontiguousarray(indices, np.int64)
    if idx.size and (idx.min() < 0 or idx.max() >= arr.shape[0]):
        raise IndexError("gather index out of range")
    lib = _load()
    if lib is None:
        return arr[idx]
    row_elems = int(np.prod(arr.shape[1:])) if arr.ndim > 1 else 1
    out = np.empty((idx.shape[0],) + arr.shape[1:], np.float32)
    rc = lib.dl4j_gather_rows_f32(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        idx.shape[0], row_elems, nthreads)
    if rc != 0:
        raise IndexError("gather index out of range")
    return out
