"""Native Word2Vec pair generation bindings.

The reference trains embeddings with a multithreaded Java worker pool
(SequenceVectors.java:192 fit); the TPU build batches the device math into
jit steps, which left numpy pair generation as the measured host ceiling
(~200k words/s, PERF.md). native/src/word2vec.cpp generates an epoch of
skip-gram pairs / CBOW rows across C++ threads (ctypes releases the GIL);
results are deterministic in (seed, sequence index) regardless of thread
count. Falls back to None when the toolchain is unavailable — callers keep
the numpy path.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.native._loader import NativeLib

_i32p = ctypes.POINTER(ctypes.c_int32)
_i64p = ctypes.POINTER(ctypes.c_int64)
_f32p = ctypes.POINTER(ctypes.c_float)


def _configure(lib: ctypes.CDLL) -> None:
    lib.w2v_sg_pairs.restype = ctypes.c_int64
    lib.w2v_sg_pairs.argtypes = [
        _i32p, _i64p, ctypes.c_int64, ctypes.c_int32, _f32p,
        ctypes.c_uint64, ctypes.c_int32, _i32p, _i32p, _i32p,
        ctypes.c_int64, ctypes.c_int32]
    lib.w2v_cbow_rows.restype = ctypes.c_int64
    lib.w2v_cbow_rows.argtypes = [
        _i32p, _i64p, ctypes.c_int64, ctypes.c_int32, _f32p,
        ctypes.c_uint64, ctypes.c_int32, ctypes.c_int32, _i32p, _f32p,
        _i32p, _i32p, ctypes.c_int64, ctypes.c_int32]


_LIB = NativeLib("libdl4jtpu_word2vec.so", "word2vec.cpp", _configure)


def native_available() -> bool:
    return _LIB.available()


def _threads() -> int:
    return min(8, os.cpu_count() or 1)


def _ptr(a: np.ndarray, ct):
    return a.ctypes.data_as(ct)


def sg_pairs(corpus: np.ndarray, offsets: np.ndarray, window: int,
             keep: Optional[np.ndarray], seed: int, shrink: bool = True
             ) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Skip-gram (input=context, output=center) pairs for the whole
    corpus. corpus: concatenated int32 vocab indices; offsets: int64
    [n_seqs+1]; keep: per-vocab keep probability (None = no subsample).
    Returns (ins, outs, pair_seq) or None when the native lib is absent."""
    lib = _LIB.load()
    if lib is None:
        return None
    corpus = np.ascontiguousarray(corpus, np.int32)
    offsets = np.ascontiguousarray(offsets, np.int64)
    n_seqs = len(offsets) - 1
    kp = None if keep is None else np.ascontiguousarray(keep, np.float32)
    kpp = None if kp is None else _ptr(kp, _f32p)
    sd = ctypes.c_uint64(seed & (2**64 - 1))
    shr = 1 if shrink else 0
    # probe with cap=0 (counting pass only, returns -(pairs needed)) so
    # the buffers are sized EXACTLY — no worst-case corpus*2w allocation
    probe = np.empty(1, np.int32)
    n = lib.w2v_sg_pairs(
        _ptr(corpus, _i32p), _ptr(offsets, _i64p), n_seqs, window, kpp,
        sd, shr, _ptr(probe, _i32p), _ptr(probe, _i32p),
        _ptr(probe, _i32p), 0, _threads())
    if n == -(2 ** 63):
        raise ValueError(f"invalid w2v_sg_pairs arguments (window={window})")
    need = -n if n < 0 else n
    ins = np.empty(need, np.int32)
    outs = np.empty(need, np.int32)
    pair_seq = np.empty(need, np.int32)
    if need:
        n = lib.w2v_sg_pairs(
            _ptr(corpus, _i32p), _ptr(offsets, _i64p), n_seqs, window, kpp,
            sd, shr, _ptr(ins, _i32p), _ptr(outs, _i32p),
            _ptr(pair_seq, _i32p), need, _threads())
        if n != need:
            raise RuntimeError(f"w2v_sg_pairs fill mismatch {n} != {need}")
    return ins, outs, pair_seq


def cbow_rows(corpus: np.ndarray, offsets: np.ndarray, window: int,
              keep: Optional[np.ndarray], seed: int, row_width: int,
              shrink: bool = True):
    """CBOW context rows ([n, row_width] ctxs + mask, centers, row_seq)
    with columns [-w..-1, 1..w] like SequenceVectors._cbow_contexts.
    row_width >= 2*window (extra columns left zero for label slots).
    Returns None when the native lib is absent."""
    lib = _LIB.load()
    if lib is None:
        return None
    corpus = np.ascontiguousarray(corpus, np.int32)
    offsets = np.ascontiguousarray(offsets, np.int64)
    n_seqs = len(offsets) - 1
    kp = None if keep is None else np.ascontiguousarray(keep, np.float32)
    kpp = None if kp is None else _ptr(kp, _f32p)
    sd = ctypes.c_uint64(seed & (2**64 - 1))
    shr = 1 if shrink else 0
    probe_i = np.empty(1, np.int32)
    probe_f = np.empty(1, np.float32)
    n = lib.w2v_cbow_rows(
        _ptr(corpus, _i32p), _ptr(offsets, _i64p), n_seqs, window, kpp,
        sd, shr, row_width, _ptr(probe_i, _i32p), _ptr(probe_f, _f32p),
        _ptr(probe_i, _i32p), _ptr(probe_i, _i32p), 0, _threads())
    if n == -(2 ** 63):
        raise ValueError(
            f"invalid w2v_cbow_rows arguments (window={window}, "
            f"row_width={row_width})")
    need = -n if n < 0 else n
    # np.empty is enough: the engine memsets + fills every written row
    ctxs = np.empty((need, row_width), np.int32)
    cmask = np.empty((need, row_width), np.float32)
    centers = np.empty(need, np.int32)
    row_seq = np.empty(need, np.int32)
    if need:
        n = lib.w2v_cbow_rows(
            _ptr(corpus, _i32p), _ptr(offsets, _i64p), n_seqs, window, kpp,
            sd, shr, row_width, _ptr(ctxs, _i32p), _ptr(cmask, _f32p),
            _ptr(centers, _i32p), _ptr(row_seq, _i32p), need, _threads())
        if n != need:
            raise RuntimeError(f"w2v_cbow_rows fill mismatch {n} != {need}")
    return ctxs, cmask, centers, row_seq
