"""Array streaming + model-serving routes.

Equivalent of deeplearning4j-streaming (SURVEY §2.5): Kafka+Camel NDArray
pub/sub (kafka/NDArrayKafkaClient.java) and the serving route
(routes/DL4jServeRouteBuilder.java — consume arrays, run a model, publish
predictions).

Kafka/Camel are JVM infrastructure; the TPU-native equivalent keeps the
same roles with stdlib primitives:
- ArrayPublisher/ArraySubscriber: length-prefixed npz frames over TCP —
  the pub/sub transport (works cross-process on one host or across hosts).
- ServeRoute: subscribe → model.output → publish, the serving route.
If a kafka client library is available it can be slotted in by implementing
the same two-method transport interface; none is baked into this image.
"""

from __future__ import annotations

import io
import logging
import socket
import socketserver
import struct
import threading
from typing import Callable, List, Optional

import numpy as np

log = logging.getLogger(__name__)

_MAGIC = b"DL4J"


def _pack(arrays: dict) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    payload = buf.getvalue()
    return _MAGIC + struct.pack(">I", len(payload)) + payload


def _read_exact(sock: socket.socket, n: int) -> bytes:
    out = b""
    while len(out) < n:
        chunk = sock.recv(n - len(out))
        if not chunk:
            raise ConnectionError("stream closed mid-frame")
        out += chunk
    return out


def _unpack_stream(sock: socket.socket) -> dict:
    header = _read_exact(sock, 8)
    if header[:4] != _MAGIC:
        raise IOError("bad frame magic")
    (length,) = struct.unpack(">I", header[4:])
    payload = _read_exact(sock, length)
    with np.load(io.BytesIO(payload)) as z:
        return {k: z[k] for k in z.files}


class ArrayHub:
    """Broker: accepts subscriber connections and fans out published
    frames (the Kafka-topic role). One hub ≈ one topic."""

    def __init__(self, port: int = 0, send_timeout: float = 5.0):
        self._subs: List[socket.socket] = []
        self._lock = threading.Lock()       # subscriber list
        self._pub_lock = threading.Lock()   # one publisher at a time
        self.send_timeout = send_timeout
        hub = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                with hub._lock:
                    hub._subs.append(self.request)
                # hold the connection open until the hub closes it
                try:
                    while self.request.recv(1):
                        pass
                except OSError:
                    pass

        self._server = socketserver.ThreadingTCPServer(
            ("127.0.0.1", port), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def publish(self, **arrays) -> int:
        """Send a frame to all connected subscribers; returns how many
        received it. Sends happen OUTSIDE the lock with a timeout so one
        stalled subscriber can't wedge the hub; timed-out/dead subscribers
        are dropped."""
        frame = _pack(arrays)
        with self._pub_lock:  # serialize publishers (frame interleaving)
            return self._publish_frame(frame)

    def _publish_frame(self, frame: bytes) -> int:
        with self._lock:
            targets = list(self._subs)
        sent, dead = 0, []
        for s in targets:
            try:
                s.settimeout(self.send_timeout)
                s.sendall(frame)
                sent += 1
            except OSError:
                dead.append(s)
        if dead:
            with self._lock:
                for s in dead:
                    if s in self._subs:
                        self._subs.remove(s)
                    s.close()
        return sent

    def close(self):
        self._server.shutdown()
        self._server.server_close()
        with self._lock:
            for s in self._subs:
                s.close()
            self._subs = []


class ArraySubscriber:
    """Blocking subscriber to an ArrayHub (NDArrayKafkaClient consume
    role)."""

    def __init__(self, port: int, host: str = "127.0.0.1",
                 timeout: Optional[float] = None):
        self._sock = socket.create_connection((host, port), timeout=timeout)

    def next(self) -> dict:
        return _unpack_stream(self._sock)

    def close(self):
        self._sock.close()


class ServeRoute:
    """Model-serving route (ref: DL4jServeRouteBuilder): consume feature
    frames from an input hub, run the model, publish prediction frames to
    an output hub."""

    def __init__(self, model_fn: Callable[[np.ndarray], np.ndarray],
                 in_port: int, out_hub: "ArrayHub",
                 feature_key: str = "features",
                 prediction_key: str = "predictions"):
        self.model_fn = model_fn
        self.out_hub = out_hub
        self.feature_key = feature_key
        self.prediction_key = prediction_key
        self._sub = ArraySubscriber(in_port)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.is_set():
            try:
                frame = self._sub.next()
            except (ConnectionError, OSError):
                break
            preds = np.asarray(self.model_fn(frame[self.feature_key]))
            out = dict(frame)
            out[self.prediction_key] = preds
            self.out_hub.publish(**out)

    def stop(self):
        self._stop.set()
        self._sub.close()
        self._thread.join(timeout=5)
