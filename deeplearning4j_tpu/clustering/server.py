"""Nearest-neighbors REST server + client.

Equivalent of deeplearning4j-nearestneighbors-parent nearestneighbor-server
(283 LoC Play REST server over a VPTree), nearestneighbors-client, and the
-model JSON DTOs (Base64NDArrayBody etc., SURVEY §2.10).

The Play server becomes stdlib http.server; the VPTree index becomes the
device brute-force kNN (clustering.knn.NearestNeighbors) — the TPU-idiomatic
fast path. DTOs are plain JSON (points as number lists; the reference's
base64-NDArray encoding existed for JVM interop and has no value here).

Endpoints (mirroring the reference's routes):
- POST /knn       {"index": i, "k": n}              → neighbors of a stored point
- POST /knnnew    {"point": [...], "k": n}          → neighbors of a new point
- GET  /status    → {"numPoints": N, "dim": D, "metric": "..."}
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from deeplearning4j_tpu.clustering.knn import NearestNeighbors

log = logging.getLogger(__name__)


class _Handler(BaseHTTPRequestHandler):
    server_version = "dl4jtpu-knn/0.1"

    def log_message(self, fmt, *args):
        log.debug("knn: " + fmt, *args)

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        nn: NearestNeighbors = self.server.nn
        if self.path.rstrip("/") == "/status":
            return self._json({
                "numPoints": int(nn.points.shape[0]),
                "dim": int(nn.points.shape[1]),
                "metric": nn.metric,
            })
        self._json({"error": "not found"}, 404)

    def do_POST(self):
        nn: NearestNeighbors = self.server.nn
        try:
            n = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(n) or b"{}")
            k = int(payload.get("k", 1))
            if self.path.rstrip("/") == "/knn":
                index = int(payload["index"])
                n_pts = int(nn.points.shape[0])
                if not 0 <= index < n_pts:  # jnp indexing would clamp OOB
                    return self._json(
                        {"error": f"index {index} outside [0, {n_pts})"},
                        400)
                idx, d = nn.query_point_index(index, k=k)
            elif self.path.rstrip("/") == "/knnnew":
                point = np.asarray(payload["point"], np.float32)
                if point.ndim != 1 or point.shape[0] != nn.points.shape[1]:
                    return self._json(
                        {"error": f"point must have dim "
                                  f"{int(nn.points.shape[1])}"}, 400)
                ii, dd = nn.query(point, k=k)
                idx, d = ii[0], dd[0]
            else:
                return self._json({"error": "not found"}, 404)
        except (KeyError, TypeError, ValueError, IndexError) as e:
            return self._json({"error": f"bad request: {e}"}, 400)
        self._json({"results": [
            {"index": int(i), "distance": float(x)}
            for i, x in zip(idx, d)]})


class NearestNeighborsServer:
    """ref: nearestneighbor-server NearestNeighborsServer.java —
    runs until stop(), serving kNN over the given points."""

    def __init__(self, points, port: int = 9100,
                 metric: str = "euclidean"):
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self._httpd.nn = NearestNeighbors(points, metric=metric)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        log.info("kNN server at http://127.0.0.1:%d", self.port)

    def stop(self):
        self._httpd.shutdown()
        self._thread.join(timeout=5)
        self._httpd.server_close()


class NearestNeighborsClient:
    """ref: nearestneighbors-client NearestNeighborsClient.java."""

    def __init__(self, url: str, timeout: float = 10.0):
        self.url = url.rstrip("/")
        self.timeout = timeout

    def _post(self, route: str, payload: dict) -> dict:
        req = urllib.request.Request(
            self.url + route, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.load(r)

    def knn(self, index: int, k: int = 1) -> dict:
        return self._post("/knn", {"index": index, "k": k})

    def knn_new(self, point, k: int = 1) -> dict:
        return self._post("/knnnew",
                          {"point": np.asarray(point).tolist(), "k": k})

    def status(self) -> dict:
        with urllib.request.urlopen(self.url + "/status",
                                    timeout=self.timeout) as r:
            return json.load(r)
