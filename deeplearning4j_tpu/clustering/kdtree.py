"""KD-tree (host-side) for exact low-dimensional nearest neighbor.

Equivalent of nearestneighbor-core clustering/kdtree/KDTree.java (insert,
nn search, knn, delete). Host numpy — tree traversal is pointer-chasing,
which does not map to XLA; the device path for bulk queries is
clustering.knn.
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np


class _Node:
    __slots__ = ("point", "left", "right")

    def __init__(self, point: np.ndarray):
        self.point = point
        self.left: Optional["_Node"] = None
        self.right: Optional["_Node"] = None


class KDTree:
    """ref: KDTree.java — axis cycles with depth; Euclidean metric."""

    def __init__(self, dims: int):
        self.dims = dims
        self._root: Optional[_Node] = None
        self._size = 0

    def size(self) -> int:
        return self._size

    def insert(self, point) -> None:
        p = np.asarray(point, np.float64)
        if p.shape != (self.dims,):
            raise ValueError(f"expected point of dim {self.dims}")
        self._size += 1
        if self._root is None:
            self._root = _Node(p)
            return
        node, depth = self._root, 0
        while True:
            axis = depth % self.dims
            if p[axis] < node.point[axis]:
                if node.left is None:
                    node.left = _Node(p)
                    return
                node = node.left
            else:
                if node.right is None:
                    node.right = _Node(p)
                    return
                node = node.right
            depth += 1

    def nn(self, point) -> Tuple[Optional[np.ndarray], float]:
        """Nearest neighbor (ref: KDTree.nn)."""
        res = self.knn(point, 1)
        return (res[0][1], res[0][0]) if res else (None, float("inf"))

    def knn(self, point, k: int) -> List[Tuple[float, np.ndarray]]:
        """k nearest as [(distance, point)] sorted ascending."""
        q = np.asarray(point, np.float64)
        heap: List[Tuple[float, int, np.ndarray]] = []  # max-heap by -dist
        counter = [0]

        def visit(node: Optional[_Node], depth: int):
            if node is None:
                return
            d = float(np.linalg.norm(node.point - q))
            if len(heap) < k:
                heapq.heappush(heap, (-d, counter[0], node.point))
                counter[0] += 1
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, counter[0], node.point))
                counter[0] += 1
            axis = depth % self.dims
            diff = q[axis] - node.point[axis]
            near, far = (node.left, node.right) if diff < 0 \
                else (node.right, node.left)
            visit(near, depth + 1)
            if len(heap) < k or abs(diff) < -heap[0][0]:
                visit(far, depth + 1)

        visit(self._root, 0)
        return sorted([(-nd, pt) for nd, _, pt in heap], key=lambda t: t[0])
