"""K-means clustering with device-side assignment + update steps.

Equivalent of nearestneighbor-core clustering/kmeans/KMeansClustering.java and
the BaseClusteringAlgorithm framework (ClusteringStrategy, iteration
conditions — algorithm/BaseClusteringAlgorithm.java, condition
VarianceVariationCondition / FixedIterationCountCondition).

TPU-first: the reference loops point-by-point over a ClusterSet; here each
iteration is two jitted kernels — a [N,K] distance matmul + argmin
(assignment, MXU) and a segment-sum centroid update — so the whole Lloyd
step runs on device regardless of N.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("k",))
def _lloyd_step(points, centroids, k: int):
    """One Lloyd iteration: assign to nearest centroid, recompute means.
    Empty clusters keep their previous centroid."""
    p2 = jnp.sum(points * points, axis=1)
    c2 = jnp.sum(centroids * centroids, axis=1)
    d2 = p2[:, None] - 2.0 * points @ centroids.T + c2[None, :]  # [N,K]
    assign = jnp.argmin(d2, axis=1)                              # [N]
    sums = jax.ops.segment_sum(points, assign, num_segments=k)   # [K,D]
    counts = jax.ops.segment_sum(jnp.ones(points.shape[0]), assign,
                                 num_segments=k)                 # [K]
    new_c = jnp.where(counts[:, None] > 0,
                      sums / jnp.maximum(counts[:, None], 1.0), centroids)
    cost = jnp.sum(jnp.take_along_axis(d2, assign[:, None], axis=1))
    return new_c, assign, cost


@dataclass
class Cluster:
    """One cluster: centroid + member point indices
    (ref: cluster/Cluster.java)."""
    center: np.ndarray
    point_indices: List[int] = field(default_factory=list)


@dataclass
class ClusterSet:
    """Result of clustering (ref: cluster/ClusterSet.java)."""
    clusters: List[Cluster]
    assignments: np.ndarray
    cost: float

    def get_cluster_count(self) -> int:
        return len(self.clusters)

    def nearest_cluster(self, point) -> int:
        centers = np.stack([c.center for c in self.clusters])
        d = np.linalg.norm(centers - np.asarray(point), axis=1)
        return int(np.argmin(d))


class KMeansClustering:
    """ref: KMeansClustering.setup(clusterCount, maxIterationCount, ...) /
    setup(clusterCount, minDistributionVariationRate, ...) — both stopping
    strategies supported."""

    def __init__(self, cluster_count: int, max_iterations: int = 100,
                 min_variation_rate: Optional[float] = None,
                 init: str = "kmeans++", seed: int = 42):
        self.k = cluster_count
        self.max_iterations = max_iterations
        self.min_variation_rate = min_variation_rate
        self.init = init
        self.seed = seed
        self.cost_history: List[float] = []

    def apply_to(self, points) -> ClusterSet:
        pts = np.asarray(points, np.float32)
        n = pts.shape[0]
        if n < self.k:
            raise ValueError(f"need >= {self.k} points, got {n}")
        centroids = jnp.asarray(self._init_centroids(pts))
        dev_pts = jnp.asarray(pts)
        self.cost_history = []
        assign = None
        prev_cost = None
        for _ in range(self.max_iterations):
            centroids, assign, cost = _lloyd_step(dev_pts, centroids, self.k)
            cost = float(cost)
            self.cost_history.append(cost)
            if prev_cost is not None:
                if cost == 0.0 or (
                        self.min_variation_rate is not None and
                        abs(prev_cost - cost) / max(prev_cost, 1e-12)
                        < self.min_variation_rate):
                    break
                if cost == prev_cost:
                    break
            prev_cost = cost
        assign_np = np.asarray(assign)
        cent_np = np.asarray(centroids)
        clusters = [Cluster(cent_np[i],
                            np.nonzero(assign_np == i)[0].tolist())
                    for i in range(self.k)]
        return ClusterSet(clusters, assign_np, self.cost_history[-1])

    def _init_centroids(self, pts: np.ndarray) -> np.ndarray:
        rng = np.random.default_rng(self.seed)
        if self.init == "random":
            idx = rng.choice(pts.shape[0], self.k, replace=False)
            return pts[idx]
        # k-means++ (ref picks random initial centers; ++ strictly improves)
        centers = [pts[rng.integers(0, pts.shape[0])]]
        for _ in range(1, self.k):
            d2 = np.min(
                [np.sum((pts - c) ** 2, axis=1) for c in centers], axis=0)
            tot = d2.sum()
            if tot <= 0:  # fewer distinct points than k: fall back uniform
                centers.append(pts[rng.integers(0, pts.shape[0])])
                continue
            centers.append(pts[rng.choice(pts.shape[0], p=d2 / tot)])
        return np.stack(centers)
