"""Vantage-point tree for metric-space nearest neighbor.

Equivalent of nearestneighbor-core clustering/vptree/VPTree.java (random
vantage point, median-distance split, tau-pruned search) and
VPTreeFillSearch (collect >=k candidates then exact-sort).
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Sequence, Tuple

import numpy as np


def _metric(name: str):
    if name in ("euclidean", "l2"):
        return lambda a, b: float(np.linalg.norm(a - b))
    if name == "manhattan":
        return lambda a, b: float(np.abs(a - b).sum())
    if name == "cosine":
        def cos(a, b):
            den = np.linalg.norm(a) * np.linalg.norm(b)
            return 1.0 - float(np.dot(a, b) / den) if den > 0 else 1.0
        return cos
    raise ValueError(f"unknown metric {name!r}")


class _VPNode:
    __slots__ = ("index", "threshold", "inside", "outside")

    def __init__(self, index: int):
        self.index = index
        self.threshold = 0.0
        self.inside: Optional["_VPNode"] = None
        self.outside: Optional["_VPNode"] = None


class VPTree:
    """ref: VPTree.java — buildFromData with median split; search prunes
    with the running kth distance (tau)."""

    def __init__(self, points, similarity_function: str = "euclidean",
                 seed: int = 123):
        self.items = np.asarray(points, np.float64)
        self.dist = _metric(similarity_function)
        self._rng = np.random.default_rng(seed)
        idxs = list(range(len(self.items)))
        self._root = self._build(idxs)

    def _build(self, idxs: List[int]) -> Optional[_VPNode]:
        if not idxs:
            return None
        vp_pos = int(self._rng.integers(0, len(idxs)))
        idxs[0], idxs[vp_pos] = idxs[vp_pos], idxs[0]
        node = _VPNode(idxs[0])
        rest = idxs[1:]
        if rest:
            vp = self.items[node.index]
            dists = [self.dist(vp, self.items[i]) for i in rest]
            order = np.argsort(dists)
            median_pos = len(rest) // 2
            node.threshold = dists[order[median_pos]]
            inside = [rest[j] for j in order[:median_pos + 1]]
            outside = [rest[j] for j in order[median_pos + 1:]]
            node.inside = self._build(inside)
            node.outside = self._build(outside)
        return node

    def search(self, target, k: int) -> Tuple[List[int], List[float]]:
        """k nearest item indices + distances, ascending."""
        q = np.asarray(target, np.float64)
        heap: List[Tuple[float, int]] = []  # max-heap (-dist, idx)
        tau = [float("inf")]

        def visit(node: Optional[_VPNode]):
            if node is None:
                return
            d = self.dist(self.items[node.index], q)
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
                if len(heap) == k:
                    tau[0] = -heap[0][0]
            elif d < tau[0]:
                heapq.heapreplace(heap, (-d, node.index))
                tau[0] = -heap[0][0]
            if d < node.threshold:
                visit(node.inside)
                if d + tau[0] >= node.threshold:
                    visit(node.outside)
            else:
                visit(node.outside)
                if d - tau[0] <= node.threshold:
                    visit(node.inside)

        visit(self._root)
        out = sorted([(-nd, i) for nd, i in heap])
        return [i for _, i in out], [d for d, _ in out]


class VPTreeFillSearch:
    """Collect at least k results then exact-rank
    (ref: vptree/VPTreeFillSearch.java)."""

    def __init__(self, tree: VPTree, k: int, target):
        self.tree = tree
        self.k = k
        self.target = np.asarray(target, np.float64)
        self.results: List[int] = []
        self.distances: List[float] = []

    def search(self) -> None:
        idx, d = self.tree.search(self.target, self.k)
        self.results, self.distances = idx, d
