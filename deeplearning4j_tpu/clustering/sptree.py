"""SP-tree: n-dimensional Barnes-Hut space-partitioning tree.

Equivalent of nearestneighbor-core clustering/sptree/SpTree.java — the
generalized (any-D) octree used by BarnesHutTsne: cells with
center-of-mass, 2^D children, computeNonEdgeForces/computeEdgeForces.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class SpTree:
    """ref: SpTree.java — node capacity 1, duplicate points merge mass."""

    def __init__(self, data: Optional[np.ndarray] = None, *,
                 center: Optional[np.ndarray] = None,
                 width: Optional[np.ndarray] = None):
        if data is not None:
            data = np.asarray(data, np.float64)
            lo, hi = data.min(axis=0), data.max(axis=0)
            center = (lo + hi) / 2
            width = (hi - lo) / 2 + 1e-5
        self.center = np.asarray(center, np.float64)
        self.width = np.asarray(width, np.float64)
        self.dims = len(self.center)
        self.size = 0
        self.center_of_mass = np.zeros(self.dims)
        self.point: Optional[np.ndarray] = None
        self.children: Optional[List["SpTree"]] = None
        if data is not None:
            for p in data:
                self.insert(p)

    @property
    def is_leaf(self) -> bool:
        return self.children is None

    def insert(self, p) -> bool:
        p = np.asarray(p, np.float64)
        if np.any(np.abs(p - self.center) > self.width + 1e-12):
            return False
        self.center_of_mass = (self.center_of_mass * self.size + p) / (self.size + 1)
        self.size += 1
        if self.is_leaf and self.point is None:
            self.point = p
            return True
        if self.is_leaf:
            if np.allclose(self.point, p):
                return True
            self._subdivide()
        child = self.children[self._child_index(p)]
        return child.insert(p)

    def _child_index(self, p: np.ndarray) -> int:
        idx = 0
        for d in range(self.dims):
            if p[d] > self.center[d]:
                idx |= (1 << d)
        return idx

    def _subdivide(self) -> None:
        half = self.width / 2
        self.children = []
        for i in range(1 << self.dims):
            offs = np.array([half[d] if (i >> d) & 1 else -half[d]
                             for d in range(self.dims)])
            self.children.append(
                SpTree(center=self.center + offs, width=half))
        old = self.point
        self.point = None
        self.children[self._child_index(old)].insert(old)

    def compute_non_edge_forces(self, point, theta: float,
                                neg: np.ndarray) -> float:
        """Accumulate Barnes-Hut repulsive forces into ``neg``; returns the
        partial normalization sum_Q (ref: SpTree.computeNonEdgeForces)."""
        if self.size == 0:
            return 0.0
        p = np.asarray(point, np.float64)
        diff = p - self.center_of_mass
        d2 = float(diff @ diff)
        if self.is_leaf and self.point is not None and np.allclose(self.point, p):
            n_here = self.size - 1
            if n_here <= 0:
                return 0.0
            q = 1.0 / (1.0 + d2)
            neg += n_here * q * q * diff
            return n_here * q
        max_width = float(self.width.max()) * 2
        if self.is_leaf or (d2 > 0 and max_width / np.sqrt(d2) < theta):
            q = 1.0 / (1.0 + d2)
            neg += self.size * q * q * diff
            return self.size * q
        s = 0.0
        for ch in self.children:
            s += ch.compute_non_edge_forces(p, theta, neg)
        return s
