"""Brute-force k-nearest-neighbors on device.

The TPU-idiomatic replacement for the reference's tree searches
(NearestNeighborsServer backed by VPTree — nearestneighbor-server, SURVEY
§2.10): compute the [Q,N] distance matrix as one matmul on the MXU and
``jax.lax.top_k`` the negated distances. Exact (not approximate), and for
the dataset sizes the reference serves (<1e6 points) faster on TPU than
tree traversal is on host.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("k", "metric"))
def _knn_kernel(points, queries, k: int, metric: str):
    """[Q,N] distances via ||p||² − 2q·p (+ ||q||², constant per row —
    omitted for ranking) then top-k. Returns (indices [Q,k], dists [Q,k])."""
    if metric == "euclidean":
        p2 = jnp.sum(points * points, axis=1)            # [N]
        q2 = jnp.sum(queries * queries, axis=1)          # [Q]
        d2 = q2[:, None] - 2.0 * queries @ points.T + p2[None, :]
        d2 = jnp.maximum(d2, 0.0)
        neg, idx = jax.lax.top_k(-d2, k)
        return idx, jnp.sqrt(-neg)
    elif metric == "cosine":
        pn = points / (jnp.linalg.norm(points, axis=1, keepdims=True) + 1e-12)
        qn = queries / (jnp.linalg.norm(queries, axis=1, keepdims=True) + 1e-12)
        sim = qn @ pn.T
        top, idx = jax.lax.top_k(sim, k)
        return idx, 1.0 - top
    raise ValueError(f"unknown metric {metric!r}")


@jax.jit
def _manhattan_block(points_blk, queries):
    """[Q,B] L1 distances for one block of points — the [Q,B,D] intermediate
    is bounded by the block size (L1 has no matmul trick like L2)."""
    return jnp.sum(jnp.abs(queries[:, None, :] - points_blk[None, :, :]),
                   axis=-1)


def knn_search(points, queries, k: int, metric: str = "euclidean",
               query_block: int = 1024) -> Tuple[np.ndarray, np.ndarray]:
    """k nearest points for each query; blocks queries to bound the [Q,N]
    matrix in HBM. Returns (indices [Q,k], distances [Q,k])."""
    points = jnp.asarray(points, jnp.float32)
    queries = np.asarray(queries, np.float32)
    if queries.ndim == 1:
        queries = queries[None, :]
    k = min(k, points.shape[0])
    idx_out, d_out = [], []
    for s in range(0, queries.shape[0], query_block):
        q = jnp.asarray(queries[s:s + query_block])
        if metric == "manhattan":
            # bound the [Q,B,D] intermediate to ~4M elements
            point_block = max(1, (1 << 22) //
                              max(1, q.shape[0] * points.shape[1]))
            dists = np.concatenate(
                [np.asarray(_manhattan_block(points[ps:ps + point_block], q))
                 for ps in range(0, points.shape[0], point_block)], axis=1)
            idx = np.argpartition(dists, k - 1, axis=1)[:, :k]
            d = np.take_along_axis(dists, idx, axis=1)
            order = np.argsort(d, axis=1)
            idx, d = (np.take_along_axis(idx, order, axis=1),
                      np.take_along_axis(d, order, axis=1))
        else:
            idx, d = _knn_kernel(points, q, k, metric)
        idx_out.append(np.asarray(idx))
        d_out.append(np.asarray(d))
    return np.concatenate(idx_out), np.concatenate(d_out)


class NearestNeighbors:
    """Index-free exact kNN service (replaces nearestneighbor-server's
    VPTree-backed REST lookups with device matmuls)."""

    def __init__(self, points, metric: str = "euclidean"):
        self.points = jnp.asarray(np.asarray(points, np.float32))
        self.metric = metric

    def query(self, q, k: int = 1) -> Tuple[np.ndarray, np.ndarray]:
        idx, d = knn_search(self.points, np.asarray(q, np.float32), k,
                            metric=self.metric)
        return idx, d

    def query_point_index(self, index: int, k: int = 1):
        """Neighbors of an indexed point, excluding itself
        (ref: NearestNeighborsServer /knn endpoint semantics)."""
        q = np.asarray(self.points[index])[None, :]
        idx, d = knn_search(self.points, q, k + 1, metric=self.metric)
        keep = idx[0] != index
        return idx[0][keep][:k], d[0][keep][:k]
