"""Quad-tree over 2-D points (Barnes-Hut helper).

Equivalent of nearestneighbor-core clustering/quadtree/QuadTree.java:
bounded cells with center-of-mass, subdivide at capacity, used by 2-D
Barnes-Hut t-SNE gradient approximation.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

QT_NODE_CAPACITY = 1  # ref: QuadTree.java QT_NO_DIMS=2, capacity 1


class Cell:
    """Axis-aligned half-width box (ref: quadtree/Cell.java)."""

    def __init__(self, x: float, y: float, hw: float, hh: float):
        self.x, self.y, self.hw, self.hh = x, y, hw, hh

    def contains(self, px: float, py: float) -> bool:
        return (self.x - self.hw <= px <= self.x + self.hw and
                self.y - self.hh <= py <= self.y + self.hh)


class QuadTree:
    """ref: QuadTree.java — insert, subdivide, computeNonEdgeForces."""

    def __init__(self, data: Optional[np.ndarray] = None,
                 cell: Optional[Cell] = None):
        self.cell = cell
        self.size = 0
        self.center_of_mass = np.zeros(2)
        self.point: Optional[np.ndarray] = None
        self.children: List[Optional["QuadTree"]] = [None] * 4
        self.is_leaf = True
        if data is not None:
            data = np.asarray(data, np.float64)
            mean = data.mean(axis=0)
            span = np.maximum(np.abs(data - mean).max(axis=0), 1e-5)
            self.cell = Cell(mean[0], mean[1], span[0] + 1e-5, span[1] + 1e-5)
            for p in data:
                self.insert(p)

    def insert(self, p) -> bool:
        p = np.asarray(p, np.float64)
        if self.cell is not None and not self.cell.contains(p[0], p[1]):
            return False
        # update center of mass
        self.center_of_mass = (self.center_of_mass * self.size + p) / (self.size + 1)
        self.size += 1
        if self.is_leaf and self.point is None:
            self.point = p
            return True
        if self.is_leaf:
            if np.allclose(self.point, p):
                return True  # duplicate point joins this leaf's mass
            self._subdivide()
        for ch in self.children:
            if ch.insert(p):
                return True
        return False

    def _subdivide(self) -> None:
        c = self.cell
        hw, hh = c.hw / 2, c.hh / 2
        quads = [(-hw, hh), (hw, hh), (-hw, -hh), (hw, -hh)]
        self.children = [
            QuadTree(cell=Cell(c.x + dx, c.y + dy, hw, hh))
            for dx, dy in quads]
        old = self.point
        self.point = None
        self.is_leaf = False
        for ch in self.children:
            if ch.insert(old):
                break

    def compute_non_edge_forces(self, point, theta: float,
                                neg: np.ndarray) -> float:
        """Barnes-Hut repulsive force accumulation
        (ref: QuadTree.computeNonEdgeForces). Returns the partial sum_Q."""
        if self.size == 0:
            return 0.0
        p = np.asarray(point, np.float64)
        diff = p - self.center_of_mass
        d2 = float(diff @ diff)
        if self.is_leaf and self.point is not None and \
                np.allclose(self.point, p):
            n_here = self.size - 1  # exclude the query point itself
            if n_here <= 0:
                return 0.0
            q = 1.0 / (1.0 + d2)
            neg += n_here * q * q * diff
            return n_here * q
        max_width = max(self.cell.hw, self.cell.hh) * 2
        if self.is_leaf or (d2 > 0 and max_width / np.sqrt(d2) < theta):
            q = 1.0 / (1.0 + d2)
            neg += self.size * q * q * diff
            return self.size * q
        s = 0.0
        for ch in self.children:
            if ch is not None:
                s += ch.compute_non_edge_forces(p, theta, neg)
        return s
