"""Nearest neighbors + clustering.

TPU-native equivalent of deeplearning4j-nearestneighbors-parent (SURVEY §2.10):
clustering/kdtree/KDTree.java, vptree/VPTree.java (+VPTreeFillSearch),
sptree/SpTree.java, quadtree/QuadTree.java, kmeans/KMeansClustering.java and
the BaseClusteringAlgorithm strategy/condition framework.

The idiomatic TPU fast path is batched brute force — one [Q,N] distance
matrix per query block rides the MXU (knn.py), and the k-means assignment
step is the same kernel. The tree structures (KD/VP/Quad/SP) are host-side:
they exist for API parity, CPU-bound callers, and Barnes-Hut t-SNE.
"""

from deeplearning4j_tpu.clustering.knn import NearestNeighbors, knn_search  # noqa: F401
from deeplearning4j_tpu.clustering.kdtree import KDTree  # noqa: F401
from deeplearning4j_tpu.clustering.vptree import VPTree, VPTreeFillSearch  # noqa: F401
from deeplearning4j_tpu.clustering.quadtree import QuadTree  # noqa: F401
from deeplearning4j_tpu.clustering.sptree import SpTree  # noqa: F401
from deeplearning4j_tpu.clustering.kmeans import KMeansClustering, ClusterSet  # noqa: F401
