"""Input pipeline: device-side prefetch + tail-batch shape bucketing.

The stages feeding the fused multi-step fit path (ISSUE 3):

- `prefetch.DevicePrefetchIterator` — a bounded background stage that
  `jax.device_put`s batches ahead of the consumer so H2D transfer
  overlaps device compute (double/triple buffered; optional
  NamedSharding for the mesh path), with queue-depth / bytes-moved
  telemetry in the global metrics registry.
- `padding.pad_batch` / `padding.with_example_weights` — pad the ragged
  last batch of an epoch to the canonical batch shape with an
  example-weight mask folded into the loss, so a whole fit shares ONE
  compiled train-step shape (exact for row-wise layers; see padding.py
  for the BatchNorm caveat).

The fit loops (`nn/multilayer.py`, `nn/graph.py`, `parallel/wrapper.py`)
wire both under ``fit(..., steps_per_dispatch=K, prefetch=depth)``.
"""

from deeplearning4j_tpu.pipeline.padding import (  # noqa: F401
    example_weight_mask, group_signature, num_real_examples, pad_batch,
    with_example_weights)
from deeplearning4j_tpu.pipeline.prefetch import (  # noqa: F401
    PREFETCH_BATCHES, PREFETCH_BYTES, PREFETCH_DEPTH,
    DevicePrefetchIterator, prefetch_bytes_total)

__all__ = [
    "DevicePrefetchIterator", "PREFETCH_BATCHES", "PREFETCH_BYTES",
    "PREFETCH_DEPTH", "example_weight_mask", "group_signature",
    "num_real_examples", "pad_batch", "prefetch_bytes_total",
    "with_example_weights",
]
