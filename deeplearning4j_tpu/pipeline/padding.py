"""Tail-batch shape bucketing: pad ragged batches to the canonical batch
shape with an example-weight mask folded into the loss.

The last batch of an epoch is usually smaller than the rest. Dispatching
it ragged compiles a SECOND copy of every train step for that one shape
(the recompile hazard the monitoring watcher counts), and under
``steps_per_dispatch > 1`` it makes the K-batch stack impossible.
Instead `pad_batch` repeats a real row up to the canonical row count and
zeroes the padded rows' weight in the labels mask. The loss reduction
(``nn/losses._reduce``) sums ``per_example * mask`` and divides by the
UNMASKED count, so the score and every gradient term of a padded batch
are exactly the math of the unpadded batch: padded rows multiply by 0
into the sum and are excluded from the normalizer. Repeating a real row
(rather than zero-filling) keeps the padded rows' forward activations
finite, so no NaN can leak through ``0 * nan`` in the masked sum.

`example_weight_mask` builds the all-ones mask for a FULL batch: under
padding every batch in a fit carries an explicit example-weight mask, so
the whole epoch shares one jit signature (ones-masked mean == plain
mean, exactly — same sum, same count).

Caveat: layers whose statistics couple rows across the batch
(BatchNormalization batch stats in train mode) see the padded rows, so
with such layers the padded tail is an approximation, not an identity.
Everything row-wise (dense/conv/rnn/attention, all losses) is exact.

Host-side module by design: padding runs BEFORE the device transfer
(in the fit loop or in DevicePrefetchIterator's worker), on numpy.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet

__all__ = ["example_weight_mask", "group_signature", "num_real_examples",
           "pad_batch", "with_example_weights"]


def _pad_rows(a, target: int):
    """Pad axis 0 to `target` rows by repeating row 0 (dict-aware)."""
    if a is None:
        return None
    if isinstance(a, dict):
        return {k: _pad_rows(v, target) for k, v in a.items()}
    a = np.asarray(a)
    n = a.shape[0]
    if n >= target:
        return a
    reps = np.repeat(a[:1], target - n, axis=0)
    return np.concatenate([a, reps], axis=0)


def _zero_rows_from(m, start: int):
    """Zero mask rows >= start (dict-aware); returns a copy."""
    if m is None:
        return None
    if isinstance(m, dict):
        return {k: _zero_rows_from(v, start) for k, v in m.items()}
    m = np.array(m, copy=True)
    m[start:] = 0
    return m


def example_weight_mask(labels):
    """All-ones example-weight mask matching the labels layout: [N, C]
    labels -> [N] mask; [N, C, T] sequence labels -> [N, T] (the
    per-timestep mask RnnOutputLayer folds); dict labels -> dict of
    masks. Built from shape METADATA only — never materializes device
    values."""
    if isinstance(labels, dict):
        return {k: example_weight_mask(v) for k, v in labels.items()}
    shp = tuple(labels.shape)
    if len(shp) >= 3:
        return np.ones((shp[0], shp[-1]), np.float32)
    return np.ones((shp[0],), np.float32)


def with_example_weights(ds: DataSet) -> DataSet:
    """Attach an all-ones example-weight labels mask to a batch that has
    none, so full batches share one jit signature with padded tails.
    Exact: the masked mean over an all-ones mask IS the plain mean."""
    if ds.labels_mask is not None or ds.labels is None:
        return ds
    out = DataSet(ds.features, ds.labels, ds.features_mask,
                  example_weight_mask(ds.labels))
    out.real_examples = num_real_examples(ds)
    return out


def pad_batch(ds: DataSet, target_n: int) -> DataSet:
    """Pad a ragged batch to `target_n` rows; the returned DataSet's
    labels mask zeroes the padded rows (synthesizing an all-ones mask
    first when the batch had none). `num_real_examples` on the result
    still reports the original row count for throughput stats."""
    n = ds.num_examples()
    if n >= target_n:
        return ds
    lmask = ds.labels_mask
    if lmask is None and ds.labels is not None:
        lmask = example_weight_mask(ds.labels)
    lmask = _zero_rows_from(_pad_rows(lmask, target_n), n)
    out = DataSet(_pad_rows(ds.features, target_n),
                  _pad_rows(ds.labels, target_n),
                  _pad_rows(ds.features_mask, target_n),
                  lmask)
    out.real_examples = n
    return out


def num_real_examples(ds: DataSet) -> int:
    """Rows that carry loss weight: the pre-padding count for a padded
    batch, num_examples() otherwise."""
    n = getattr(ds, "real_examples", None)
    return int(n) if n is not None else ds.num_examples()


def _shape_of(x) -> Optional[tuple]:
    if x is None:
        return None
    if isinstance(x, dict):
        return tuple(sorted((k, tuple(v.shape)) for k, v in x.items()))
    return tuple(x.shape)


def group_signature(ds: DataSet) -> tuple:
    """Hashable stacking signature of a batch: array shapes and mask
    presence. Batches are fused into one lax.scan dispatch only when
    their signatures are identical — anything else (ragged shape that
    escaped padding, mixed mask presence) falls back to the per-batch
    step rather than forcing a retrace or a semantic change."""
    return (_shape_of(ds.features), _shape_of(ds.labels),
            _shape_of(ds.features_mask), _shape_of(ds.labels_mask))
