"""Device-side input pipeline: H2D prefetch ahead of the consumer.

`AsyncDataSetIterator` (datasets/iterators.py — the seed's port of
DL4J's ADSI) overlaps host ETL with device compute, but the host→device
copy itself still happens synchronously at dispatch: the fit loop's
`jnp.asarray(ds.features)` stages the transfer on the consumer thread
while the accelerator idles. `DevicePrefetchIterator` moves the copy
into a bounded background stage: a worker thread calls
`jax.device_put` (optionally with a `NamedSharding` for the mesh path)
`prefetch` batches ahead of the consumer, so the transfer for batches
N+1..N+depth overlaps the compute of batch N — double/triple buffering
by queue depth, the device-side half DL4J's MagicQueue did with
device-affinity host buffers.

The stop/sentinel/error protocol is deliberately IDENTICAL to
AsyncDataSetIterator (tested for parity): bounded `put` with a stop
check so an abandoned consumer can't pin the worker, a sentinel that
carries end-of-stream, and base-iterator exceptions re-raised in the
consumer.

Telemetry (global metrics registry, monitoring/):

- ``dl4jtpu_prefetch_queue_depth`` (gauge): batches currently staged on
  device ahead of the consumer.
- ``dl4jtpu_prefetch_h2d_bytes_total`` (counter): bytes handed to
  `jax.device_put` by prefetch stages — the bench records carry this so
  the perf trajectory shows how much transfer left the dispatch path.
- ``dl4jtpu_prefetch_batches_total`` (counter): batches transferred.

jax is imported lazily (first use) so constructing the iterator — or
importing this module from a bench failure path — never initializes a
backend.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Callable, List, Optional, Union

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator
from deeplearning4j_tpu.monitoring.metrics import (
    MetricsRegistry, global_registry)
from deeplearning4j_tpu.pipeline.padding import num_real_examples, pad_batch
from deeplearning4j_tpu.resilience.retry import RetryPolicy, retry_call

log = logging.getLogger(__name__)

PREFETCH_DEPTH = "dl4jtpu_prefetch_queue_depth"
PREFETCH_BYTES = "dl4jtpu_prefetch_h2d_bytes_total"
PREFETCH_BATCHES = "dl4jtpu_prefetch_batches_total"

__all__ = ["DevicePrefetchIterator", "PREFETCH_BATCHES", "PREFETCH_BYTES",
           "PREFETCH_DEPTH", "prefetch_bytes_total"]


class _BaseIteratorDead(Exception):
    """A generator-backed base died on an error: retrying can never
    succeed. Deliberately NOT a typical retry_on type, so the retry
    layer propagates it immediately instead of burning its backoff
    budget on a corpse."""

    def __init__(self, original: BaseException):
        super().__init__(repr(original))
        self.original = original


def _nbytes(x) -> int:
    if x is None:
        return 0
    if isinstance(x, dict):
        return sum(_nbytes(v) for v in x.values())
    n = getattr(x, "nbytes", None)
    return int(n) if n is not None else 0


def prefetch_bytes_total(registry: Optional[MetricsRegistry] = None) -> float:
    """Total H2D bytes moved by prefetch stages this process (0.0 before
    any ran). Pure registry read — safe on bench failure paths."""
    r = registry or global_registry()
    c = r.get(PREFETCH_BYTES)
    if c is None:
        return 0.0
    try:
        return float(c.value())
    except Exception:  # noqa: BLE001 — a metrics read must never raise here
        return 0.0


class DevicePrefetchIterator(DataSetIterator):
    """Background device-transfer stage over a base DataSetIterator.

    Args:
        base: the host-side iterator to consume.
        prefetch: queue depth — how many batches may sit transferred (or
            in flight) ahead of the consumer. 2 = double buffering.
        mesh / data_axis: when given, every array is placed with
            ``NamedSharding(mesh, P(data_axis, None, ...))`` so the
            batch lands pre-sharded for SPMD fit loops (ParallelWrapper
            allreduce mode) instead of being resharded at dispatch.
        transform: optional host-side ``DataSet -> DataSet`` hook run in
            the worker before the transfer (e.g. the wrapper's
            mesh-divisibility trim).
        pad_to: tail-batch bucketing in the pipeline stage: an int pads
            every smaller batch to that row count (``pipeline.padding``
            mask semantics); ``"auto"`` uses the first batch of each
            pass as the canonical size. Padding here — BEFORE the
            transfer — keeps the fit loop from ever padding
            device-resident arrays (a D2H round-trip).
        pad_when: optional host-side predicate gating `pad_to` per
            batch (e.g. ComputationGraph's mask-shadowing exemption);
            batches it rejects pass through ragged.
        retry: optional ``resilience.retry.RetryPolicy`` — the worker
            retries a failed base-iterator pull (``policy.retry_on``
            exceptions only) with bounded backoff before surfacing the
            error, so a transiently flaky input source (remote FS
            hiccup, a lock-contended reader) doesn't kill the epoch.
    """

    _SENTINEL = object()

    def __init__(self, base: DataSetIterator, prefetch: int = 2,
                 mesh=None, data_axis: str = "data",
                 transform: Optional[Callable[[DataSet], DataSet]] = None,
                 pad_to: Union[int, str, None] = None,
                 pad_when: Optional[Callable[[DataSet], bool]] = None,
                 retry: Optional[RetryPolicy] = None,
                 registry: Optional[MetricsRegistry] = None):
        if prefetch < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {prefetch}")
        if pad_to is not None and pad_to != "auto" and int(pad_to) < 1:
            raise ValueError(f"pad_to must be >= 1 or 'auto', got {pad_to}")
        self.base = base
        self.prefetch = prefetch
        self.mesh = mesh
        self.data_axis = data_axis
        self.transform = transform
        self.pad_to = pad_to
        self.pad_when = pad_when
        self.retry = retry
        self._registry = registry
        self._last_thread: Optional[threading.Thread] = None
        # most recent worker error of the most recent pass (a list cell so
        # the worker thread appends instead of assigning shared state);
        # consult it when a pass ended early after an abandoned consumer
        self._err_holder: List[BaseException] = []
        # durable-cursor bookkeeping: CONSUMER-side position (the worker
        # pulls ahead of the fit loop, so the base iterator's own
        # counters overstate what training actually consumed)
        self._pass_index = 0
        self._consumed = 0
        self._resume_pos = 0
        self._resume_armed = False
        self._in_pass = False

    @property
    def last_worker_error(self) -> Optional[BaseException]:
        """Error that killed the most recent pass's worker, if any —
        ALSO set when the consumer was already gone, so an error can
        never vanish silently (worker-shutdown audit)."""
        return self._err_holder[0] if self._err_holder else None

    def reset(self):
        self.base.reset()

    # -- durable cursor (see datasets.iterators.DataSetIterator) --------
    def state(self):
        """Consumer-visible cursor: batches the FIT LOOP pulled, not the
        (further ahead) batches the worker staged — the difference is
        exactly the prefetch depth, which must be re-transferred on
        resume, not skipped."""
        if self._resume_armed:
            return {"epoch": self._pass_index, "pos": self._resume_pos}
        if self._in_pass:
            return {"epoch": self._pass_index - 1, "pos": self._consumed}
        # between (or before any) passes: the BASE owns the pass index —
        # a fresh wrapper's local counter is 0 even when the base was
        # aligned/advanced to a later epoch, and the next pass seeds its
        # shuffle from the base's counter (see __iter__)
        state_fn = getattr(self.base, "state", None)
        if state_fn is not None:
            try:
                return {"epoch": int(state_fn()["epoch"]), "pos": 0}
            except Exception:  # noqa: BLE001 — cursor read is best-effort
                pass
        return {"epoch": self._pass_index, "pos": 0}

    def restore_state(self, state):
        """Delegates to the base iterator (the stage is a 1:1 per-batch
        transform, so consumer position == base position); requires the
        base to support the cursor protocol."""
        restore = getattr(self.base, "restore_state", None)
        if restore is None:
            raise NotImplementedError(
                f"prefetch base {type(self.base).__name__} has no "
                f"restore_state(): cannot fast-forward exactly")
        restore(state)
        self._pass_index = int(state.get("epoch", 0))
        self._resume_pos = int(state.get("pos", 0))
        self._resume_armed = True
        self._in_pass = False

    # ------------------------------------------------------------------
    def _sharding_for(self, arr):
        if self.mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P
        spec = P(self.data_axis, *([None] * (np.ndim(arr) - 1)))
        return NamedSharding(self.mesh, spec)

    def _put(self, x):
        """jax.device_put, dict-aware; the one H2D call of the stage."""
        if x is None:
            return None
        if isinstance(x, dict):
            return {k: self._put(v) for k, v in x.items()}
        import jax
        return jax.device_put(x, self._sharding_for(x))

    def _stage(self, ds: DataSet) -> DataSet:
        out = DataSet(self._put(ds.features), self._put(ds.labels),
                      self._put(ds.features_mask), self._put(ds.labels_mask))
        out.real_examples = num_real_examples(ds)
        return out

    # ------------------------------------------------------------------
    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        err: List[BaseException] = []
        self._err_holder = err  # publish THIS pass's error slot
        # cursor bookkeeping: a restored pass starts mid-stream; an
        # UNRESTORED pass takes its index from the BASE iterator's own
        # cursor when it exposes one — the base drives the shuffle seed,
        # and its passes need not start at 0 (fit aligns internal
        # iterators to the absolute epoch count)
        if self._resume_armed:
            self._resume_armed = False
            start_pass = self._pass_index
        else:
            start_pass = self._pass_index
            state_fn = getattr(self.base, "state", None)
            if state_fn is not None:
                try:
                    start_pass = int(state_fn()["epoch"])
                except Exception:  # noqa: BLE001 — labeling is best-effort
                    pass
        self._consumed = self._resume_pos
        self._resume_pos = 0
        self._pass_index = start_pass + 1
        self._in_pass = True
        stop = threading.Event()
        r = self._registry or global_registry()
        depth = r.gauge(PREFETCH_DEPTH,
                        "Batches staged on device ahead of the consumer")
        h2d_bytes = r.counter(PREFETCH_BYTES,
                              "Host->device bytes moved by prefetch stages")
        batches = r.counter(PREFETCH_BATCHES,
                            "Batches transferred by prefetch stages")
        # canonical row count for this pass ("auto" resolves per pass so
        # a re-iterated epoch re-locks onto its own first batch)
        target = [self.pad_to if isinstance(self.pad_to, int) else None]
        _done = object()

        def worker():
            delivered = False  # sentinel actually enqueued
            try:
                import types

                it = iter(self.base)
                # only GENERATORS die on their first error; an object
                # iterator that raised can legitimately continue — or
                # legitimately end — on the next pull
                gen_backed = isinstance(it, types.GeneratorType)
                failed: List[BaseException] = []

                def pull():
                    # StopIteration must not hit the retry layer (a
                    # retry_on of Exception would "retry" end-of-stream)
                    try:
                        ds = next(it)
                    except StopIteration:
                        if failed and gen_backed:
                            # a generator-backed base dies on its first
                            # error: this StopIteration is the corpse,
                            # not a clean end-of-stream — surface the
                            # original failure (non-retryably: further
                            # attempts can never succeed) instead of
                            # silently truncating the epoch
                            raise _BaseIteratorDead(failed[0]) from None
                        return _done
                    except BaseException as e:
                        failed.append(e)
                        raise
                    failed.clear()
                    return ds

                while True:
                    if self.retry is None:
                        ds = pull()
                    else:
                        try:
                            ds = retry_call(pull, policy=self.retry,
                                            op="prefetch-pull")
                        except _BaseIteratorDead as e:
                            raise e.original from None
                    if ds is _done:
                        break
                    if self.transform is not None:
                        ds = self.transform(ds)
                    if self.pad_to is not None:
                        if target[0] is None:
                            target[0] = ds.num_examples()
                        if ds.num_examples() < target[0] and (
                                self.pad_when is None or self.pad_when(ds)):
                            ds = pad_batch(ds, target[0])
                    n = _nbytes(ds.features) + _nbytes(ds.labels) + \
                        _nbytes(ds.features_mask) + _nbytes(ds.labels_mask)
                    dev = self._stage(ds)
                    h2d_bytes.inc(n)
                    batches.inc()
                    # bounded put with a stop check so an abandoned
                    # consumer (early break) can't pin the worker forever
                    while not stop.is_set():
                        try:
                            q.put(dev, timeout=0.1)
                            depth.set(q.qsize())
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
            except BaseException as e:  # surface worker errors to consumer
                err.append(e)
            finally:
                while not stop.is_set():
                    try:
                        q.put(self._SENTINEL, timeout=0.1)
                        delivered = True
                        break
                    except queue.Full:
                        continue
                if err and not delivered:
                    # consumer left before the error could be handed over
                    # (stop beat the sentinel put): the guarantee is that
                    # no worker error ever vanishes — it stays readable on
                    # last_worker_error and lands in the log
                    log.warning("prefetch worker error after consumer "
                                "detached: %r", err[0])

        t = threading.Thread(target=worker, daemon=True,
                             name="device-prefetch")
        self._last_thread = t
        t.start()
        try:
            while True:
                try:
                    # bounded get + liveness check: if the worker died in
                    # a way that lost its sentinel (full queue + abandoned
                    # pass), the consumer must not block forever
                    item = q.get(timeout=0.2)
                except queue.Empty:
                    if not t.is_alive():
                        # worker exited between our timeout and this
                        # check — it may have staged tail batches (and
                        # the sentinel) in that gap; drain them before
                        # settling, or the epoch silently loses batches
                        drained = []
                        while True:
                            try:
                                tail = q.get_nowait()
                            except queue.Empty:
                                break
                            if tail is self._SENTINEL:
                                break
                            drained.append(tail)
                        for tail in drained:
                            self._consumed += 1
                            yield tail
                        if err:
                            raise err[0]
                        self._in_pass = False
                        return  # worker gone, stream fully drained
                    continue
                depth.set(q.qsize())
                if item is self._SENTINEL:
                    if err:
                        raise err[0]
                    self._in_pass = False
                    return
                self._consumed += 1
                yield item
        finally:
            # generator closed (break/GC): release the worker thread
            stop.set()
