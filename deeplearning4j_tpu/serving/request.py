"""Generation requests and their streaming response handles.

A submitted prompt becomes a ``GenerationRequest`` (the engine-side
descriptor riding the admission queue and a slot) paired with a
``GenerationStream`` (the caller-side handle): tokens stream into the
handle as each decode dispatch retires, so time-to-first-token is one
prefill away from admission instead of a whole batch away.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.serving.errors import InferenceTimeout

_DONE = object()     # terminal queue sentinel


class GenerationStream:
    """Caller-side handle for one generation request.

    Tokens arrive as they are generated: iterate the handle to consume
    them (blocks until the engine produces the next one; ends at
    retirement, re-raising the request's failure if it has one), or call
    :meth:`result` for the classic one-shot ``sample_stream`` contract
    (full id list, prompt included). ``finish_reason`` is one of
    ``stop`` / ``length`` / ``capacity`` / ``cancelled`` / ``error``
    once done.

    The engine guarantees a terminal event on every path — retirement,
    request failure, engine shutdown — so consumers never block forever
    on a dead server (the ParallelInference no-hung-callers contract).
    """

    def __init__(self, prompt):
        self.prompt = list(prompt)
        self._ids: List[int] = list(prompt)
        self._q: "queue.Queue" = queue.Queue()
        self._done = threading.Event()
        self._error: Optional[BaseException] = None
        self.finish_reason: Optional[str] = None
        self.cancelled = False
        #: seconds from submit to first token / to admission (set by the
        #: engine; None until known)
        self.ttft_s: Optional[float] = None
        self.queue_wait_s: Optional[float] = None

    # -- engine side ---------------------------------------------------
    def _push(self, token: int) -> None:
        self._ids.append(int(token))
        self._q.put(int(token))

    def _finish(self, reason: str) -> None:
        self.finish_reason = reason
        self._done.set()
        self._q.put(_DONE)

    def _fail(self, exc: BaseException, reason: str = "error") -> None:
        self._error = exc
        self._finish(reason)

    # -- caller side ---------------------------------------------------
    @property
    def done(self) -> bool:
        return self._done.is_set()

    @property
    def error(self) -> Optional[BaseException]:
        return self._error

    @property
    def ids(self) -> List[int]:
        """Snapshot of prompt + tokens generated so far."""
        return list(self._ids)

    @property
    def generated(self) -> List[int]:
        """Snapshot of the tokens generated so far (prompt excluded)."""
        return list(self._ids[len(self.prompt):])

    def cancel(self) -> None:
        """Ask the engine to retire this request at its next step (frees
        the slot; a queued request is dropped at pop). Iterators/result()
        then raise RequestCancelled."""
        self.cancelled = True

    def __iter__(self):
        while True:
            try:
                item = self._q.get(timeout=0.1)
            except queue.Empty:
                # a finished, fully-drained stream (e.g. a SECOND
                # iteration after the terminal sentinel was consumed)
                # must end, not block forever
                if self._done.is_set():
                    if self._error is not None:
                        raise self._error
                    return
                continue
            if item is _DONE:
                if self._error is not None:
                    raise self._error
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the request retires; returns prompt + generated
        ids (the ``sample_stream`` return contract). Raises the
        request's failure, or InferenceTimeout if `timeout` seconds pass
        first."""
        if not self._done.wait(timeout):
            raise InferenceTimeout(
                f"no result within {timeout:g}s "
                f"(generated {len(self._ids) - len(self.prompt)} tokens)")
        if self._error is not None:
            raise self._error
        return list(self._ids)


class GenerationRequest:
    """Engine-side descriptor: sampling config, stop rules, deadline and
    priority for one prompt, plus the slot-lifecycle scratch the engine
    tracks (pending token, rng, timing marks)."""

    __slots__ = ("prompt", "steps", "want", "temperature", "top_k",
                 "top_p", "stop_tokens", "rng", "deadline", "priority",
                 "handle", "submit_t", "pending_token", "last_token_t")

    def __init__(self, prompt, steps: int, *, temperature: float = 1.0,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 stop_tokens=(), rng=None,
                 max_length: Optional[int] = None,
                 deadline: Optional[float] = None, priority: int = 0):
        self.prompt = [int(t) for t in prompt]
        self.steps = int(steps)
        self.want = len(self.prompt) + self.steps
        if max_length is not None:
            self.want = min(self.want, int(max_length))
        self.temperature = float(temperature)
        self.top_k = top_k
        self.top_p = top_p
        self.stop_tokens = frozenset(int(t) for t in stop_tokens)
        self.rng = rng if rng is not None else np.random.default_rng(0)
        self.deadline = deadline          # monotonic seconds, or None
        self.priority = int(priority)
        self.handle = GenerationStream(self.prompt)
        self.submit_t = time.monotonic()
        self.pending_token: Optional[int] = None
        self.last_token_t: Optional[float] = None
