"""Generation requests, streaming handles, and the request ledger.

A submitted prompt becomes a ``GenerationRequest`` (the engine-side
descriptor riding the admission queue and a slot) paired with a
``GenerationStream`` (the caller-side handle): tokens stream into the
handle as each decode dispatch retires, so time-to-first-token is one
prefill away from admission instead of a whole batch away.

``RequestLedgerEntry`` is the PUBLIC, versioned form of the PR 9
insight that the host side already holds everything needed to rebuild
any in-flight request bit-identically: the prompt, the committed token
ids (whose last element is the pending, not-yet-fed token), the
per-request numpy ``Generator`` (advanced exactly once per draw, never
by the device), and the sampling config. Supervisor recovery
(``EngineSupervisor``) and fleet migration (``serving/fleet``) both
move requests as ledger entries through ONE engine code path
(``GenerationEngine.export_ledger`` / ``admit_from_ledger``) instead
of two hand-synced copies of the rebuild payload.

``RequestTrace`` (ISSUE 15) is the per-request observability half of
the same insight: every lifecycle transition a request goes through —
submit, queue pop, prefill (with its jit bucket), seat, first token,
periodic decode rollups, shed / early rejection, migration hops,
supervisor re-admissions, retirement — lands as a timestamped record
on the request's handle, so "why was THIS request slow" decomposes
into queue wait vs prefill vs decode vs recovery instead of being one
opaque TTFT histogram sample. Traces are host-side, bounded, and ride
the ledger payload across replicas (LEDGER_VERSION 2; v1 payloads
still admit, trace-less), so a migrated stream's history survives the
hop. ``ttft_attribution`` aggregates a window of traces into the
queue/prefill/placement decomposition the bench serve legs record.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.monitoring.events import events_enabled
from deeplearning4j_tpu.serving.errors import InferenceTimeout

#: format version stamped into every exported ledger entry; bump on any
#: change to the payload fields or their meaning.
#: v1: prompt/ids/rng/config.  v2 (ISSUE 15): + the request trace.
LEDGER_VERSION = 2

_DONE = object()     # terminal queue sentinel


def rng_state_payload(rng) -> dict:
    """JSON-able snapshot of a numpy ``Generator``'s bit-generator
    state — the per-token consistency record the cross-process stream
    journal carries (``serving/fleet/transport.py``): a re-placement
    re-primes from (committed ids, this state) and continues
    bit-identically. Same normalization as the full ledger payload
    (``RequestLedgerEntry.payload``'s ``rng_state`` field); the state
    setter accepts the list form back."""
    return RequestLedgerEntry._jsonable(rng.bit_generator.state)

#: decode progress lands on a trace as ROLLUPS — one record per this
#: many committed tokens (plus a flush at retirement) — never one
#: record per token: a 4k-token stream is ~128 trace records, not 4k
TRACE_ROLLUP_EVERY = 32
#: per-trace record cap; overflow drops (counted) rather than growing
TRACE_MAX_RECORDS = 256


class RequestTrace:
    """Bounded host-side trace of one request's lifecycle.

    Records are small dicts ``{"event", "t", ...attrs}`` with ``t`` =
    wall-clock ``time.time()`` (wall, not monotonic, deliberately: a
    trace crosses process boundaries inside the ledger payload, and
    monotonic clocks do not). Thread-safe — the submit caller, the
    engine step thread, and a fleet poll thread may all touch one
    request. All methods are no-ops while
    ``monitoring.events.set_events_enabled(False)`` holds, except reads.

    ``breakdown()`` is the attribution contract: where did this
    request's wall time go — queue wait, prefill, decode — and how many
    migration hops / supervisor rebuilds did it survive.
    """

    __slots__ = ("records", "dropped", "_pend_tokens", "_pend_accepted",
                 "_pend_proposed", "_mu")

    def __init__(self, records: Optional[List[Dict[str, Any]]] = None,
                 dropped: int = 0):
        self.records: List[Dict[str, Any]] = records if records is not None \
            else []
        self.dropped = int(dropped)
        self._pend_tokens = 0
        self._pend_accepted = 0
        self._pend_proposed = 0
        self._mu = threading.Lock()

    # -- write side (engine / router / migration) ----------------------
    def record(self, event: str, **attrs) -> None:
        if not events_enabled():
            return
        rec = {"event": event, "t": time.time()}
        rec.update(attrs)
        with self._mu:
            if len(self.records) >= TRACE_MAX_RECORDS:
                if event == "decode":
                    self.dropped += 1
                    return
                # lifecycle records (retire, migrate, rebuild, ...)
                # outrank decode-progress history: evict the oldest
                # rollup so a very long stream still ends with its
                # retirement cause and hops on the trace
                for i, r in enumerate(self.records):
                    if r["event"] == "decode":
                        del self.records[i]
                        self.dropped += 1
                        break
                else:
                    self.dropped += 1
                    return
            self.records.append(rec)

    def rollup(self, tokens: int, accepted: Optional[int] = None,
               proposed: Optional[int] = None) -> None:
        """Accumulate decode progress; emits one ``decode`` record per
        ``TRACE_ROLLUP_EVERY`` committed tokens (the no-per-token-spam
        contract). Speculative steps pass accepted/proposed counts."""
        if not events_enabled():
            return
        with self._mu:
            self._pend_tokens += int(tokens)
            if accepted is not None:
                self._pend_accepted += int(accepted)
            if proposed is not None:
                self._pend_proposed += int(proposed)
            flush = self._pend_tokens >= TRACE_ROLLUP_EVERY
        if flush:
            self.flush_rollup()

    def flush_rollup(self) -> None:
        """Materialize any pending rollup (retirement / export calls
        this so a short stream still shows its decode record)."""
        with self._mu:
            n = self._pend_tokens
            acc, prop = self._pend_accepted, self._pend_proposed
            self._pend_tokens = 0
            self._pend_accepted = self._pend_proposed = 0
        if n:
            extra = {}
            if prop:
                extra = {"accepted": acc, "proposed": prop}
            self.record("decode", tokens=n, **extra)

    # -- read side -----------------------------------------------------
    def events(self) -> List[Dict[str, Any]]:
        """Snapshot of the trace records (oldest first)."""
        with self._mu:
            return [dict(r) for r in self.records]

    def replicas(self) -> List[str]:
        """Engine labels this request was ever seated (or re-primed)
        on, in first-seen order — a migrated stream lists both sides of
        the hop."""
        seen: List[str] = []
        for r in self.events():
            eng = r.get("engine")
            if eng is not None and eng not in seen:
                seen.append(eng)
        return seen

    def breakdown(self) -> Dict[str, Any]:
        """Decompose the trace into the attribution dict:

        - ``queue_wait_s``: sum over every enqueue→pop span (a request
          can ride a queue more than once — requeue, migration);
          ``queue_wait_ttft_s`` is the subset accrued BEFORE the first
          token (what TTFT attribution may count — a migrated active
          stream's target-queue wait is recovery cost, not
          time-to-first-token);
        - ``prefill_s``: sum over prefill_start→prefill_end spans
          (re-prime prefills after a rebuild/migration included;
          ``prefill_ttft_s`` is the pre-first-token subset);
        - ``decode_s``: first token → retirement, MINUS any prefill
          spans inside that window (re-primes are recovery cost, not
          decode) — so the components partition the request's life;
        - ``migrations`` / ``rebuilds``: hop and re-admission counts;
        - ``ttft_s``: submit → first token when both were traced.
        """
        evs = self.events()
        out: Dict[str, Any] = {"queue_wait_s": 0.0,
                               "queue_wait_ttft_s": 0.0,
                               "prefill_s": 0.0, "prefill_ttft_s": 0.0,
                               "decode_s": None, "migrations": 0,
                               "rebuilds": 0, "ttft_s": None}
        enq_t: Optional[float] = None
        pre_t: Optional[float] = None
        submit_t: Optional[float] = None
        first_t: Optional[float] = None
        end_t: Optional[float] = None
        re_prefill = 0.0
        for r in evs:
            ev, t = r["event"], r["t"]
            if ev == "submit":
                submit_t = t
                enq_t = t
            elif ev in ("requeue", "migrate"):
                if ev == "migrate":
                    out["migrations"] += 1
                enq_t = t
            elif ev == "queue_pop":
                if enq_t is not None:
                    span = max(0.0, t - enq_t)
                    out["queue_wait_s"] += span
                    if first_t is None:
                        out["queue_wait_ttft_s"] += span
                    enq_t = None
            elif ev == "prefill_start":
                pre_t = t
            elif ev == "prefill_end":
                if pre_t is not None:
                    span = max(0.0, t - pre_t)
                    out["prefill_s"] += span
                    if first_t is not None:
                        re_prefill += span
                    else:
                        out["prefill_ttft_s"] += span
                    pre_t = None
            elif ev == "first_token":
                if first_t is None:
                    first_t = t
            elif ev == "rebuild":
                out["rebuilds"] += 1
            elif ev == "retire":
                end_t = t
        if submit_t is not None and first_t is not None:
            out["ttft_s"] = max(0.0, first_t - submit_t)
        if first_t is not None and end_t is not None:
            out["decode_s"] = max(0.0, end_t - first_t - re_prefill)
        return out

    # -- the ledger wire form ------------------------------------------
    def to_payload(self) -> dict:
        self.flush_rollup()
        with self._mu:
            return {"records": [dict(r) for r in self.records],
                    "dropped": self.dropped}

    @classmethod
    def from_payload(cls, payload: Optional[dict]) -> "RequestTrace":
        if not payload:
            return cls()
        return cls(records=[dict(r) for r in payload.get("records", ())],
                   dropped=int(payload.get("dropped", 0)))


def ttft_attribution(traces: Iterable[RequestTrace]) -> Dict[str, Any]:
    """Aggregate a window of request traces into the TTFT attribution
    dict the bench serve legs stamp into every record: mean observed
    TTFT decomposed into queue wait + prefill + placement residue
    ("other": submit-side routing, admission bookkeeping, the dispatch
    the first token rode). Traces without a first token (shed, early
    rejected, failed pre-prefill) are excluded from the TTFT means but
    counted. All values are SECONDS; the caller renders units."""
    n = n_ttft = 0
    ttft = queue_w = prefill = 0.0
    migrations = rebuilds = 0
    for tr in traces:
        b = tr.breakdown()
        n += 1
        migrations += b["migrations"]
        rebuilds += b["rebuilds"]
        if b["ttft_s"] is None:
            continue
        n_ttft += 1
        ttft += b["ttft_s"]
        # only queue wait accrued BEFORE the first token counts toward
        # TTFT — a migrated stream's later target-queue ride is
        # recovery cost, not admission latency
        q = min(b["queue_wait_ttft_s"], b["ttft_s"])
        queue_w += q
        # prefill inside the TTFT window only (re-primes come later)
        prefill += min(b["prefill_ttft_s"], max(0.0, b["ttft_s"] - q))
    if n_ttft == 0:
        return {"requests": n, "with_ttft": 0}
    other = max(0.0, (ttft - queue_w - prefill) / n_ttft)
    return {"requests": n, "with_ttft": n_ttft,
            "ttft_mean_s": round(ttft / n_ttft, 6),
            "queue_wait_mean_s": round(queue_w / n_ttft, 6),
            "prefill_mean_s": round(prefill / n_ttft, 6),
            "other_mean_s": round(other, 6),
            "migrations": migrations, "rebuilds": rebuilds}


class GenerationStream:
    """Caller-side handle for one generation request.

    Tokens arrive as they are generated: iterate the handle to consume
    them (blocks until the engine produces the next one; ends at
    retirement, re-raising the request's failure if it has one), or call
    :meth:`result` for the classic one-shot ``sample_stream`` contract
    (full id list, prompt included). ``finish_reason`` is one of
    ``stop`` / ``length`` / ``capacity`` / ``cancelled`` / ``error``
    once done.

    The engine guarantees a terminal event on every path — retirement,
    request failure, engine shutdown — so consumers never block forever
    on a dead server (the ParallelInference no-hung-callers contract).
    """

    def __init__(self, prompt):
        self.prompt = list(prompt)
        self._ids: List[int] = list(prompt)
        self._q: "queue.Queue" = queue.Queue()
        self._done = threading.Event()
        self._error: Optional[BaseException] = None
        self.finish_reason: Optional[str] = None
        self.cancelled = False
        #: seconds from submit to first token / to admission (set by the
        #: engine; None until known)
        self.ttft_s: Optional[float] = None
        self.queue_wait_s: Optional[float] = None
        self._trace = RequestTrace()

    def trace(self) -> RequestTrace:
        """This request's lifecycle trace (live — it keeps growing
        until retirement; ``breakdown()`` any time)."""
        return self._trace

    # -- engine side ---------------------------------------------------
    def _push(self, token: int) -> None:
        self._ids.append(int(token))
        self._q.put(int(token))

    def _finish(self, reason: str) -> None:
        self.finish_reason = reason
        self._trace.flush_rollup()
        self._trace.record("retire", reason=reason,
                           **({"error": repr(self._error)}
                              if self._error is not None else {}))
        self._done.set()
        self._q.put(_DONE)

    def _fail(self, exc: BaseException, reason: str = "error") -> None:
        self._error = exc
        self._finish(reason)

    # -- relay side (cross-process fleet transport) --------------------
    def relay_token(self, token: int) -> None:
        """Public engine-side push for a TRANSPORT RELAY: the
        out-of-process fleet router plays the engine's role for a
        handle whose real engine lives in another process, pushing each
        journaled committed token into the local stream
        (``serving/fleet/transport.py``). Identical semantics to the
        in-process engine push — the caller's iterator/result() cannot
        tell a relayed stream from a local one."""
        self._push(token)

    def relay_finish(self, reason: str,
                     error: Optional[BaseException] = None) -> None:
        """Transport-relay terminal event: finish (or fail) the local
        handle when the remote replica journals the request's
        retirement. No-op if the handle already has a terminal event
        (duplicate journal delivery must stay idempotent)."""
        if self._done.is_set():
            return
        if error is not None:
            self._fail(error, reason)
        else:
            self._finish(reason)

    # -- caller side ---------------------------------------------------
    @property
    def done(self) -> bool:
        return self._done.is_set()

    @property
    def error(self) -> Optional[BaseException]:
        return self._error

    @property
    def ids(self) -> List[int]:
        """Snapshot of prompt + tokens generated so far."""
        return list(self._ids)

    @property
    def generated(self) -> List[int]:
        """Snapshot of the tokens generated so far (prompt excluded)."""
        return list(self._ids[len(self.prompt):])

    def cancel(self) -> None:
        """Ask the engine to retire this request at its next step (frees
        the slot; a queued request is dropped at pop). Iterators/result()
        then raise RequestCancelled."""
        self.cancelled = True

    def __iter__(self):
        while True:
            try:
                item = self._q.get(timeout=0.1)
            except queue.Empty:
                # a finished, fully-drained stream (e.g. a SECOND
                # iteration after the terminal sentinel was consumed)
                # must end, not block forever
                if self._done.is_set():
                    if self._error is not None:
                        raise self._error
                    return
                continue
            if item is _DONE:
                if self._error is not None:
                    raise self._error
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the request retires; returns prompt + generated
        ids (the ``sample_stream`` return contract). Raises the
        request's failure, or InferenceTimeout if `timeout` seconds pass
        first."""
        if not self._done.wait(timeout):
            raise InferenceTimeout(
                f"no result within {timeout:g}s "
                f"(generated {len(self._ids) - len(self.prompt)} tokens)")
        if self._error is not None:
            raise self._error
        return list(self._ids)


class GenerationRequest:
    """Engine-side descriptor: sampling config, stop rules, deadline and
    priority for one prompt, plus the slot-lifecycle scratch the engine
    tracks (pending token, rng, timing marks)."""

    __slots__ = ("prompt", "steps", "want", "temperature", "top_k",
                 "top_p", "stop_tokens", "rng", "deadline", "priority",
                 "handle", "submit_t", "pending_token", "last_token_t")

    def __init__(self, prompt, steps: int, *, temperature: float = 1.0,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 stop_tokens=(), rng=None,
                 max_length: Optional[int] = None,
                 deadline: Optional[float] = None, priority: int = 0):
        self.prompt = [int(t) for t in prompt]
        self.steps = int(steps)
        self.want = len(self.prompt) + self.steps
        if max_length is not None:
            self.want = min(self.want, int(max_length))
        self.temperature = float(temperature)
        self.top_k = top_k
        self.top_p = top_p
        self.stop_tokens = frozenset(int(t) for t in stop_tokens)
        self.rng = rng if rng is not None else np.random.default_rng(0)
        self.deadline = deadline          # monotonic seconds, or None
        self.priority = int(priority)
        self.handle = GenerationStream(self.prompt)
        self.submit_t = time.monotonic()
        self.pending_token: Optional[int] = None
        self.last_token_t: Optional[float] = None
        self.handle._trace.record("submit", prompt_len=len(self.prompt),
                                  steps=self.steps,
                                  priority=self.priority)

    @property
    def trace(self) -> RequestTrace:
        """The handle's lifecycle trace (engine-side shorthand)."""
        return self.handle._trace

    @property
    def streamed(self) -> bool:
        """Whether any token has streamed: THE re-admission mode switch
        (re-prime ``ids[:-1]`` with the pending token vs a fresh
        admission) — one definition for the admission pop, the
        supervisor rebuild, and ``admit_from_ledger``. A fresh request
        can never read True before its admission draw (tokens only
        appear at admission)."""
        return len(self.handle._ids) > len(self.prompt)


@dataclasses.dataclass(frozen=True)
class RequestLedgerEntry:
    """One in-flight request as an exportable ledger record.

    ``ids`` is the capture-time snapshot of prompt + committed tokens;
    when the request has streamed at all, ``ids[-1]`` is the PENDING
    token (drawn but never yet fed to the model), so a re-admission
    re-primes ``ids[:-1]`` and the next dispatch recomputes exactly the
    distribution the unperturbed run would have seen. ``phase`` records
    where the request lived at export: ``active`` (seated in a slot),
    ``seating`` (the pop-to-seat handoff window — the request the
    PR 9 audit made visible to ``_break`` and the export must carry the
    same way), or ``queued`` (never prefilled).

    The entry carries the LIVE ``GenerationRequest`` — its
    ``GenerationStream`` handle is the caller's, so an in-process
    re-admission (supervisor rebuild, fleet migration) continues the
    stream the caller is already consuming. :meth:`payload` /
    :meth:`from_payload` are the serialized form for a cross-process
    handoff: everything bit-exactness needs travels (rng bit-generator
    state included), but the reconstructed request has a FRESH handle —
    the original caller's stream cannot cross a process boundary.
    """

    version: int
    request: GenerationRequest
    ids: Tuple[int, ...]
    phase: str

    @classmethod
    def capture(cls, request: GenerationRequest,
                phase: str) -> "RequestLedgerEntry":
        return cls(LEDGER_VERSION, request,
                   tuple(request.handle._ids), phase)

    @property
    def streamed(self) -> bool:
        """Whether the request had streamed any token at CAPTURE time
        (the serialized counterpart of ``GenerationRequest.streamed``,
        which re-admission consults on the live request)."""
        return len(self.ids) > len(self.request.prompt)

    def resolve(self, exc: BaseException) -> None:
        """Terminally fail the carried request (no-op if it already has
        a terminal event) — the ledger holder's obligation when no
        engine can re-admit an entry: every exported request must end
        in a terminal event on SOME path, or its caller blocks forever."""
        if not self.request.handle.done:
            self.request.handle._fail(exc)

    @staticmethod
    def _jsonable(obj):
        """Recursively strip numpy types from an rng state dict: the
        default PCG64 state is plain ints, but e.g. MT19937 carries an
        ndarray key — the wire form must survive json.dumps for ANY
        Generator a caller submitted with (the state setters accept
        the list form back)."""
        if isinstance(obj, dict):
            return {k: RequestLedgerEntry._jsonable(v)
                    for k, v in obj.items()}
        if isinstance(obj, np.ndarray):
            return obj.tolist()
        if isinstance(obj, np.integer):
            return int(obj)
        return obj

    def payload(self) -> dict:
        """JSON-able form of everything a bit-identical continuation
        needs on another host. Deadlines travel as REMAINING budget
        (monotonic clocks don't cross processes); ``None`` stays None.
        Since v2 the request's lifecycle trace travels too (wall-clock
        timestamps — the one clock that crosses processes), so a
        migrated stream's post-mortem shows its whole history, hops
        included."""
        req = self.request
        remaining = None if req.deadline is None else \
            req.deadline - time.monotonic()
        return {
            "version": self.version,
            "phase": self.phase,
            "prompt": list(req.prompt),
            "ids": list(self.ids),
            "want": req.want,
            "temperature": req.temperature,
            "top_k": req.top_k,
            "top_p": req.top_p,
            "stop_tokens": sorted(req.stop_tokens),
            "priority": req.priority,
            "deadline_remaining_s": remaining,
            "rng_state": self._jsonable(req.rng.bit_generator.state),
            "trace": req.handle._trace.to_payload(),
        }

    @classmethod
    def from_payload(cls, payload: dict) -> "RequestLedgerEntry":
        """Rebuild an admissible entry from :meth:`payload`. The rng is
        restored bit-exactly (same bit-generator type + state), the
        committed ids are replayed into a fresh handle, and the pending
        token is restored — ``admit_from_ledger`` then continues the
        stream exactly as an in-process entry would. v1 payloads (no
        trace) still admit cleanly: the continuation starts a fresh
        trace with an import marker instead of refusing the request."""
        version = int(payload["version"])
        if version > LEDGER_VERSION:
            raise ValueError(
                f"ledger entry version {version} is newer than this "
                f"build understands ({LEDGER_VERSION})")
        state = payload["rng_state"]
        bit_gen = getattr(np.random, state["bit_generator"])()
        bit_gen.state = state
        prompt = [int(t) for t in payload["prompt"]]
        remaining = payload.get("deadline_remaining_s")
        # deadline re-anchoring contract (test-pinned): the wire form
        # carries REMAINING budget and the deadline is re-anchored on
        # the RECEIVER's monotonic clock — sender/receiver wall-clock
        # skew can neither extend nor prematurely expire a migrated
        # request. An already-expired budget (remaining < 0) stays
        # expired: the deadline lands in the receiver's past.
        deadline = None if remaining is None else \
            time.monotonic() + float(remaining)
        req = GenerationRequest(
            prompt, int(payload["want"]) - len(prompt),
            temperature=payload["temperature"],
            top_k=payload["top_k"], top_p=payload["top_p"],
            stop_tokens=payload["stop_tokens"],
            rng=np.random.Generator(bit_gen), deadline=deadline,
            priority=int(payload["priority"]))
        ids = [int(t) for t in payload["ids"]]
        if len(ids) > len(prompt):
            req.handle._ids = list(ids)
            req.pending_token = ids[-1]
        trace_payload = payload.get("trace")
        if trace_payload:
            req.handle._trace = RequestTrace.from_payload(trace_payload)
        else:
            # a v1 (trace-less) payload: keep the fresh trace the
            # request constructor started, marked so attribution knows
            # this history begins at the import boundary
            req.handle._trace.record("imported",
                                     payload_version=version)
        return cls(version, req, tuple(ids), str(payload["phase"]))
