"""Generation requests, streaming handles, and the request ledger.

A submitted prompt becomes a ``GenerationRequest`` (the engine-side
descriptor riding the admission queue and a slot) paired with a
``GenerationStream`` (the caller-side handle): tokens stream into the
handle as each decode dispatch retires, so time-to-first-token is one
prefill away from admission instead of a whole batch away.

``RequestLedgerEntry`` is the PUBLIC, versioned form of the PR 9
insight that the host side already holds everything needed to rebuild
any in-flight request bit-identically: the prompt, the committed token
ids (whose last element is the pending, not-yet-fed token), the
per-request numpy ``Generator`` (advanced exactly once per draw, never
by the device), and the sampling config. Supervisor recovery
(``EngineSupervisor``) and fleet migration (``serving/fleet``) both
move requests as ledger entries through ONE engine code path
(``GenerationEngine.export_ledger`` / ``admit_from_ledger``) instead
of two hand-synced copies of the rebuild payload.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.serving.errors import InferenceTimeout

#: format version stamped into every exported ledger entry; bump on any
#: change to the payload fields or their meaning
LEDGER_VERSION = 1

_DONE = object()     # terminal queue sentinel


class GenerationStream:
    """Caller-side handle for one generation request.

    Tokens arrive as they are generated: iterate the handle to consume
    them (blocks until the engine produces the next one; ends at
    retirement, re-raising the request's failure if it has one), or call
    :meth:`result` for the classic one-shot ``sample_stream`` contract
    (full id list, prompt included). ``finish_reason`` is one of
    ``stop`` / ``length`` / ``capacity`` / ``cancelled`` / ``error``
    once done.

    The engine guarantees a terminal event on every path — retirement,
    request failure, engine shutdown — so consumers never block forever
    on a dead server (the ParallelInference no-hung-callers contract).
    """

    def __init__(self, prompt):
        self.prompt = list(prompt)
        self._ids: List[int] = list(prompt)
        self._q: "queue.Queue" = queue.Queue()
        self._done = threading.Event()
        self._error: Optional[BaseException] = None
        self.finish_reason: Optional[str] = None
        self.cancelled = False
        #: seconds from submit to first token / to admission (set by the
        #: engine; None until known)
        self.ttft_s: Optional[float] = None
        self.queue_wait_s: Optional[float] = None

    # -- engine side ---------------------------------------------------
    def _push(self, token: int) -> None:
        self._ids.append(int(token))
        self._q.put(int(token))

    def _finish(self, reason: str) -> None:
        self.finish_reason = reason
        self._done.set()
        self._q.put(_DONE)

    def _fail(self, exc: BaseException, reason: str = "error") -> None:
        self._error = exc
        self._finish(reason)

    # -- caller side ---------------------------------------------------
    @property
    def done(self) -> bool:
        return self._done.is_set()

    @property
    def error(self) -> Optional[BaseException]:
        return self._error

    @property
    def ids(self) -> List[int]:
        """Snapshot of prompt + tokens generated so far."""
        return list(self._ids)

    @property
    def generated(self) -> List[int]:
        """Snapshot of the tokens generated so far (prompt excluded)."""
        return list(self._ids[len(self.prompt):])

    def cancel(self) -> None:
        """Ask the engine to retire this request at its next step (frees
        the slot; a queued request is dropped at pop). Iterators/result()
        then raise RequestCancelled."""
        self.cancelled = True

    def __iter__(self):
        while True:
            try:
                item = self._q.get(timeout=0.1)
            except queue.Empty:
                # a finished, fully-drained stream (e.g. a SECOND
                # iteration after the terminal sentinel was consumed)
                # must end, not block forever
                if self._done.is_set():
                    if self._error is not None:
                        raise self._error
                    return
                continue
            if item is _DONE:
                if self._error is not None:
                    raise self._error
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the request retires; returns prompt + generated
        ids (the ``sample_stream`` return contract). Raises the
        request's failure, or InferenceTimeout if `timeout` seconds pass
        first."""
        if not self._done.wait(timeout):
            raise InferenceTimeout(
                f"no result within {timeout:g}s "
                f"(generated {len(self._ids) - len(self.prompt)} tokens)")
        if self._error is not None:
            raise self._error
        return list(self._ids)


class GenerationRequest:
    """Engine-side descriptor: sampling config, stop rules, deadline and
    priority for one prompt, plus the slot-lifecycle scratch the engine
    tracks (pending token, rng, timing marks)."""

    __slots__ = ("prompt", "steps", "want", "temperature", "top_k",
                 "top_p", "stop_tokens", "rng", "deadline", "priority",
                 "handle", "submit_t", "pending_token", "last_token_t")

    def __init__(self, prompt, steps: int, *, temperature: float = 1.0,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 stop_tokens=(), rng=None,
                 max_length: Optional[int] = None,
                 deadline: Optional[float] = None, priority: int = 0):
        self.prompt = [int(t) for t in prompt]
        self.steps = int(steps)
        self.want = len(self.prompt) + self.steps
        if max_length is not None:
            self.want = min(self.want, int(max_length))
        self.temperature = float(temperature)
        self.top_k = top_k
        self.top_p = top_p
        self.stop_tokens = frozenset(int(t) for t in stop_tokens)
        self.rng = rng if rng is not None else np.random.default_rng(0)
        self.deadline = deadline          # monotonic seconds, or None
        self.priority = int(priority)
        self.handle = GenerationStream(self.prompt)
        self.submit_t = time.monotonic()
        self.pending_token: Optional[int] = None
        self.last_token_t: Optional[float] = None

    @property
    def streamed(self) -> bool:
        """Whether any token has streamed: THE re-admission mode switch
        (re-prime ``ids[:-1]`` with the pending token vs a fresh
        admission) — one definition for the admission pop, the
        supervisor rebuild, and ``admit_from_ledger``. A fresh request
        can never read True before its admission draw (tokens only
        appear at admission)."""
        return len(self.handle._ids) > len(self.prompt)


@dataclasses.dataclass(frozen=True)
class RequestLedgerEntry:
    """One in-flight request as an exportable ledger record.

    ``ids`` is the capture-time snapshot of prompt + committed tokens;
    when the request has streamed at all, ``ids[-1]`` is the PENDING
    token (drawn but never yet fed to the model), so a re-admission
    re-primes ``ids[:-1]`` and the next dispatch recomputes exactly the
    distribution the unperturbed run would have seen. ``phase`` records
    where the request lived at export: ``active`` (seated in a slot),
    ``seating`` (the pop-to-seat handoff window — the request the
    PR 9 audit made visible to ``_break`` and the export must carry the
    same way), or ``queued`` (never prefilled).

    The entry carries the LIVE ``GenerationRequest`` — its
    ``GenerationStream`` handle is the caller's, so an in-process
    re-admission (supervisor rebuild, fleet migration) continues the
    stream the caller is already consuming. :meth:`payload` /
    :meth:`from_payload` are the serialized form for a cross-process
    handoff: everything bit-exactness needs travels (rng bit-generator
    state included), but the reconstructed request has a FRESH handle —
    the original caller's stream cannot cross a process boundary.
    """

    version: int
    request: GenerationRequest
    ids: Tuple[int, ...]
    phase: str

    @classmethod
    def capture(cls, request: GenerationRequest,
                phase: str) -> "RequestLedgerEntry":
        return cls(LEDGER_VERSION, request,
                   tuple(request.handle._ids), phase)

    @property
    def streamed(self) -> bool:
        """Whether the request had streamed any token at CAPTURE time
        (the serialized counterpart of ``GenerationRequest.streamed``,
        which re-admission consults on the live request)."""
        return len(self.ids) > len(self.request.prompt)

    def resolve(self, exc: BaseException) -> None:
        """Terminally fail the carried request (no-op if it already has
        a terminal event) — the ledger holder's obligation when no
        engine can re-admit an entry: every exported request must end
        in a terminal event on SOME path, or its caller blocks forever."""
        if not self.request.handle.done:
            self.request.handle._fail(exc)

    @staticmethod
    def _jsonable(obj):
        """Recursively strip numpy types from an rng state dict: the
        default PCG64 state is plain ints, but e.g. MT19937 carries an
        ndarray key — the wire form must survive json.dumps for ANY
        Generator a caller submitted with (the state setters accept
        the list form back)."""
        if isinstance(obj, dict):
            return {k: RequestLedgerEntry._jsonable(v)
                    for k, v in obj.items()}
        if isinstance(obj, np.ndarray):
            return obj.tolist()
        if isinstance(obj, np.integer):
            return int(obj)
        return obj

    def payload(self) -> dict:
        """JSON-able form of everything a bit-identical continuation
        needs on another host. Deadlines travel as REMAINING budget
        (monotonic clocks don't cross processes); ``None`` stays None."""
        req = self.request
        remaining = None if req.deadline is None else \
            req.deadline - time.monotonic()
        return {
            "version": self.version,
            "phase": self.phase,
            "prompt": list(req.prompt),
            "ids": list(self.ids),
            "want": req.want,
            "temperature": req.temperature,
            "top_k": req.top_k,
            "top_p": req.top_p,
            "stop_tokens": sorted(req.stop_tokens),
            "priority": req.priority,
            "deadline_remaining_s": remaining,
            "rng_state": self._jsonable(req.rng.bit_generator.state),
        }

    @classmethod
    def from_payload(cls, payload: dict) -> "RequestLedgerEntry":
        """Rebuild an admissible entry from :meth:`payload`. The rng is
        restored bit-exactly (same bit-generator type + state), the
        committed ids are replayed into a fresh handle, and the pending
        token is restored — ``admit_from_ledger`` then continues the
        stream exactly as an in-process entry would."""
        version = int(payload["version"])
        if version > LEDGER_VERSION:
            raise ValueError(
                f"ledger entry version {version} is newer than this "
                f"build understands ({LEDGER_VERSION})")
        state = payload["rng_state"]
        bit_gen = getattr(np.random, state["bit_generator"])()
        bit_gen.state = state
        prompt = [int(t) for t in payload["prompt"]]
        remaining = payload.get("deadline_remaining_s")
        deadline = None if remaining is None else \
            time.monotonic() + float(remaining)
        req = GenerationRequest(
            prompt, int(payload["want"]) - len(prompt),
            temperature=payload["temperature"],
            top_k=payload["top_k"], top_p=payload["top_p"],
            stop_tokens=payload["stop_tokens"],
            rng=np.random.Generator(bit_gen), deadline=deadline,
            priority=int(payload["priority"]))
        ids = [int(t) for t in payload["ids"]]
        if len(ids) > len(prompt):
            req.handle._ids = list(ids)
            req.pending_token = ids[-1]
        return cls(version, req, tuple(ids), str(payload["phase"]))
