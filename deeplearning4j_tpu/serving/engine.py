"""Continuous-batching generation engine over a slot-based KV arena.

The one-shot batch decoders (``util/decoding.sample_stream_batch``)
stall a serving batch on its slowest request and re-dispatch from
scratch per call. This engine decomposes the serving batch into
independently admitted/retired micro-units (the μ-batching lever,
arXiv:1804.04806) while keeping the dispatch loop free of per-request
shape work (the framework-overhead lesson of arXiv:2001.04206):

- **Slot arena**: the net's carried streaming state (attention KV
  caches, LSTM h/c) lives at a fixed batch of S slots — ONE canonical
  ``[S, V, 1]`` decode dispatch advances every active request per step,
  so after warmup the steady state never retraces regardless of request
  mix. Per-slot positions ride the per-row ``kv_pos`` vector the
  batched-speculation machinery introduced; free slots idle harmlessly
  (their writes drop, their outputs are discarded).
- **Admission mid-flight**: a request prefills at batch 1 through the
  shared ``_prime_padded`` width buckets (one left-padded dispatch, one
  jit shape per power-of-two bucket) into a detached state that ONE
  jitted scatter joins to the arena at its slot — running requests
  never wait for a newcomer's prompt.
- **Retirement per request**: stop-token / length / capacity /
  deadline / cancellation free the slot immediately (host bookkeeping
  only — no device op); the next queued request takes it on the same
  step.
- **Streaming**: tokens stream to a per-request ``GenerationStream``
  handle as each dispatch retires — TTFT is queue-wait + one prefill,
  not a batch drain.

Greedy (top_k=1) per-request outputs are bit-identical to one-shot
``sample_stream`` with the same rng (test-pinned): the arena feeds each
request exactly the token sequence a dedicated stream would, row
independence makes the math per-slot, and each request draws from its
OWN rng in generation order.

Exactness conditions are ``sample_stream_batch``'s: recurrent (LSTM)
state or attention with rope / no positions. Models with LEARNED
positional tables are rejected at construction (``pos_offset`` is a
scalar shared across the batch — it cannot track per-slot positions).

Chaos/resilience seams (tests/test_serving_engine.py drives these with
``resilience/chaos.py`` injectors): ``prefill_chaos`` fires before each
admission's prefill — a raise fails THAT request only, the arena is
restored untouched; ``decode_chaos`` fires before each decode dispatch
INSIDE the optional ``decode_retry`` RetryPolicy — a transient
mid-stream preemption is retried with numerics identical to a
fault-free run (the fault fires before any state mutates).

Serving engine v2 extras, each orthogonal and composable:

- ``paging=PagedKVConfig(...)`` rebuilds the arena's KV storage as
  **block-paged** (``serving/paging.py``): capacity becomes a token
  budget — admission checks the request's worst-case pages against the
  free pool (head-of-line blocking when short; requests that can NEVER
  fit are rejected at submit), retirement frees pages immediately, and
  decode runs DIRECTLY on the page pool by default (``direct=True``):
  the attention step reads K/V through the per-slot page table (XLA
  fallback, or the ``serving/paged_kernel.py`` Pallas paged-attention
  kernel) and the new token appends with an O(one-token) in-dispatch
  write — no per-step gather/scatter round trip (``direct=False``
  keeps the legacy round trip as the bench A/B baseline). Outputs stay
  bit-identical to the slot arena (and to one-shot ``sample_stream``)
  on every path. With ``prefix_cache=True`` (default) shared
  full-block prompt prefixes prime once (``serving/prefix_cache.py``):
  later requests map the cached pages and prefill only their suffix.
  ``dl4jtpu_serving_kv_bytes_moved_total`` prices the KV path in use;
  see ARCHITECTURE.md "Paged decode fast path".
- ``speculation=SpeculationConfig(draft, gamma)`` folds the
  ``speculative_sample`` machinery into the decode loop: per step the
  host `draft` proposes up to gamma tokens per active slot and ONE
  widened ``[S, V, 1+gamma]`` verify dispatch scores them all; each
  row's accept/reject walk (``util.decoding.accept_proposals``) commits
  accepted+1 tokens and a per-row ``rewind_stream_state`` drops the
  rejected positions — greedy outputs stay bit-identical to plain
  ``sample_stream`` (every committed token is the argmax chain), and
  the target's sampling distribution is exactly preserved.

Survivability (PR 9, ARCHITECTURE.md "Serving survivability"):

- ``supervisor=EngineSupervisor(...)`` replaces the terminal
  fail-all with request-preserving recovery: a step-cycle fault
  quarantines the arena and rebuilds it from the host-side ledger,
  re-admitting every in-flight request bit-identically; a windowed
  ``RestartBudget`` bounds the rebuild rate and escalates to the
  original ``_break`` when exhausted.
- ``overload=OverloadConfig(...)`` adds SLO-aware admission control:
  sustained-breach shedding of low-priority queued work
  (``ServingOverloaded``), deadline-based early rejection at submit,
  and the page-pressure brownout ladder (reduced gamma → speculation
  off → prefix-cache inserts off, auto-restoring).
- ``drain(timeout)`` stops admission and finishes the actives — the
  clean handoff point for planned restarts.
- the request-ledger seam (``export_ledger`` / ``admit_from_ledger`` /
  ``detach_ledger``): every in-flight request exports as a versioned
  ``RequestLedgerEntry`` and re-admits bit-identically on this or ANY
  other replica — the one rebuild path the supervisor's quarantine and
  ``serving/fleet``'s live migration both ride.
- ``seat_chaos`` fires in the pop-to-seat admission window (the
  handoff seam the supervisor also covers); ``prefill_chaos`` /
  ``seat_chaos`` receive the request as event context, so
  ``resilience.chaos.RequestFaultInjector`` can target named victims.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.monitoring import flightrecorder
from deeplearning4j_tpu.monitoring.events import emit as emit_event
from deeplearning4j_tpu.monitoring.metrics import (
    MetricsRegistry, global_registry)
from deeplearning4j_tpu.nn.conf.layers import (
    BATCHED_STREAM_KEYS, PositionalEmbeddingLayer, check_rewindable,
    paged_decode_impl, rewind_stream_state, set_paged_decode_impl,
    stream_capacity)
from deeplearning4j_tpu.resilience.chaos import fire as _fire_chaos
from deeplearning4j_tpu.resilience.retry import RetryPolicy, retry_call
from deeplearning4j_tpu.serving.errors import (
    EngineShutdown, InferenceTimeout, RequestCancelled,
    ServingOverloaded, ServingQueueFull)
from deeplearning4j_tpu.serving.health import (
    SERVING_ACTIVE_SLOTS, SERVING_BROWNOUT_LEVEL,
    SERVING_DEADLINE_EXCEEDED, SERVING_DISPATCH_LATENCY,
    SERVING_DRAINING, SERVING_EARLY_REJECTED, SERVING_ERRORS,
    SERVING_KV_BYTES_MOVED, SERVING_KV_PAGES_TOTAL,
    SERVING_KV_PAGES_USED, SERVING_PREFIX_HITS, SERVING_PREFIX_MISSES,
    SERVING_PREFIX_REUSED_TOKENS, SERVING_QUEUE_REJECTED,
    SERVING_QUEUE_WAIT, SERVING_REQUESTS, SERVING_SHED,
    SERVING_SPEC_ACCEPTANCE, SERVING_TOKENS, SERVING_TPOT, SERVING_TTFT,
    register_serving_metrics, scrape_probe)
from deeplearning4j_tpu.serving.overload import (
    BROWNOUT_NO_PREFIX_INSERTS, BROWNOUT_NO_SPECULATION,
    BROWNOUT_REDUCED_GAMMA, OverloadConfig, OverloadController)
from deeplearning4j_tpu.serving.paged_kernel import (
    paged_attention_supported)
from deeplearning4j_tpu.serving.paging import (
    PagedKVConfig, PagePool, gather_pages, pages_needed, scatter_pages,
    set_page)
from deeplearning4j_tpu.serving.prefix_cache import (
    ROOT_DIGEST, PrefixCache, chain_digests)
from deeplearning4j_tpu.serving.request import (
    GenerationRequest, GenerationStream, RequestLedgerEntry,
    rng_state_payload)
from deeplearning4j_tpu.serving.scheduler import AdmissionQueue
from deeplearning4j_tpu.util.decoding import (
    _check_seed, _stream_layers, _width_bucket, accept_proposals, draw,
    filter_probs, prime_prompt, step_tokens, stop_reason, verify_tokens)

log = logging.getLogger(__name__)

#: stream-state keys the admission scatter writes into the arena row
#: (kv_mask is deliberately absent: engine prefill is packed/maskless,
#: so per-slot validity is carried by kv_pos alone)
_SCATTER_KEYS = frozenset(BATCHED_STREAM_KEYS | {"kv_pos", "kv_abs"}) \
    - {"kv_mask"}


@dataclass
class SpeculationConfig:
    """In-engine speculative decoding knobs.

    `draft` is a HOST proposer callable ``(ids, gamma) -> proposals``
    (e.g. ``util.decoding.prompt_lookup_proposer()``): zero extra
    device dispatches, applied per active slot each step. `gamma` caps
    proposals per slot per step; the verify dispatch is the fixed
    ``[S, V, 1+gamma]`` widened shape regardless of how many proposals
    each row actually made (short rows pad with dummies that causality
    hides and the per-row rewind drops). Model-based drafting (a second
    net with its own arena) stays on the one-shot
    ``speculative_sample`` path."""

    draft: Callable
    gamma: int = 4

    def __post_init__(self):
        if self.gamma < 1:
            raise ValueError(f"gamma must be >= 1, got {self.gamma}")
        if hasattr(self.draft, "rnn_time_step") or \
                not callable(self.draft):
            raise TypeError(
                "in-engine speculation takes a host proposer callable "
                "(ids, gamma) -> proposals, e.g. "
                "util.decoding.prompt_lookup_proposer(); model-based "
                "drafting stays on the one-shot speculative_sample path")


@jax.jit
def _scatter_rows(arena, primed, slot):
    """Join one primed request's stream state into the arena at `slot`:
    batch-leading leaves take the primed row 0, per-row counters
    (kv_pos [S] <- scalar, kv_abs [S, L] <- [L]) take the primed value.
    One trace per net structure — `slot` rides as a traced scalar."""
    out = []
    for a, p in zip(arena, primed):
        out.append(a.at[slot].set(p[0] if p.ndim == a.ndim else p))
    return out


class GenerationEngine:
    """Continuous-batching generation over a fixed S-slot arena.

    Drive it manually (``submit()`` then ``step()`` /
    ``run_until_idle()`` — deterministic single-threaded serving, the
    test/bench shape) or start the background loop (``start()`` /
    ``shutdown()``) and consume ``GenerationStream`` handles from any
    thread.
    """

    def __init__(self, net, vocab_size: int, slots: int = 8,
                 queue_limit: int = 64, queue_policy: str = "block",
                 prime_padded: bool = True,
                 registry: Optional[MetricsRegistry] = None,
                 name: Optional[str] = None,
                 prefill_chaos=None, decode_chaos=None, seat_chaos=None,
                 decode_retry: Optional[RetryPolicy] = None,
                 paging: Optional[PagedKVConfig] = None,
                 speculation: Optional[SpeculationConfig] = None,
                 supervisor=None,
                 overload=None):
        if not hasattr(net, "rnn_time_step"):
            raise TypeError("GenerationEngine needs a streaming net "
                            "(rnn_time_step / rnn_clear_previous_state)")
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if vocab_size < 1:
            raise ValueError(f"vocab_size must be >= 1, got {vocab_size}")
        if getattr(net, "_initialized", True) is False:
            net.init()
        layers = list(_stream_layers(net))
        for l in layers:
            if isinstance(l, PositionalEmbeddingLayer):
                raise ValueError(
                    "continuous batching needs per-slot positions: "
                    "learned positional tables carry a shared pos_offset "
                    "(use a rope, position-free, or recurrent model)")
        net_inputs = getattr(getattr(net, "conf", None),
                             "network_inputs", None)
        if net_inputs is not None and len(net_inputs) != 1:
            raise ValueError("GenerationEngine serves single-input "
                             "decoder graphs only")
        self.net = net
        self.V = int(vocab_size)
        self.slots = int(slots)
        self._cap = stream_capacity(layers)
        self._prime_padded = bool(prime_padded)
        self._label = name or f"engine:{type(net).__name__}"
        self._graph_vertices = tuple(
            n for n, v in (getattr(net.conf, "vertices", None) or {}).items()
            if getattr(getattr(v, "layer", None), "supports_streaming",
                       False)) if hasattr(net, "conf") else ()
        #: PUBLIC replica identity, set by a fleet router at join time
        #: (replicas built by one factory share the default model
        #: label, so traces/timeline need the rid to tell them apart);
        #: None outside a fleet
        self.replica_tag: Optional[int] = None
        self._pending = AdmissionQueue(queue_limit, queue_policy)
        self._slots: List[Optional[GenerationRequest]] = [None] * slots
        self._row_pos = np.zeros(slots, np.int64)
        self._arena_ready = False
        self._merge_keys = None
        # -- block-paged KV arena (serving/paging.py) ------------------
        self._paging = paging
        self._pool: Optional[PagePool] = None
        self._prefix: Optional[PrefixCache] = None
        self._page_store = None            # device pools, per paged leaf
        self._paged_keys = None            # [(layer name, kv_k|kv_v)]
        self._page_tables: List[List[int]] = [[] for _ in range(slots)]
        #: fleet page-shipping hook (serving/fleet/agent.py sets it):
        #: called as ``page_publisher(prompt, table)`` right after a
        #: prefix-cache insert, under the engine lock — typically a
        #: closure over :meth:`export_prefix_chain`. Failures are
        #: swallowed: publishing is best-effort, admission is not.
        self.page_publisher: Optional[Callable] = None
        #: direct paged decode (no gather/scatter round trip) + its
        #: resolved attention impl ("xla" | "pallas"); see
        #: ARCHITECTURE.md "Paged decode fast path"
        self._direct = False
        self._decode_impl: Optional[str] = None
        self._decode_key: Optional[str] = None
        #: the pool's authoritative storage precision ("bf16" = the
        #: net's native leaf dtype, "int8" = serving/quant.py) and the
        #: int8 plumbing: per-leaf [P, Hkv] scale sidecars + the
        #: (name, Hkv, head_dim) layer map the eager store builds from
        self._kv_dtype = "bf16"
        self._quant_key: Optional[str] = None
        self._scale_store = None
        self._quant_dims = None
        self._scale_row_bytes = 0          # per-dispatch scale read unit
        #: cached [S, n_max] page table — np + device copies, rebuilt
        #: only after a table MUTATION (admit/retire/rebuild), not per
        #: step (the host used to rebuild and re-upload it every step
        #: even when nothing changed)
        self._tables_cache: Optional[np.ndarray] = None
        self._table_dev_cache = None
        self._tables_layer_cache = None    # per-layer copies (donation)
        #: modeled KV bytes moved by the pool<->dispatch paths (see
        #: serving/health.SERVING_KV_BYTES_MOVED)
        self._kv_bytes_total = 0
        self._tok_bytes = 0                # per-position bytes, all leaves
        #: whether direct dispatches actually donate state buffers
        #: (rnn_time_step resolves donation off on CPU — there the
        #: pre-dispatch table/pool references stay valid)
        self._state_donated = jax.default_backend() != "cpu"
        #: host mirror of the dispatch-latency histogram (health())
        self._dispatch_s_total = 0.0
        #: a retirement freed a slot whose DEVICE kv_pos keeps coasting
        #: (+1 per dispatch): the next direct install zeroes free rows'
        #: positions so an idle slot that once held a long context
        #: doesn't defeat the kernel's dead-block skip forever
        self._kv_pos_dirty = False
        if paging is not None:
            kv_layers = [l for l in layers
                         if getattr(l, "supports_streaming", False)
                         and getattr(l, "cache_length", 0)]
            if not kv_layers:
                raise ValueError(
                    "block-paged KV needs attention KV streaming state "
                    "(a layer with cache_length > 0) — a pure-recurrent "
                    "net has no per-token pages to manage")
            if any(getattr(l, "window", None) for l in kv_layers):
                raise ValueError(
                    "rolling (windowed) caches are not pageable: their "
                    "modular slot reuse has no stable token->page map "
                    "(use the slot arena, or a non-windowed model)")
            lens = {int(l.cache_length) for l in kv_layers}
            if len(lens) != 1:
                raise ValueError(
                    f"block-paged KV needs one shared cache_length "
                    f"across attention layers, got {sorted(lens)}")
            self._L = lens.pop()
            self._ps = paging.page_size
            self._n_max = -(-self._L // self._ps)
            # -- kv_dtype resolution (before pool sizing: a byte
            # budget and the impl eligibility both depend on it) -----
            l0 = kv_layers[0]
            native_dtype = getattr(net.conf, "dtype", None) or "float32"
            recurrent = any(getattr(l, "carries_recurrent_state", False)
                            for l in layers)
            kv_dtype = getattr(paging, "kv_dtype", "bf16")
            if kv_dtype != "bf16":
                from deeplearning4j_tpu.tuning.plan import (
                    quant_key_for_engine, resolve_kv_dtype)
                #: the paged_decode_quant crossover fingerprint — what
                #: kv_dtype="auto" consults and a calibrating bench
                #: records (tuning/crossover.py)
                self._quant_key = quant_key_for_engine(
                    self._ps, l0.n_out // l0.n_heads,
                    getattr(l0, "n_kv_heads", None) or l0.n_heads,
                    self._L, native_dtype)
            if kv_dtype == "auto":
                # eligibility is the static gate (direct paged decode,
                # no recurrent h/c); the CHOICE needs a calibrated,
                # platform-matching paged_decode_quant entry that says
                # int8 won — uncalibrated runs stay bf16 (quantization
                # is an accuracy trade, opted into by measurement)
                kv_dtype = resolve_kv_dtype(
                    bool(paging.direct) and not recurrent,
                    self._quant_key)
            if kv_dtype == "int8" and recurrent:
                raise ValueError(
                    "kv_dtype='int8' quantizes position-indexed KV "
                    "pages only; recurrent h/c state is a function of "
                    "the whole prefix and cannot re-prime through the "
                    "paged path (use kv_dtype='bf16', or a pure-"
                    "attention model)")
            self._kv_dtype = kv_dtype
            if paging.total_bytes is not None:
                from deeplearning4j_tpu.serving.quant import (
                    kv_page_bytes)
                dims = self._paged_layer_dims()
                usable = paging.resolve_pages_bytes(kv_page_bytes(
                    [(h, d) for _, h, d in dims], self._ps, kv_dtype,
                    native_dtype))
            else:
                usable = paging.resolve_pages(slots, self._n_max)
            self._pool = PagePool(usable + 1, self._ps)  # +1: null page
            self._direct = bool(paging.direct)
            if self._direct:
                from deeplearning4j_tpu.tuning.plan import (
                    decode_key_for_engine, resolve_decode_impl)
                #: the crossover fingerprint of this engine's decode
                #: shape — what "auto" consults and what a calibrating
                #: bench records (tuning/crossover.py)
                self._decode_key = decode_key_for_engine(
                    self._ps, l0.n_out // l0.n_heads,
                    getattr(l0, "n_kv_heads", None) or l0.n_heads,
                    self._L,
                    getattr(net.conf, "dtype", None) or "float32")
                impl = paging.decode_impl
                if impl == "auto":
                    # ELIGIBILITY is the static gate (unchanged): the
                    # kernel path needs TPU-tileable shapes and a TPU
                    # backend; the XLA fallback serves everything else.
                    # The CHOICE among eligible impls comes from the
                    # measured kernel-crossover store when a calibrated
                    # entry for this (page_size, head_dim, L) exists —
                    # PERF.md: "record the crossover so auto can learn
                    # it". No entry → the kernel (the PR 10 default).
                    ok = all(paged_attention_supported(
                        (0, 0, self._ps, l.n_out // l.n_heads), 1,
                        kv_dtype=self._kv_dtype)
                        for l in kv_layers)
                    eligible = jax.default_backend() == "tpu" and ok
                    impl = resolve_decode_impl(eligible,
                                               self._decode_key)
                # process-wide like stream-cache sharding: part of the
                # streaming jit key, so engines with different impls
                # retrace rather than silently sharing a trace
                set_paged_decode_impl(impl, paging.kernel_interpret)
                self._decode_impl = impl
            if paging.prefix_cache:
                if any(getattr(l, "carries_recurrent_state", False)
                       for l in layers):
                    raise ValueError(
                        "the prefix cache reuses position-indexed KV "
                        "pages only; recurrent h/c state is a function "
                        "of the whole prefix and lives outside the "
                        "pages — construct with "
                        "PagedKVConfig(prefix_cache=False)")
                self._prefix = PrefixCache(self._pool)
            if self._kv_dtype == "int8":
                # EAGER store build (bf16 builds lazily from the first
                # primed state): int8 prefill itself writes through
                # the paged path — quantize-once means the pools must
                # exist BEFORE the first prime, so they are sized from
                # the layer configs instead of a primed pytree
                self._quant_dims = self._paged_layer_dims()
                self._init_quant_store()
        # -- in-engine speculation (SpeculationConfig) -----------------
        self._speculation = speculation
        if speculation is not None:
            # rewind up to the full uniform chunk (gamma + 1 — a free
            # row keeps nothing); fails fast for LSTMs / tight windows
            check_rewindable(net, speculation.gamma + 1)
        self._admissions = 0
        self._dispatches = 0
        self._prefill_chaos = prefill_chaos
        self._decode_chaos = decode_chaos
        self._seat_chaos = seat_chaos
        self._decode_retry = decode_retry
        #: donate state into direct dispatches ONLY without a retry
        #: policy: a retried attempt would re-run against donated,
        #: already-consumed buffers. With decode_retry set, direct mode
        #: pays a pool copy per step (on TPU/GPU) for retryability —
        #: the retry-exactness contract (the fault fires before any
        #: state mutates) then holds exactly as on the legacy path.
        self._donate = self._direct and decode_retry is None
        # -- survivability (serving/supervisor.py, serving/overload.py)
        self._supervisor = supervisor
        if isinstance(overload, OverloadConfig):
            overload = OverloadController(overload)
        self._overload: Optional[OverloadController] = overload
        if overload is not None:
            overload._bind(self)
        self._brownout = 0
        self._draining = False
        #: the pop-to-seat handoff window: a request popped from the
        #: admission queue but not yet seated in a slot lives here so a
        #: fault in that window can fail (or recover) it instead of
        #: stranding its handle with no terminal event
        self._seating: Optional[GenerationRequest] = None
        #: traces of recently retired requests — the flight recorder's
        #: "last N requests" context when the engine breaks (in-flight
        #: requests' traces are read live off the slots)
        self._recent_traces = deque(maxlen=16)
        #: this engine's own recent lifecycle events (mirrored from the
        #: global ring at emit time): health() reads THIS, not a full
        #: ring scan — health() sits on polled paths (the autoscaler
        #: reads every replica's health per tick)
        self._own_events = deque(maxlen=10)
        self._stop = threading.Event()
        self._worker: Optional[threading.Thread] = None
        self._broken: Optional[BaseException] = None
        # ONE lock serializes every arena/net touch: step() may run from
        # the background loop while warmup/manual drivers call in
        self._lock = threading.RLock()
        net.rnn_clear_previous_state()     # the engine owns the stream
        self._register_metrics(registry)

    # ------------------------------------------------------------------
    # telemetry
    # ------------------------------------------------------------------
    def _register_metrics(self, registry) -> None:
        r = registry or global_registry()
        self._handles = register_serving_metrics(self, self._label,
                                                 registry)
        lab = dict(model=self._label)
        self._tokens = r.counter(
            SERVING_TOKENS, "Tokens generated by the serving engine",
            ("model",)).labels(**lab)
        self._ttft_hist = r.histogram(
            SERVING_TTFT, "Seconds from submit to first token",
            ("model",)).labels(**lab)
        self._tpot_hist = r.histogram(
            SERVING_TPOT, "Seconds between consecutive tokens of one "
            "request", ("model",)).labels(**lab)
        self._queue_wait_hist = r.histogram(
            SERVING_QUEUE_WAIT, "Seconds a request waited for admission",
            ("model",)).labels(**lab)
        self._dispatch_hist = r.histogram(
            SERVING_DISPATCH_LATENCY, "Wall seconds per decode/verify "
            "dispatch cycle (paged modes include the KV path around it)",
            ("model",)).labels(**lab)
        if self._pool is not None:
            self._kv_bytes = r.counter(
                SERVING_KV_BYTES_MOVED, "Modeled bytes the KV path "
                "moves between the page pool and the dispatch (legacy: "
                "full gather+scatter round trip; direct: in-dispatch "
                "read + one-token append)", ("model",)).labels(**lab)
        r.gauge(SERVING_ACTIVE_SLOTS, "Arena slots holding an active "
                "request", ("model",)).set_function(
            scrape_probe(self, lambda s: s.active_slots()),
            model=self._label)
        if self._pool is not None:
            r.gauge(SERVING_KV_PAGES_TOTAL, "Allocatable KV pages in "
                    "the paged arena's pool", ("model",)).set_function(
                scrape_probe(self, lambda s: s._pool.usable),
                model=self._label)
            r.gauge(SERVING_KV_PAGES_USED, "KV pages currently held by "
                    "slots or the prefix cache", ("model",)).set_function(
                scrape_probe(self, lambda s: s._pool.used_count()),
                model=self._label)
        if self._prefix is not None:
            self._prefix_hits = r.counter(
                SERVING_PREFIX_HITS, "Admissions that reused >= 1 "
                "cached prefix block", ("model",)).labels(**lab)
            self._prefix_misses = r.counter(
                SERVING_PREFIX_MISSES, "Admissions that reused no "
                "cached prefix block", ("model",)).labels(**lab)
            self._prefix_reused = r.counter(
                SERVING_PREFIX_REUSED_TOKENS, "Prompt tokens whose "
                "prefill was skipped via cached pages",
                ("model",)).labels(**lab)
        if self._speculation is not None:
            self._spec_accept_hist = r.histogram(
                SERVING_SPEC_ACCEPTANCE, "Per-slot fraction of draft "
                "proposals accepted by a verify dispatch",
                ("model",)).labels(**lab)
        r.gauge(SERVING_DRAINING, "Engine draining: admission stopped, "
                "actives finishing (1) or serving normally (0)",
                ("model",)).set_function(
            scrape_probe(self, lambda s: 1.0 if s._draining else 0.0),
            model=self._label)
        if self._supervisor is not None:
            self._supervisor._bind(self, registry)
        if self._overload is not None:
            self._shed_counter = r.counter(
                SERVING_SHED, "Queued requests shed under a sustained "
                "SLO breach", ("model",)).labels(**lab)
            self._early_rejected = r.counter(
                SERVING_EARLY_REJECTED, "Submits refused because their "
                "deadline provably cannot be met",
                ("model",)).labels(**lab)
            r.gauge(SERVING_BROWNOUT_LEVEL, "Brownout ladder rung: 0 "
                    "off, 1 reduced gamma, 2 speculation off, 3 prefix "
                    "inserts off", ("model",)).set_function(
                scrape_probe(self, lambda s: float(s._brownout)),
                model=self._label)

    # ------------------------------------------------------------------
    # health / readiness (the ParallelInference probe contract)
    # ------------------------------------------------------------------
    @property
    def label(self) -> str:
        """The model label this engine's telemetry/events carry — the
        public identity the fleet layer (and the event timeline) keys
        on."""
        return self._label

    @property
    def trace_identity(self) -> str:
        """The identity request traces record per lifecycle event: the
        model label, rid-suffixed when a fleet router stamped
        ``replica_tag`` (factory-built replicas share the label, and a
        migrated trace must name BOTH sides of its hop)."""
        if self.replica_tag is None:
            return self._label
        return f"{self._label}#r{self.replica_tag}"

    def _emit_serving_event(self, name: str, **attrs) -> None:
        """Publish one serving-lifecycle event under this engine's
        trace identity (rid-suffixed in a fleet — label-sharing
        replicas must not blend their timelines) and mirror it into
        the bounded per-engine tail ``health()`` serves. The
        supervisor emits its rebuild/escalate events through this too,
        so one engine's recovery history lives in one place."""
        ev = emit_event("serving", name, engine=self.trace_identity,
                        **attrs)
        if ev is not None:
            self._own_events.append({"name": ev.name, "wall": ev.wall,
                                     "attrs": dict(ev.attrs)})

    def is_healthy(self) -> bool:
        if self._broken is not None or self._stop.is_set():
            return False
        if self._worker is not None and not self._worker.is_alive():
            return False
        return True

    def is_ready(self) -> bool:
        return self.is_healthy() and not self._draining \
            and not self._pending.full()

    def queue_depth(self) -> int:
        return self._pending.depth()

    def active_slots(self) -> int:
        return sum(r is not None for r in self._slots)

    def health(self) -> dict:
        out = {"healthy": self.is_healthy(), "ready": self.is_ready(),
               # identity for multi-engine / multi-PROCESS probes: a
               # /health dump or an agent status file must say which
               # replica (and whose pid) this payload describes
               "label": self.trace_identity,
               "pid": os.getpid(),
               "queue_depth": self.queue_depth(),
               "active_slots": self.active_slots(),
               "slots": self.slots,
               "decode_dispatch": {
                   "count": self._dispatches,
                   "mean_ms": round(
                       self._dispatch_s_total * 1e3
                       / max(1, self._dispatches), 3)}}
        if self._pool is not None:
            out["kv_pages"] = {"total": self._pool.usable,
                               "used": self._pool.used_count(),
                               "free": self._pool.free_count(),
                               "page_size": self._pool.page_size}
            out["kv_traffic"] = {
                # the LIVE impl: another engine's construction can flip
                # the process-wide setting — report what dispatches
                # actually run, not the construction-time resolution
                "decode_path": (f"direct-{self._live_impl()}"
                                if self._direct else "roundtrip"),
                "kv_dtype": self._kv_dtype,
                "bytes_moved_total": self._kv_bytes_total,
                "dispatches": self._dispatches,
            }
        if self._prefix is not None:
            out["prefix_cache"] = {"entries": len(self._prefix),
                                   "hits": self._prefix.hits,
                                   "misses": self._prefix.misses,
                                   "reused_tokens":
                                       self._prefix.reused_tokens}
        if self._speculation is not None:
            out["speculation"] = {"gamma": self._speculation.gamma}
        if self._draining:
            out["draining"] = True
        if self._supervisor is not None:
            out["supervisor"] = self._supervisor.health()
        if self._overload is not None:
            out["overload"] = {
                "brownout_level": self._brownout,
                "shed_total": self._overload.shed_total,
                "early_rejected_total":
                    self._overload.early_rejected_total,
            }
        # recent lifecycle events (bounded, non-mutating, O(1)): the
        # per-engine mirror, not a global-ring scan — health() runs on
        # polled paths (every autoscaler tick reads every replica)
        out["last_events"] = list(self._own_events)
        return out

    @property
    def page_pool(self) -> Optional[PagePool]:
        """The paged arena's pool (None in slot-arena mode) — the
        chaos seam resilience.chaos.PageExhaustionInjector drives."""
        return self._pool

    @property
    def prefix_cache(self) -> Optional[PrefixCache]:
        return self._prefix

    # ------------------------------------------------------------------
    # submission
    # ------------------------------------------------------------------
    def submit(self, prompt, steps: int, *, temperature: float = 1.0,
               top_k: Optional[int] = None, top_p: Optional[float] = None,
               stop_tokens=(), rng=None, timeout: Optional[float] = None,
               priority: int = 0,
               max_length: Optional[int] = None) -> GenerationStream:
        """Queue one prompt for up to `steps` generated tokens; returns
        its streaming handle immediately (admission happens on a later
        ``step()``). Arguments mirror ``sample_stream`` — same rng, same
        stop semantics, `max_length` defaulting to the net's streaming
        capacity — plus serving controls: `timeout` (end-to-end deadline
        in seconds; expiry anywhere — queued or mid-generation — fails
        the handle with InferenceTimeout and frees the slot) and
        `priority` (higher admitted first)."""
        if self._broken is not None:
            raise EngineShutdown("GenerationEngine is broken: "
                                 f"{self._broken!r}")
        if self._stop.is_set():
            raise EngineShutdown("GenerationEngine shut down")
        if self._draining:
            raise EngineShutdown("GenerationEngine draining — submit "
                                 "to the replacement instance")
        prompt = [int(t) for t in prompt]
        if max_length is None:
            max_length = self._cap
        _check_seed(prompt, steps, max_length)
        if self._cap is not None and len(prompt) > self._cap:
            raise ValueError(
                f"prompt of {len(prompt)} tokens exceeds the net's "
                f"streaming capacity ({self._cap})")
        want = len(prompt) + int(steps)
        if max_length is not None:
            want = min(want, int(max_length))
        if self._speculation is not None and self._cap is not None \
                and want > self._cap - self._speculation.gamma + 1:
            raise ValueError(
                f"prompt + steps ({want} ids) needs speculative "
                f"headroom: every verify dispatch transiently consumes "
                f"1 + gamma positions, so in-engine speculation serves "
                f"at most capacity - gamma + 1 = "
                f"{self._cap - self._speculation.gamma + 1} ids")
        if self._pool is not None:
            # admission-time capacity check: a request whose worst case
            # can NEVER fit the page budget is rejected here, not
            # admitted and retired mid-stream on capacity
            store = self._store_positions(want)
            if pages_needed(store, self._ps) > self._pool.usable:
                raise ValueError(
                    f"prompt + steps would hold {store} KV positions "
                    f"({pages_needed(store, self._ps)} pages of "
                    f"{self._ps} tokens) but the pool has only "
                    f"{self._pool.usable} pages total — the request "
                    f"can never be admitted")
        self._handles[SERVING_REQUESTS].inc()
        deadline = None if timeout is None else \
            time.monotonic() + float(timeout)
        req = GenerationRequest(
            prompt, steps, temperature=temperature, top_k=top_k,
            top_p=top_p, stop_tokens=stop_tokens, rng=rng,
            max_length=max_length, deadline=deadline, priority=priority)
        if self._overload is not None:
            reason = self._overload.reject_at_submit(
                self, req, time.monotonic())
            if reason is not None:
                self._early_rejected.inc()
                req.trace.record("early_reject", reason=reason)
                self._emit_serving_event("early_reject")
                raise ServingOverloaded(reason)
        try:
            self._pending.submit(req)
        except ServingQueueFull:
            self._handles[SERVING_QUEUE_REJECTED].inc()
            raise
        except InferenceTimeout:
            self._handles[SERVING_DEADLINE_EXCEEDED].inc()
            raise
        return req.handle

    # ------------------------------------------------------------------
    # the dispatch cycle
    # ------------------------------------------------------------------
    def step(self) -> bool:
        """One engine cycle: expire/cancel, shed under overload, admit
        into free slots, one decode (or widened speculative verify)
        dispatch over the arena, sample + stream + retire. Returns
        whether any progress was made (False = idle).

        The WHOLE cycle past reaping is one failure domain: a fault
        anywhere — the pop-to-seat admission window included, not just
        the dispatch — lands in one place where the supervisor (if any)
        can quarantine + rebuild the arena from the request ledger;
        without one (or with the restart budget exhausted) the engine
        falls to the terminal ``_break`` fail-all."""
        with self._lock:
            if self._stop.is_set() or self._broken is not None:
                return False
            now = time.monotonic()
            progress = self._reap(now) > 0
            try:
                if self._overload is not None:
                    progress = self._apply_overload(now) or progress
                if not self._draining:
                    # admission staging (prefill buffers, first-admission
                    # pool build, prefix-page mapping) is per-REQUEST
                    # slot-lifecycle work, not the per-token decode
                    # steady state this rule protects — between
                    # admissions steps re-upload nothing (cached tables)
                    # tpulint: disable=device-transfer-in-hot-loop
                    progress = self._admit_ready(now) > 0 or progress
                active = [s for s, r in enumerate(self._slots)
                          if r is not None]
                if not active:
                    return progress
                if self._speculation is not None:
                    self._step_speculative(active)
                else:
                    self._step_plain(active)
            except Exception as e:  # noqa: BLE001 — fail waiters, not hang
                self._handles[SERVING_ERRORS].inc()
                if self._recover(e):
                    return True
                self._break(e)
                return False
            return True

    def _recover(self, exc: BaseException) -> bool:
        """Hand a step-cycle fault to the supervisor (if any): True =
        the arena was rebuilt and every in-flight request re-admitted
        bit-identically, keep serving."""
        if self._supervisor is None:
            return False
        cause = ("admission_fault" if self._seating is not None
                 else "decode_fault")
        return self._supervisor.on_dispatch_fault(self, exc, cause)

    def _apply_overload(self, now: float) -> bool:
        """One overload-control tick: shed queued work under a
        sustained SLO breach, refresh the brownout rung from page
        pressure. Host-only; runs before admission so a shed victim is
        never admitted on the same step."""
        ov = self._overload
        victims = ov.shed(self)
        for req in victims:
            self._shed_counter.inc()
            req.trace.record("shed", engine=self.trace_identity)
            req.handle._fail(ServingOverloaded(
                "shed from the admission queue under a sustained "
                "latency-SLO breach (lowest-priority first)"))
        if victims:
            self._emit_serving_event("shed", victims=len(victims))
        prev = self._brownout
        self._brownout = ov.brownout_level(self)
        if self._brownout != prev:
            self._emit_serving_event("brownout", level=self._brownout,
                                     prev=prev)
        return bool(victims)

    def _step_plain(self, active) -> None:
        """One canonical [S, V, 1] decode dispatch + one draw per row."""
        probs = self._dispatch_step()
        now = time.monotonic()
        for s in active:
            req = self._slots[s]
            if req is None:        # retired by the capacity guard
                continue
            tok = draw(probs[s], req.temperature, req.rng,
                       top_k=req.top_k, top_p=req.top_p)
            if req.last_token_t is not None:
                self._tpot_hist.observe(now - req.last_token_t)
            req.last_token_t = now
            req.handle._push(tok)
            req.trace.rollup(1)
            self._tokens.inc()
            reason = stop_reason(tok, len(req.handle._ids), req.want,
                                 req.stop_tokens)
            if reason:
                self._retire(s, reason)
            else:
                req.pending_token = tok

    def _step_speculative(self, active) -> None:
        """One widened [S, V, 1+gamma] verify dispatch: the host draft
        proposes per slot, the target scores pending + proposals in ONE
        forward, each row commits its accepted prefix + one
        replacement/bonus token (the shared rejection rule), and a
        per-row rewind drops the rejected positions — accepted tokens
        advance multiple positions per engine step."""
        spec = self._speculation
        k = spec.gamma
        # brownout ladder: a reduced (or zero) gamma pads the SAME
        # widened [S, V, 1+gamma] dispatch with fewer real proposals —
        # feature degradation with zero shape changes, zero retraces
        g_cap = k
        if self._brownout >= BROWNOUT_NO_SPECULATION:
            g_cap = 0
        elif self._brownout >= BROWNOUT_REDUCED_GAMMA:
            g_cap = self._overload.brownout_gamma(k)
        if self._cap is not None:
            for s in active:
                if self._slots[s] is not None \
                        and self._row_pos[s] >= self._cap:
                    self._retire(s, "capacity")
        chunk = np.zeros((self.slots, 1 + k), np.int64)
        props: List[List[int]] = [[] for _ in range(self.slots)]
        q_dists = [None] * self.slots
        riders = []
        for s, req in enumerate(self._slots):
            if req is None:
                continue
            riders.append(s)
            g = min(g_cap, req.want - len(req.handle._ids))
            # g <= 0 (brownout rung 2+, or one token wanted): don't pay
            # the host draft — the rung exists to SHED host/device work
            p = ([int(t) for t in spec.draft(list(req.handle._ids), g)][:g]
                 if g > 0 else [])
            props[s] = p
            q_dists[s] = [None] * len(p)   # deterministic = one-hot draft
            chunk[s, 0] = req.pending_token
            chunk[s, 1:1 + len(p)] = p
        if not riders:
            return                 # everything retired at the guard
        self._sync_accounting()
        tp = self._run_dispatch(
            lambda: verify_tokens(self.net, chunk, self.V,
                                  donate_state=self._donate),
            width=1 + k)
        now = time.monotonic()
        amounts = np.full(self.slots, 1 + k, np.int32)  # free rows: all
        for s in riders:
            req = self._slots[s]
            g = len(props[s])
            p_dists = [filter_probs(tp[s, :, j], req.temperature,
                                    req.top_k, req.top_p)
                       for j in range(g)]
            p_bonus = filter_probs(tp[s, :, g], req.temperature,
                                   req.top_k, req.top_p)
            accepted, nxt = accept_proposals(props[s], p_dists,
                                             q_dists[s], p_bonus,
                                             req.rng)
            if g:
                self._spec_accept_hist.observe(accepted / g)
            committed = props[s][:accepted] + [nxt]
            req.trace.rollup(len(committed), accepted=accepted,
                             proposed=g)
            self._row_pos[s] += 1 + accepted
            amounts[s] = k - accepted
            reason = None
            for tok in committed:
                if req.last_token_t is not None:
                    self._tpot_hist.observe(now - req.last_token_t)
                req.last_token_t = now
                req.handle._push(tok)
                self._tokens.inc()
                reason = stop_reason(tok, len(req.handle._ids),
                                     req.want, req.stop_tokens)
                if reason:
                    break
            if reason:
                self._retire(s, reason)
            else:
                req.pending_token = committed[-1]
        rewind_stream_state(self.net, amounts)
        self._sync_accounting()

    def run_until_idle(self, max_steps: int = 1_000_000) -> int:
        """Manually drive ``step()`` until nothing is active or
        admissible (single-threaded serving: tests, warmup, offline
        drains). Returns the number of cycles taken."""
        n = 0
        while self.step():
            n += 1
            if n >= max_steps:
                raise RuntimeError(f"engine still busy after {n} steps")
        return n

    def _reap(self, now: float) -> int:
        """Retire expired/cancelled requests, ACTIVE (frees their slots
        — a slow consumer cannot squat the arena) and QUEUED (a full
        arena must not defer a queued request's deadline until a slot
        happens to free)."""
        n = 0
        for req in self._pending.reap(now):
            n += 1
            if req.handle.cancelled:
                req.handle._fail(RequestCancelled(
                    "request cancelled while queued"), reason="cancelled")
            else:
                self._handles[SERVING_DEADLINE_EXCEEDED].inc()
                req.handle._fail(InferenceTimeout(
                    "deadline expired in the admission queue"))
        for s, req in enumerate(self._slots):
            if req is None:
                continue
            if req.handle.cancelled:
                self._retire(s, "cancelled",
                             RequestCancelled("request cancelled"))
                n += 1
            elif req.deadline is not None and now >= req.deadline:
                self._handles[SERVING_DEADLINE_EXCEEDED].inc()
                self._retire(s, "error", InferenceTimeout(
                    "deadline expired mid-generation "
                    f"({len(req.handle._ids) - len(req.prompt)} tokens "
                    "streamed)"))
                n += 1
        return n

    def _store_positions(self, want: int) -> int:
        """KV positions a request of `want` total ids holds at worst:
        the final drawn token never re-enters the cache, and the
        stream-capacity guard retires a row before it can pass `cap`.
        The ONE formula behind submit()'s never-fits rejection, the
        head-of-line admission gate, and the page reservation — they
        must agree or a request could pass submit yet never admit."""
        return want - 1 if self._cap is None else min(want - 1,
                                                      self._cap)

    def _pages_admissible(self, req: GenerationRequest) -> bool:
        """Worst-case page check for the head-of-line request: admit
        only when its full reservation fits the free pool plus what the
        prefix cache could evict. Conservative — a prefix hit may need
        fewer fresh pages — so admission never over-commits; pages free
        as active requests retire, so a fitting-in-principle head
        always eventually admits."""
        store = self._store_positions(req.want)
        avail = self._pool.free_count() + (
            self._prefix.evictable_pages() if self._prefix is not None
            else 0)
        return pages_needed(store, self._ps) <= avail

    def _admit_ready(self, now: float) -> int:
        """Fill free slots from the admission queue in priority order
        (paged mode: while the head request's pages fit).

        Every popped request is pinned to ``self._seating`` until it is
        seated in a slot or its handle carries a terminal event: the
        pop-to-seat window is otherwise invisible to both the slot scan
        and the queue drain, and a fault inside it (arena join, the
        admission draw, a chaos hook) would strand the handle with no
        terminal event — callers blocked forever on a request the
        engine no longer knows about."""
        n = 0
        gate = self._pages_admissible if self._pool is not None else None
        while None in self._slots:
            req = self._pending.pop(admissible=gate)
            if req is None:
                break
            self._seating = req
            n += 1
            if self._fail_if_dead(req, now, "in the admission queue"):
                self._seating = None
                continue
            _fire_chaos(self._seat_chaos, self._admissions, ctx=req)
            req.trace.record("queue_pop", engine=self.trace_identity)
            req.handle.queue_wait_s = now - req.submit_t
            self._queue_wait_hist.observe(req.handle.queue_wait_s)
            if self._overload is not None:
                self._overload.observe_queue_wait(req.handle.queue_wait_s)
            # a popped request that already streamed tokens is a ledger
            # survivor riding the queue (migration / requeue overflow):
            # re-prime it instead of fresh-admitting
            self._admit_one(req, self._slots.index(None),
                            readmit=req.streamed)
            self._seating = None
        return n

    def _fail_if_dead(self, req, now: float, where: str) -> bool:
        """Give `req` its terminal event if it was cancelled or its
        deadline has passed (or it already carries one); True means the
        caller must skip it. The ONE cancel/deadline gate shared by the
        admission pop and the rebuild's re-admissions, so the recovery
        path can never drift from the admission path's semantics."""
        if req.handle.done:
            return True
        if req.handle.cancelled:
            req.handle._fail(RequestCancelled(
                f"request cancelled {where}"), reason="cancelled")
            return True
        if req.deadline is not None and now >= req.deadline:
            self._handles[SERVING_DEADLINE_EXCEEDED].inc()
            req.handle._fail(InferenceTimeout(
                f"deadline expired {where}"))
            return True
        return False

    def _alloc_request_pages(self, req: GenerationRequest):
        """Reserve the request's worst-case pages: look up the longest
        cached full-block prefix (mapped shared, refcount++), evict
        unmapped cache entries if the fresh allocation falls short, and
        allocate the rest. Returns ``(table, hit_len)`` — the slot's
        block-ordered page table and how many prompt tokens the cached
        pages already cover."""
        hit_len, shared = 0, []
        if self._prefix is not None:
            if self._page_store is not None:
                hit_len, shared = self._prefix.lookup(req.prompt)
            else:
                self._prefix.misses += 1   # nothing cached before the
            (self._prefix_hits if shared  # first arena build
             else self._prefix_misses).inc()
            if hit_len:
                self._prefix_reused.inc(hit_len)
        store = self._store_positions(req.want)
        need_new = pages_needed(store, self._ps) - len(shared)
        # retain the shared pages BEFORE evicting: a deep shortfall must
        # not reclaim the very blocks this admission is about to map
        for p in shared:
            self._pool.retain(p)
        try:
            short = need_new - self._pool.free_count()
            if short > 0 and self._prefix is not None:
                self._prefix.evict(short)
            fresh = self._pool.alloc(need_new)  # PageExhausted only
        except Exception:                       # under a chaos seize
            for p in shared:
                self._pool.release(p)
            raise
        return shared + fresh, hit_len

    def _install_prefix(self, table, hit_len: int) -> None:
        """Seed the detached prefill state with the cached prefix: the
        mapped pages gather into a batch-1 dense view, kv_pos starts at
        the block boundary, and the host position mirrors follow — the
        suffix prime then continues the stream exactly as if the prefix
        had just been primed."""
        net = self.net
        row = np.zeros((1, self._n_max), np.int32)
        n_hit = hit_len // self._ps
        row[0, :n_hit] = table[:n_hit]
        dense = gather_pages(self._page_store, row, length=self._L)
        self._kv_traffic(self._L * self._tok_bytes)   # one-row gather
        pos = jnp.asarray(hit_len, jnp.int32)
        for (n, k), leaf in zip(self._paged_keys, dense):
            cur = net.state.get(n)
            cur = dict(cur) if isinstance(cur, dict) else {}
            cur[k] = leaf
            cur["kv_pos"] = pos
            net.state[n] = cur
        net._stream_pos = hit_len
        net._stream_pos_rows = None
        if self._graph_vertices:
            net._stream_pos_map = {n: hit_len
                                   for n in self._graph_vertices}

    def _admit_one(self, req: GenerationRequest, slot: int,
                   readmit: bool = False) -> None:
        """Prefill `req` at batch 1 and join it to the arena at `slot`.
        A prefill failure fails THAT request only: the arena state is
        restored untouched (and the request's pages released), so
        in-flight requests are unaffected.

        ``readmit=True`` is the supervisor's recovery path: the request
        already streamed tokens before the arena was quarantined, so
        the prime feeds ``ids[:-1]`` (prompt + committed tokens minus
        the pending one — exactly what the lost arena row had consumed)
        and NOTHING else happens: no draw (the rng must stay at its
        fault-time position), no token push, no TTFT/queue-wait
        observation, no prefill chaos (the request already cleared
        admission once). The next dispatch recomputes the identical
        next-token distribution, so the stream continues bit-identical
        to an unperturbed run."""
        net = self.net
        saved_state = dict(net.state)
        saved_acct = self._save_accounting()
        prime_ids = req.handle._ids[:-1] if readmit else req.prompt
        table, hit_len = [], 0
        try:
            if self._pool is not None:
                table, hit_len = self._alloc_request_pages(req)
            if not readmit:
                _fire_chaos(self._prefill_chaos, self._admissions,
                            ctx=req)
            net.rnn_clear_previous_state()
            fed = len(prime_ids) - hit_len
            req.trace.record(
                "prefill_start", engine=self.trace_identity, width=fed,
                bucket=(_width_bucket(max(1, fed))
                        if self._prime_padded else None),
                prefix_hit=hit_len, readmit=readmit)
            if self._kv_dtype == "int8":
                # the int8 prime runs THROUGH the paged path (quantize-
                # once: the prompt's pool bytes must come from the same
                # quantized append the decode steps run) — a prefix hit
                # just starts kv_pos past the shared pages, no dense
                # gather/scatter round trip
                self._install_prime_paged_state(table, hit_len)
                p0 = prime_prompt(net, prime_ids[hit_len:], self.V,
                                  padded=self._prime_padded)
            elif hit_len:
                self._install_prefix(table, hit_len)
                p0 = prime_prompt(net, prime_ids[hit_len:], self.V,
                                  padded=self._prime_padded)
            else:
                p0 = prime_prompt(net, prime_ids, self.V,
                                  padded=self._prime_padded)
            req.trace.record("prefill_end")
            primed_pos = self._net_pos(net)
        except Exception as e:  # noqa: BLE001 — per-request failure domain
            net.state = saved_state
            self._restore_accounting(saved_acct)
            self._release_pages(table)
            if not readmit:
                self._admissions += 1
            self._handles[SERVING_ERRORS].inc()
            req.handle._fail(e)
            self._recent_traces.append(req.trace)
            return
        primed_state = dict(net.state)
        if self._kv_dtype == "int8":
            # pools/scales come back out of the prime's state AFTER the
            # snapshot: every early-exit below (failure already returned;
            # one-token finish; dead-request skip) leaves the store
            # exactly as the prime left it — the prime wrote the
            # request's pages in place, and a one-token finish releases
            # those pages right here via _release_pages
            primed_state = self._extract_prime_paged_state(primed_state)
        if readmit:
            tok = req.handle._ids[-1]    # pending, drawn pre-fault
            req.trace.record("readmit", engine=self.trace_identity)
        else:
            self._admissions += 1
            tok = draw(p0, req.temperature, req.rng,
                       top_k=req.top_k, top_p=req.top_p)
            now = time.monotonic()
            req.handle.ttft_s = now - req.submit_t
            self._ttft_hist.observe(req.handle.ttft_s)
            if self._overload is not None:
                self._overload.observe_ttft(req.handle.ttft_s, now)
            req.last_token_t = now
            req.trace.record("first_token", engine=self.trace_identity)
            req.handle._push(tok)
            self._tokens.inc()
            reason = stop_reason(tok, len(req.handle._ids), req.want,
                                 req.stop_tokens)
            if reason is None and self._cap is not None \
                    and primed_pos >= self._cap:
                reason = "capacity"  # prompt filled the stream: no room
            if reason:
                # one-token request: never enters the arena at all
                net.state = saved_state
                self._restore_accounting(saved_acct)
                self._release_pages(table)
                req.handle._finish(reason)
                self._recent_traces.append(req.trace)
                return
        if not self._arena_ready:
            if self._pool is not None and self._page_store is None:
                self._init_page_store(primed_state)
            saved_state = self._build_arena(primed_state, saved_state)
            self._arena_ready = True
        net.state = self._merge(saved_state, primed_state, slot)
        if self._pool is not None:
            if self._kv_dtype == "int8":
                # the prime already wrote the pool in place (quantize-
                # once) — no dense→paged scatter; charge its pool
                # traffic: the folded-gather prime read the whole
                # context per chunk and appended `fed` tokens
                self._kv_traffic((self._L + fed) * self._tok_bytes)
            else:
                self._scatter_primed_pages(primed_state, table)
            self._page_tables[slot] = table
            self._invalidate_tables()
            if self._prefix is not None \
                    and self._brownout < BROWNOUT_NO_PREFIX_INSERTS:
                self._prefix.insert(req.prompt, table)
                if self.page_publisher is not None:
                    try:
                        self.page_publisher(req.prompt, list(table))
                    except Exception:   # noqa: BLE001 — best-effort
                        log.exception("fleet page publish failed; "
                                      "admission unaffected")
        self._slots[slot] = req
        self._row_pos[slot] = primed_pos
        req.pending_token = tok
        req.trace.record("seat", engine=self.trace_identity, slot=slot)
        self._sync_accounting()

    def _release_pages(self, table) -> None:
        for p in table:
            self._pool.release(p)

    # ------------------------------------------------------------------
    # supervised recovery (serving/supervisor.py drives this)
    # ------------------------------------------------------------------
    def _quarantine_rebuild(self) -> int:
        """Drop the (possibly poisoned) device arena WHOLESALE and
        rebuild it from the host-side request ledger: fresh page pool +
        page tables + prefix cache (re-seeded by the re-primes), fresh
        arena skeleton on first re-admission, every surviving request
        re-primed from prompt + committed tokens with its pending token
        and untouched rng — each stream continues bit-identical to an
        unperturbed run. Returns the number of survivors re-admitted.
        Runs under the step lock (the supervisor is called from the
        step cycle's failure path).

        The rebuild reuses the warm prefill buckets and the compiled
        arena scatter/gather shapes, so after a full-envelope
        ``warmup()`` a recovery compiles nothing new (test-pinned).

        Survivors travel as :class:`RequestLedgerEntry` records through
        the same ``export_ledger`` capture fleet migration uses — ONE
        rebuild payload, not two hand-synced copies — including the
        pop-to-seat ``_seating`` request (at most S entries total: a
        seating request implies a free slot, so sequential free-slot
        assignment below always finds room)."""
        entries = self.export_ledger()      # actives + _seating
        self._seating = None
        self._slots = [None] * self.slots
        self._row_pos = np.zeros(self.slots, np.int64)
        self._arena_ready = False
        self._merge_keys = None
        if self._pool is not None:
            # fresh pool: the old one's refcounts may be mid-mutation
            # from the failed cycle (and chaos seizures die with it)
            self._pool = PagePool(self._pool.total_pages, self._ps)
            self._prefix = (PrefixCache(self._pool)
                            if self._prefix is not None else None)
            self._page_store = None
            self._scale_store = None
            self._paged_keys = None
            self._page_tables = [[] for _ in range(self.slots)]
            self._invalidate_tables()
            self._kv_pos_dirty = False   # the rebuilt state is fresh
            if self._kv_dtype == "int8":
                # fresh zeroed pools + scales BEFORE the re-primes:
                # int8 prefill writes through the paged path, so the
                # store must exist (bf16 rebuilds it lazily from the
                # first re-primed state)
                self._init_quant_store()
        self.net.rnn_clear_previous_state()
        self._sync_accounting()
        if self._overload is not None:
            # the replacement pool starts fresh: recompute the rung so
            # the re-primes aren't gated by pre-fault page pressure
            # (rung 3 would silently skip re-seeding the prefix cache)
            self._brownout = self._overload.brownout_level(self)
        now = time.monotonic()
        n = 0
        try:
            for entry in entries:
                req = entry.request
                if self._fail_if_dead(req, now, "during recovery"):
                    continue
                # a streamed survivor re-primes (no draw, rng untouched);
                # a never-streamed one — the pop-to-seat window request —
                # admits fresh and may even finish clean (one-token)
                req.trace.record("rebuild", engine=self.trace_identity)
                slot = self._slots.index(None)
                self._admit_one(req, slot, readmit=req.streamed)
                if self._slots[slot] is req or (
                        req.handle.done and req.handle.error is None):
                    n += 1                   # seated, or finished clean
        except BaseException as e:
            # a fault raised mid-rebuild must strand nobody: the slots
            # and _seating were cleared up front, so the escalation
            # _break can no longer see survivors that didn't make it
            # back in — fail every unseated, unresolved handle HERE,
            # then let the supervisor escalate (seated survivors get
            # their terminal event from _break's slot scan)
            seated = {id(r) for r in self._slots if r is not None}
            for entry in entries:
                if id(entry.request) not in seated \
                        and not entry.request.handle.done:
                    entry.request.handle._fail(e)
            raise
        return n

    # ------------------------------------------------------------------
    # the request-ledger seam (serving/request.RequestLedgerEntry):
    # ONE export/re-admit path shared by supervisor recovery (above)
    # and fleet migration (serving/fleet/migration.py)
    # ------------------------------------------------------------------
    def export_ledger(self, include_queued: bool = False
                      ) -> List[RequestLedgerEntry]:
        """Snapshot every in-flight request as a versioned ledger
        entry: active slots (in slot order), the pop-to-seat
        ``_seating`` request if the export lands inside that window
        (the same visibility ``_break`` gained in PR 9 — without it a
        migration would strand the popped handle forever), and,
        with ``include_queued``, the admission queue in admission
        order. Non-mutating; safe on a stopped/broken engine (the
        dead-replica export path)."""
        with self._lock:
            entries = [RequestLedgerEntry.capture(r, "active")
                       for r in self._slots if r is not None]
            if self._seating is not None:
                entries.append(RequestLedgerEntry.capture(
                    self._seating, "seating"))
            if include_queued:
                entries.extend(
                    RequestLedgerEntry.capture(r, "queued")
                    for r in self._pending.peek_all())
            return entries

    def admit_from_ledger(self, entries, where: str = "during migration"
                          ) -> int:
        """Re-admit exported ledger entries on THIS engine: streamed
        survivors re-prime from ``ids[:-1]`` with their pending token
        and untouched rng (the supervisor-recovery semantics — the
        stream continues bit-identically), never-streamed entries admit
        fresh. Entries that no longer fit a free slot ride the
        admission queue (force-requeued past the limit: survivors were
        already admitted once). Returns how many requests this engine
        took over; dead entries (cancelled / expired, or already
        carrying a terminal event) are resolved and skipped."""
        with self._lock:
            if self._broken is not None:
                raise EngineShutdown("GenerationEngine is broken: "
                                     f"{self._broken!r}")
            if self._stop.is_set():
                raise EngineShutdown("GenerationEngine shut down")
            if self._draining:
                raise EngineShutdown("GenerationEngine draining — "
                                     "migrate to another replica")
            now = time.monotonic()
            n = 0
            for entry in entries:
                req = entry.request
                if self._fail_if_dead(req, now, where):
                    continue
                if self._pool is not None:
                    store = self._store_positions(req.want)
                    if pages_needed(store, self._ps) > self._pool.usable:
                        # heterogeneous-pool edge: this replica can
                        # NEVER hold the request — fail it the way
                        # submit() would have, don't head-of-line block
                        req.handle._fail(ValueError(
                            f"migrated request holds {store} KV "
                            f"positions but this replica's pool has "
                            f"only {self._pool.usable} pages"))
                        continue
                free = (self._slots.index(None)
                        if None in self._slots else None)
                if free is not None and (
                        self._pool is None
                        or self._pages_admissible(req)):
                    self._admit_one(req, free, readmit=req.streamed)
                    if self._slots[free] is req or (
                            req.handle.done
                            and req.handle.error is None):
                        n += 1
                else:
                    req.trace.record("requeue", engine=self.trace_identity)
                    self._pending.requeue(req)
                    n += 1
            return n

    def detach_ledger(self, lock_timeout: Optional[float] = None
                      ) -> List[RequestLedgerEntry]:
        """Export EVERYTHING in flight (actives + seating + queue) and
        release it from this engine WITHOUT terminal events: the
        requests live on wherever the entries are re-admitted. The
        planned-handoff half of live migration — scale-in drains
        through this instead of waiting out ``drain()``'s natural
        retirements — and equally the post-mortem export off a dead
        replica (works under ``_stop``; a broken engine already failed
        its handles, so its export is empty). The engine is left
        draining with an empty arena, a fresh-released page pool, and a
        closed queue: terminal for this replica.

        The queued entries come from ``close()``'s drain — the SAME
        atomic removal that refuses later submits — so a request that
        squeezes through the unlocked ``submit()`` draining check
        while the detach runs is either in the export or refused,
        never silently dropped.

        ``lock_timeout`` bounds the engine-lock wait: a replica whose
        step thread wedged INSIDE a dispatch still holds the lock, and
        a caller migrating it off lease-expiry must not deadlock on it
        (raises ``TimeoutError``; the wedged engine's streams cannot
        be exported from outside the lock)."""
        if lock_timeout is not None:
            if not self._lock.acquire(timeout=lock_timeout):
                raise TimeoutError(
                    f"engine lock not released within {lock_timeout:g}s "
                    f"— a wedged dispatch still holds it; its ledger "
                    f"cannot be exported")
        else:
            self._lock.acquire()
        try:
            self._draining = True
            entries = self.export_ledger()      # actives + seating
            self._seating = None
            for s, req in enumerate(self._slots):
                if req is None:
                    continue
                self._slots[s] = None
                self._row_pos[s] = 0
                if self._pool is not None:
                    for p in self._page_tables[s]:
                        self._pool.release(p)
                    self._page_tables[s] = []
            if self._pool is not None:
                self._invalidate_tables()
                self._kv_pos_dirty = True
            entries.extend(RequestLedgerEntry.capture(r, "queued")
                           for r in self._pending.close())
            self._sync_accounting()
            return entries
        finally:
            self._lock.release()

    def detach_queued(self, max_n: Optional[int] = None
                      ) -> List[RequestLedgerEntry]:
        """Export and remove queued (never-prefilled) requests, highest
        admission priority first, up to `max_n` (None = all) — the
        overload-rebalance payload: queued work moves for free (no warm
        KV to abandon, no re-prefill debt), actives stay where their
        cache is. The queue stays open; the engine keeps serving."""
        with self._lock:
            entries = []
            while max_n is None or len(entries) < max_n:
                req = self._pending.pop()
                if req is None:
                    break
                entries.append(RequestLedgerEntry.capture(req, "queued"))
            return entries

    def queue_snapshot(self):
        """Non-mutating admission-queue view (per-priority depths +
        oldest wait) — the router's placement-scoring accessor; see
        ``serving.scheduler.QueueSnapshot``."""
        return self._pending.snapshot()

    def load_stats(self) -> dict:
        """The narrow placement-scoring payload (what the fleet
        router's hot submit path reads per candidate): slots, occupied
        slots, queue depth, and the free-page fraction (1.0 unpaged) —
        without constructing the full ``health()`` observability dict."""
        free = 1.0
        if self._pool is not None and self._pool.usable:
            free = self._pool.free_count() / self._pool.usable
        return {"slots": self.slots,
                "active_slots": self.active_slots(),
                "queue_depth": self.queue_depth(),
                "free_page_frac": free}

    # ------------------------------------------------------------------
    # disaggregated prefill/decode (serving/fleet/pages.py rides these)
    # ------------------------------------------------------------------
    def prefix_held_blocks(self, prompt) -> int:
        """Leading full `prompt` blocks already in the prefix cache
        (pure probe — no stats, no LRU touch); 0 without a cache."""
        with self._lock:
            if self._prefix is None:
                return 0
            return self._prefix.held_blocks(prompt)

    def pages_importable(self) -> bool:
        """True once :meth:`import_prefix_chain` can actually map
        shipped pages: the device pools exist (the bf16 pools
        materialize lazily at the FIRST prime — warmup or real
        traffic — because their dtype is the net's, discoverable only
        from a primed state) and prefix inserts aren't browned out.
        Agents probe this before touching the store: a fresh un-warmed
        replica's first admission primes normally and materializes the
        pools; every admission after imports."""
        with self._lock:
            return (self._pool is not None
                    and self._prefix is not None
                    and self._page_store is not None
                    and self._brownout < BROWNOUT_NO_PREFIX_INSERTS)

    def prefix_digests(self, limit: Optional[int] = None) -> List[str]:
        """Chain digests of cached prefix blocks, LRU order (most
        recent last) — the page-locality advertisement an agent puts in
        its status file."""
        with self._lock:
            if self._prefix is None:
                return []
            return self._prefix.digests(limit)

    def export_prefix_chain(self, prompt, table, store) -> dict:
        """Publish every FULL block of a just-primed `prompt` to the
        fleet page store: per block, each paged leaf's page (plus its
        int8 scale row) is read back and shipped under the block's
        chain digest. Content addressing makes this idempotent —
        already-present digests are skipped without a device read.
        Returns ``{"digests", "published", "bytes"}``."""
        with self._lock:
            out = {"digests": [], "published": 0, "bytes": 0}
            if self._pool is None or self._page_store is None:
                return out
            ps = self._ps
            n_full = len(prompt) // ps
            if not n_full:
                return out
            digs = chain_digests(prompt, ps)
            for i in range(n_full):
                out["digests"].append(digs[i])
                if store.has(digs[i], self._kv_dtype):
                    continue
                page = table[i]
                arrays = []
                for j, (n, k) in enumerate(self._paged_keys):
                    arrays.append(
                        (n, k, "kv",
                         np.asarray(self._page_store[j][page])))
                    if self._scale_store is not None:
                        arrays.append(
                            (n, k, "scale",
                             np.asarray(self._scale_store[j][page])))
                if store.publish(
                        digs[i],
                        parent=ROOT_DIGEST if i == 0 else digs[i - 1],
                        tokens=prompt[i * ps:(i + 1) * ps],
                        kv_dtype=self._kv_dtype, page_size=ps,
                        arrays=arrays):
                    out["published"] += 1
                    out["bytes"] += sum(a.nbytes for *_, a in arrays)
            return out

    def import_prefix_chain(self, prompt, start_block: int,
                            blocks) -> dict:
        """Map verified store entries (``PageStore.load`` results for
        `prompt`'s chain digests, starting at block index
        `start_block` — the first block NOT already cached locally)
        into the local pool + prefix cache. Each entry gets a fresh
        page written through the jitted single-page scatter (warmup
        precompiles it), then one ``PrefixCache.insert`` registers the
        whole run — after which an admission of this prompt takes a
        plain prefix hit and primes only the suffix, exactly as if the
        blocks had been primed here. Any shape/dtype/token mismatch
        stops the import at the blocks already validated (the suffix
        simply primes fresh — exactness never depends on the import).
        Returns ``{"blocks", "tokens", "bytes"}`` actually mapped."""
        with self._lock:
            out = {"blocks": 0, "tokens": 0, "bytes": 0}
            if (self._pool is None or self._prefix is None
                    or self._page_store is None
                    or self._brownout >= BROWNOUT_NO_PREFIX_INSERTS):
                return out
            ps = self._ps
            new_pages: List[int] = []
            for bi, entry in enumerate(blocks):
                b = start_block + bi
                lo, hi = b * ps, (b + 1) * ps
                if (entry.get("page_size") != ps or hi > len(prompt)
                        or list(entry.get("tokens", ())) !=
                        [int(t) for t in prompt[lo:hi]]):
                    break
                if not self._pool.free_count():
                    self._prefix.evict(1)
                try:
                    page = self._pool.alloc(1)[0]
                except Exception:   # noqa: BLE001 — PageExhausted et al
                    break
                arrs = {(n, k, role): a
                        for n, k, role, a in entry["arrays"]}
                writes = []
                ok = True
                for j, (n, k) in enumerate(self._paged_keys):
                    a = arrs.get((n, k, "kv"))
                    pool_j = self._page_store[j]
                    if (a is None
                            or tuple(a.shape) != tuple(pool_j.shape[1:])
                            or a.dtype != pool_j.dtype):
                        ok = False
                        break
                    writes.append((j, a, False))
                    if self._scale_store is not None:
                        sa = arrs.get((n, k, "scale"))
                        sp = self._scale_store[j]
                        if (sa is None
                                or tuple(sa.shape) != tuple(sp.shape[1:])
                                or sa.dtype != sp.dtype):
                            ok = False
                            break
                        writes.append((j, sa, True))
                if not ok:
                    self._pool.release(page)
                    break
                idx = jnp.asarray(page, jnp.int32)
                # import-time (per shipped block) uploads, not the
                # decode loop
                # tpulint: disable=device-transfer-in-hot-loop
                for j, a, is_scale in writes:
                    tgt = (self._scale_store if is_scale
                           else self._page_store)
                    tgt[j] = set_page(tgt[j], idx, jnp.asarray(a))
                    out["bytes"] += a.nbytes
                new_pages.append(page)
            if new_pages:
                covered = (start_block + len(new_pages)) * ps
                # [0]-padding for the already-held leading blocks: the
                # insert only reads table[i] for MISSING entries, and
                # blocks < start_block are present by construction
                self._prefix.insert(
                    [int(t) for t in prompt[:covered]],
                    [0] * start_block + new_pages)
                for p in new_pages:
                    self._pool.release(p)   # insert retained: the
                out["blocks"] = len(new_pages)  # cache is sole owner
                out["tokens"] = len(new_pages) * ps
                self._kv_traffic(out["tokens"] * self._tok_bytes)
            return out

    def prefill_publish(self, req: GenerationRequest, store) -> dict:
        """The PrefillAgent admission (serving/fleet/prefill.py): prime
        `req` through the normal admission path — prefix hits, the
        first-token draw, TTFT observation, prefix-cache insert all
        included — publish its full-block pages to `store`, then
        DETACH the slot instead of decoding. The prefix cache keeps the
        pages warm (and advertised); the returned record carries what
        the router needs to hand the stream to a decode replica:
        the drawn first token, the post-draw rng (the decode re-prime
        must not re-draw), the chain digests, and whether the request
        already finished (one-token requests never leave this engine).
        Raises on admission failure (no slot / prefill fault) — the
        agent nacks, the router degrades to unified placement."""
        with self._lock:
            if self._broken is not None:
                raise EngineShutdown("GenerationEngine is broken: "
                                     f"{self._broken!r}")
            if self._stop.is_set():
                raise EngineShutdown("GenerationEngine shut down")
            if self._draining:
                raise EngineShutdown("GenerationEngine draining — "
                                     "prefill elsewhere")
            now = time.monotonic()
            if self._fail_if_dead(req, now, "at prefill admission"):
                err = req.handle.error
                return {"done": True,
                        "reason": req.handle.finish_reason,
                        "error": None if err is None else repr(err),
                        "token": None, "rng": None, "digests": [],
                        "published": 0, "bytes": 0}
            free = (self._slots.index(None)
                    if None in self._slots else None)
            if free is None or (self._pool is not None
                                and not self._pages_admissible(req)):
                raise ServingOverloaded(
                    "prefill replica has no free slot/pages")
            self._admit_one(req, free, readmit=False)
            if req.handle.error is not None:
                raise req.handle.error
            pub = {"digests": [], "published": 0, "bytes": 0}
            if self._slots[free] is req:
                pub = self.export_prefix_chain(
                    req.prompt, self._page_tables[free]
                    if self._pool is not None else [], store)
                self._detach_slot(free)
            req.trace.record("prefill_publish",
                             engine=self.trace_identity,
                             blocks=len(pub["digests"]),
                             published=pub["published"])
            return {"done": req.handle.done,
                    "reason": req.handle.finish_reason,
                    "error": None,
                    "token": int(req.handle._ids[-1]),
                    "rng": rng_state_payload(req.rng),
                    "digests": pub["digests"],
                    "published": pub["published"],
                    "bytes": pub["bytes"]}

    def _detach_slot(self, slot: int) -> None:
        """Release one seated request WITHOUT a terminal event (the
        per-slot slice of ``detach_ledger``): the prefill flow seats,
        publishes, and lets the stream live on at a decode replica."""
        self._slots[slot] = None
        self._row_pos[slot] = 0
        if self._pool is not None:
            for p in self._page_tables[slot]:
                self._pool.release(p)
            self._page_tables[slot] = []
            self._invalidate_tables()
            self._kv_pos_dirty = True
        self._sync_accounting()

    def _init_page_store(self, primed_state) -> None:
        """First-admission pool build: one device page array per paged
        leaf (kv_k/kv_v of every attention layer), sized
        [total_pages, Hkv, page_size, D] in the leaf's dtype."""
        keys, store = [], []
        for n in sorted(primed_state):
            s = primed_state[n]
            if not isinstance(s, dict):
                continue
            for k in ("kv_k", "kv_v"):
                if k not in s:
                    continue
                # first-admission pool construction (runs once per
                # engine), not the per-token decode steady state
                # tpulint: disable=device-transfer-in-hot-loop
                v = jnp.asarray(s[k])      # [1, Hkv, L, D]
                if v.shape[2] != self._L:
                    raise RuntimeError(
                        f"paged leaf {n}.{k} carries length "
                        f"{v.shape[2]} != cache_length {self._L}")
                keys.append((n, k))
                store.append(jnp.zeros(
                    (self._pool.total_pages, v.shape[1], self._ps,
                     v.shape[3]), v.dtype))
        if not keys:
            raise RuntimeError("paged mode found no kv_k/kv_v leaves "
                               "in the primed stream state")
        self._paged_keys = keys
        self._page_store = store
        # per-token KV bytes summed over leaves — the unit of the
        # modeled kv-bytes-moved accounting
        self._tok_bytes = sum(
            int(p.shape[1]) * int(p.shape[3]) * p.dtype.itemsize
            for p in store)

    def _paged_layer_dims(self):
        """(state name, Hkv, head_dim) per paged attention layer,
        sorted by state name — the SAME (name, leaf) order
        _init_page_store derives from a primed state (sorted() over
        the state keys), so the eager int8 store and the lazy bf16
        store address identical leaves."""
        named = [(str(i), l) for i, l in
                 enumerate(getattr(self.net, "layers", None) or [])]
        vertices = getattr(getattr(self.net, "conf", None),
                           "vertices", None) or {}
        named += [(n, v.layer) for n, v in vertices.items()
                  if getattr(v, "layer", None) is not None]
        out = []
        for n, l in named:
            if getattr(l, "supports_streaming", False) \
                    and getattr(l, "cache_length", 0):
                hkv = getattr(l, "n_kv_heads", None) or l.n_heads
                out.append((n, int(hkv), int(l.n_out // l.n_heads)))
        return sorted(out)

    def _init_quant_store(self) -> None:
        """Eager int8 pool + scale-sidecar build (serving/quant.py):
        zeroed [P, Hkv, ps, D] int8 pools and [P, Hkv] f32 scales, two
        leaves (k, v) per attention layer. Runs at construction and
        again after a quarantine rebuild dropped the old store."""
        from deeplearning4j_tpu.serving.quant import pool_leaves
        self._paged_keys = [(n, k) for n, _, _ in self._quant_dims
                            for k in ("kv_k", "kv_v")]
        self._page_store, self._scale_store = pool_leaves(
            self._pool.total_pages, self._ps,
            [(h, d) for _, h, d in self._quant_dims])
        self._tok_bytes = sum(2 * h * d                  # int8: 1 B/el
                              for _, h, d in self._quant_dims)
        self._scale_row_bytes = sum(2 * h * 4
                                    for _, h, _ in self._quant_dims)

    def _install_prime_paged_state(self, table, hit_len: int) -> None:
        """Arm the detached batch-1 prefill to run THROUGH the paged
        path (the int8 prime: quantize-once forbids priming densely
        and converting — the prompt's pool bytes must come from the
        same quantized append the decode steps run, so a rebuild's
        re-prime reproduces them bit-identically). Installs the whole
        pools + scale sidecars, the request's one-row table, kv_pos at
        the prefix hit length, and the ``kv_page_prime`` marker that
        forces the folded-gather read and unlocks packed (pad_left)
        accounting in ``_stream_attend_paged``. On a prefix hit the
        suffix prime attends the shared pages in place — no dense
        gather, no page re-scatter (``_install_prefix``'s round trip
        has no int8 equivalent)."""
        net = self.net
        row = np.zeros((1, self._n_max), np.int32)
        row[0, :len(table)] = table
        # admission-time (per-prime) uploads, not the decode loop
        # tpulint: disable=device-transfer-in-hot-loop
        row_dev = jnp.asarray(row)
        pos = jnp.full((1,), hit_len, jnp.int32)
        marker = jnp.zeros((), jnp.int32)
        st = dict(net.state)
        for i, (n, k) in enumerate(self._paged_keys):
            cur = st.get(n)
            d = dict(cur) if isinstance(cur, dict) else {}
            d["kv_page_k" if k == "kv_k" else "kv_page_v"] = \
                self._page_store[i]
            d["kv_page_scale_k" if k == "kv_k"
              else "kv_page_scale_v"] = self._scale_store[i]
            d["kv_page_table"] = row_dev
            d["kv_page_prime"] = marker
            d["kv_pos"] = pos
            st[n] = d
        net.state = st
        net._stream_pos = hit_len
        net._stream_pos_rows = None
        if self._graph_vertices:
            net._stream_pos_map = {n: hit_len
                                   for n in self._graph_vertices}

    def _extract_prime_paged_state(self, primed_state):
        """Take the primed pools/scales back out of the prime's state
        snapshot (they are the authoritative store now — prime
        dispatches do not donate, so on failure the engine's pre-prime
        references were still valid and nothing was committed).
        Returns the cleaned state the arena build/merge sees: paged
        view keys stripped, the [1] kv_pos vector kept for the slot
        scatter."""
        out = {n: (dict(v) if isinstance(v, dict) else v)
               for n, v in primed_state.items()}
        store, scales = [], []
        for n, k in self._paged_keys:
            d = out[n]
            store.append(d.pop("kv_page_k" if k == "kv_k"
                               else "kv_page_v"))
            scales.append(d.pop("kv_page_scale_k" if k == "kv_k"
                                else "kv_page_scale_v"))
            d.pop("kv_page_table", None)
            d.pop("kv_page_prime", None)
        self._page_store = store
        self._scale_store = scales
        return out

    def _scatter_primed_pages(self, primed_state, table) -> None:
        """Commit the primed batch-1 KV into the slot's pages (one
        jitted scatter; shared prefix pages are rewritten with the
        identical bytes they were gathered from)."""
        row = np.zeros((1, self._n_max), np.int32)
        row[0, :len(table)] = table
        dense = [primed_state[n][k] for n, k in self._paged_keys]
        self._page_store = scatter_pages(self._page_store, dense, row)
        self._kv_traffic(self._L * self._tok_bytes)   # one-row commit

    def _dispatch_step(self):
        """ONE jitted decode dispatch advancing every active slot (free
        rows feed token 0; their outputs are discarded, their writes
        drop). Slots at streaming capacity retire first — they cannot
        consume another position."""
        if self._cap is not None:
            for s, req in enumerate(self._slots):
                if req is not None and self._row_pos[s] >= self._cap:
                    self._retire(s, "capacity")
        toks = np.zeros(self.slots, np.int64)
        for s, req in enumerate(self._slots):
            if req is not None:
                toks[s] = req.pending_token
        if not any(r is not None for r in self._slots):
            return None     # everything retired at the capacity guard
        self._sync_accounting()
        probs = self._run_dispatch(
            lambda: step_tokens(self.net, toks, self.V,
                                donate_state=self._donate))
        for s, req in enumerate(self._slots):
            if req is not None:
                self._row_pos[s] += 1
        self._sync_accounting()
        return probs

    def _run_dispatch(self, fn, width: int = 1):
        """The ONE paged/chaos/retry wrapper around a decode or verify
        dispatch (`width` = appended positions per row: 1 plain,
        1 + gamma speculative), with the chaos hook INSIDE the retried
        callable (the fault fires before any state mutates, so a
        retried dispatch is numerically identical to a fault-free one).

        Paged modes differ in what moves around `fn`:

        - DIRECT (the fast path): the pool + cached page tables are
          installed into ``net.state`` as references — the dispatch
          itself reads K/V through the table and appends the new
          tokens' K/V in place (O(one-token) write); afterwards the
          updated pool references are extracted back. Nothing is
          materialized densely, nothing is scattered back.
        - legacy round trip (``PagedKVConfig(direct=False)``, the bench
          A/B baseline): gather the dense view from the pool, run the
          dispatch over it, commit the updated view back BEFORE any
          retirement the outputs trigger can free pages.

        Every cycle lands in the dispatch-latency histogram and the
        modeled KV traffic in the kv-bytes-moved counter."""
        direct = self._pool is not None and self._direct
        table = None
        if direct:
            self._install_paged_state()
        elif self._pool is not None:
            table = self._paged_gather()

        def once():
            _fire_chaos(self._decode_chaos, self._dispatches)
            return fn()

        t0 = time.perf_counter()
        out = (retry_call(once, policy=self._decode_retry,
                          op="serving_decode")
               if self._decode_retry is not None else once())
        if direct:
            self._extract_paged_state()
        elif table is not None:
            self._paged_scatter(table)
        dt = time.perf_counter() - t0
        self._dispatch_s_total += dt
        self._dispatch_hist.observe(dt)
        if self._pool is not None:
            self._kv_traffic(self._kv_dispatch_bytes(width))
        self._dispatches += 1
        return out

    # ------------------------------------------------------------------
    # the paged pool <-> dispatch plumbing (direct view / legacy round
    # trip) + cached page tables
    # ------------------------------------------------------------------
    def _live_impl(self) -> Optional[str]:
        """The impl direct dispatches run under RIGHT NOW — the
        process-wide setting, which a later engine's construction can
        flip (retracing this engine's next dispatch onto the new
        path). ``self._decode_impl`` records only what THIS engine
        resolved at construction."""
        return paged_decode_impl()[0] if self._direct else None

    def _invalidate_tables(self) -> None:
        """Drop the cached [S, n_max] table snapshots — call after ANY
        page-table mutation (admit / retire / rebuild). Between
        mutations every dispatch reuses the same host array and device
        upload(s): steady-state decode re-uploads nothing."""
        self._tables_cache = None
        self._table_dev_cache = None
        self._tables_layer_cache = None

    def _tables_np(self) -> np.ndarray:
        if self._tables_cache is None:
            t = np.zeros((self.slots, self._n_max), np.int32)
            for s, pages in enumerate(self._page_tables):
                t[s, :len(pages)] = pages
            self._tables_cache = t
        return self._tables_cache

    def _table_dev(self):
        """One shared device copy of the table (the legacy round trip's
        gather/scatter argument)."""
        if self._table_dev_cache is None:
            self._table_dev_cache = jnp.asarray(self._tables_np())
        return self._table_dev_cache

    def _tables_dev_per_layer(self):
        """Device table copies, one DISTINCT buffer per paged layer:
        the direct path donates the whole state pytree on TPU, and
        donation must never see the same buffer at two leaves."""
        if self._tables_layer_cache is None:
            tnp = self._tables_np()
            self._tables_layer_cache = {
                n: jnp.asarray(tnp)
                for n in dict.fromkeys(n for n, _ in self._paged_keys)}
        return self._tables_layer_cache

    def _install_paged_state(self) -> None:
        """Install the paged decode view for the coming dispatch: each
        paged layer's state dict gains the pool pair + its page table
        (the paged state protocol —
        ``SelfAttentionLayer._stream_attend_paged``). Pure reference
        plumbing: no bytes move here, and the table device upload
        happens only on the first dispatch after a mutation."""
        tables = self._tables_dev_per_layer()
        st = dict(self.net.state)
        for i, ((n, k), pool) in enumerate(zip(self._paged_keys,
                                               self._page_store)):
            d = dict(st[n])
            d["kv_page_k" if k == "kv_k" else "kv_page_v"] = pool
            if self._scale_store is not None:
                d["kv_page_scale_k" if k == "kv_k"
                  else "kv_page_scale_v"] = self._scale_store[i]
            d["kv_page_table"] = tables[n]
            st[n] = d
        if self._kv_pos_dirty:
            # a retirement left free rows' device kv_pos coasting:
            # without a reset a once-long idle slot keeps its stale
            # length forever (the kernel would scan its dead blocks
            # every step, and the modeled bytes would drift from the
            # real reads). One tiny [S] where per layer, only on the
            # first dispatch after a retirement — free rows' appends
            # already route to the null page, so zeroing their
            # positions changes nothing any live request reads.
            # one-shot, not per-step: guarded by _kv_pos_dirty, which
            # only a retirement sets — steady-state installs skip this
            # tpulint: disable=device-transfer-in-hot-loop
            free = jnp.asarray([r is None for r in self._slots])
            for n in dict.fromkeys(n for n, _ in self._paged_keys):
                d = st[n]
                d["kv_pos"] = jnp.where(free, 0, d["kv_pos"])
            self._kv_pos_dirty = False
        self.net.state = st

    def _extract_paged_state(self) -> None:
        """Pull the (appended-to) pools back out of ``net.state`` after
        a direct dispatch, and refresh the per-layer table cache from
        the returned leaves — under donation the pre-dispatch buffers
        are consumed, so the returned references are the only live
        copies."""
        st = dict(self.net.state)
        store = [st[n]["kv_page_k" if k == "kv_k" else "kv_page_v"]
                 for n, k in self._paged_keys]
        if self._scale_store is not None:
            # under donation the returned scale leaves are likewise the
            # only live copies (base-token appends rewrite scale rows)
            self._scale_store = [
                st[n]["kv_page_scale_k" if k == "kv_k"
                      else "kv_page_scale_v"]
                for n, k in self._paged_keys]
        tables = {}
        for n in dict.fromkeys(n for n, _ in self._paged_keys):
            d = dict(st[n])
            tables[n] = d.pop("kv_page_table")
            d.pop("kv_page_k", None)
            d.pop("kv_page_v", None)
            d.pop("kv_page_scale_k", None)
            d.pop("kv_page_scale_v", None)
            st[n] = d
        self._page_store = store
        if self._state_donated and self._donate:
            # donation consumed the installed buffers: the returned
            # (pass-through) table leaves are the only live copies
            self._tables_layer_cache = tables
        self.net.state = st

    # -- modeled KV traffic (serving/health.SERVING_KV_BYTES_MOVED) ----
    def _kv_traffic(self, nbytes: int) -> None:
        if nbytes:
            self._kv_bytes_total += int(nbytes)
            self._kv_bytes.inc(int(nbytes))

    def _kv_dispatch_bytes(self, width: int) -> int:
        """Bytes the KV path moves around ONE dispatch, modeled from
        the path in use (summed over attention leaves; reads + writes):

        - legacy round trip: the gather materializes the full dense
          [S, L] view and the scatter writes it all back — 2·S·L
          positions regardless of live context.
        - direct-xla: the folded gather still materializes the mapped
          [S, L] view once inside the dispatch (S·L reads), but the
          write is the one-token append (S·width).
        - direct-pallas: only LIVE pages are read (the table-indexed
          block specs skip dead blocks to the null page) — sum of each
          active row's page-rounded context — plus the append.

        int8 adds the scale-sidecar reads (one f32 row per page per
        leaf): the xla gather folds the whole ``scales[table]`` view
        (S·n_max rows), the kernel prefetches one row per live page.
        Tiny next to the halved pool bytes — but the model is exact,
        so the test pins both terms.
        """
        if self._tok_bytes == 0:
            return 0
        S, L, ps = self.slots, self._L, self._ps
        if not self._direct:
            return 2 * S * L * self._tok_bytes
        append = S * width * self._tok_bytes
        if self._live_impl() == "pallas":
            live = sum(
                min(-(-int(self._row_pos[s] + width) // ps) * ps, L)
                for s, r in enumerate(self._slots) if r is not None)
            return (live * self._tok_bytes + append
                    + (live // ps) * self._scale_row_bytes)
        return (S * L * self._tok_bytes + append
                + S * self._n_max * self._scale_row_bytes)

    def _paged_gather(self):
        """Legacy round trip: materialize the dense per-slot KV view
        from the pool into ``net.state`` for the coming dispatch;
        returns the (cached) device page table it was gathered through
        (the scatter must use the same snapshot)."""
        table = self._table_dev()
        dense = gather_pages(self._page_store, table, length=self._L)
        st = dict(self.net.state)
        for (n, k), leaf in zip(self._paged_keys, dense):
            d = dict(st[n])
            d[k] = leaf
            st[n] = d
        self.net.state = st
        return table

    def _paged_scatter(self, table) -> None:
        """Legacy round trip: commit the dispatch's updated dense KV
        back to the mapped pages (donated in-place pool update). Must
        run before any retirement triggered by the dispatch's outputs —
        freed pages may be re-allocated at the next admission."""
        dense = [self.net.state[n][k] for n, k in self._paged_keys]
        self._page_store = scatter_pages(self._page_store, dense, table)

    def _retire(self, slot: int, reason: str,
                exc: Optional[BaseException] = None) -> None:
        """Free `slot` immediately — host bookkeeping only, no device
        op: the row's stale cache is invisible (its writes drop, its
        output is discarded) until the next admission overwrites it."""
        req = self._slots[slot]
        self._slots[slot] = None
        self._row_pos[slot] = 0
        if self._pool is not None:
            # pages return to the pool immediately; blocks the prefix
            # cache also references stay resident at the cache's own
            # refcount, warm for the next request sharing them
            for p in self._page_tables[slot]:
                self._pool.release(p)
            self._page_tables[slot] = []
            self._invalidate_tables()
            self._kv_pos_dirty = True
        if exc is not None:
            req.handle._fail(exc, reason)
        else:
            req.handle._finish(reason)
        self._recent_traces.append(req.trace)

    # ------------------------------------------------------------------
    # arena state plumbing
    # ------------------------------------------------------------------
    def _build_arena(self, primed_state, base_state):
        """First-admission skeleton: every stream key of the primed
        structure broadcast to S zeroed rows (kv_abs rows start -1 =
        empty, matching a fresh rolling cache), per-row kv_pos vector at
        0. Free rows are inert: nothing reads them until a scatter
        overwrites them.

        DIRECT paged mode drops the dense kv_k/kv_v leaves entirely:
        the pool is the only KV storage (no [S, Hkv, L, D] arena copy
        exists to allocate, gather into, or scatter from — the memory
        half of the round-trip elimination); the per-dispatch paged
        view rides in via _install_paged_state instead."""
        S = self.slots
        arena = {}
        for name, s in primed_state.items():
            if not isinstance(s, dict):
                arena[name] = s
                continue
            if "kv_mask" in s:
                raise RuntimeError(
                    "engine prefill must be maskless (packed padded "
                    "priming) — a kv_mask in the primed state means the "
                    "stream was primed with an explicit mask")
            d = dict(base_state.get(name, {}) if isinstance(
                base_state.get(name), dict) else {})
            d.update({k: v for k, v in s.items()
                      if k not in _SCATTER_KEYS})
            for k, v in s.items():
                if k not in _SCATTER_KEYS:
                    continue
                if self._direct and k in ("kv_k", "kv_v"):
                    continue        # the page pool IS the KV storage
                # admission-time arena construction (slot lifecycle),
                # not the per-token decode steady state
                # tpulint: disable=device-transfer-in-hot-loop
                v = jnp.asarray(v)
                if k == "kv_pos":
                    d[k] = jnp.zeros((S,), v.dtype)
                elif k == "kv_abs":
                    d[k] = jnp.full((S,) + v.shape, -1, v.dtype)
                else:                      # batch-leading cache/state
                    d[k] = jnp.zeros((S,) + v.shape[1:], v.dtype)
            arena[name] = d
        return arena

    def _merge(self, arena_state, primed_state, slot: int):
        if self._merge_keys is None:
            # paged leaves join through the page scatter, not the dense
            # arena (their dense view is rebuilt from the pool per step)
            excl = {"kv_k", "kv_v"} if self._pool is not None else set()
            self._merge_keys = [
                (n, k) for n in sorted(primed_state)
                if isinstance(primed_state[n], dict)
                for k in sorted(primed_state[n])
                if k in _SCATTER_KEYS and k not in excl]
        arena_leaves = [arena_state[n][k] for n, k in self._merge_keys]
        primed_leaves = [primed_state[n][k] for n, k in self._merge_keys]
        new_leaves = _scatter_rows(arena_leaves, primed_leaves,
                                   np.int32(slot))
        out = {n: (dict(v) if isinstance(v, dict) else v)
               for n, v in arena_state.items()}
        for (n, k), leaf in zip(self._merge_keys, new_leaves):
            out[n][k] = leaf
        return out

    @staticmethod
    def _net_pos(net) -> int:
        pm = getattr(net, "_stream_pos_map", None)
        if pm:
            return int(max(pm.values()))
        return int(getattr(net, "_stream_pos", 0) or 0)

    def _save_accounting(self):
        net = self.net
        pm = getattr(net, "_stream_pos_map", None)
        return (getattr(net, "_stream_pos", 0),
                getattr(net, "_stream_pos_rows", None),
                dict(pm) if pm is not None else None)

    def _restore_accounting(self, saved) -> None:
        pos, rows, pmap = saved
        net = self.net
        net._stream_pos = pos
        net._stream_pos_rows = rows
        if pmap is not None:
            net._stream_pos_map = pmap

    def _sync_accounting(self) -> None:
        """Engine-owned host position mirrors: active rows carry their
        true positions, free rows pin to 0 so an idle slot can never
        trip the stream-budget guard while its device-side counter
        coasts (those writes drop harmlessly)."""
        net = self.net
        mask = np.array([r is not None for r in self._slots])
        rows = np.where(mask, self._row_pos, 0).astype(np.int64)
        pos = int(rows.max()) if mask.any() else 0
        net._stream_pos = pos
        net._stream_pos_rows = rows
        if self._graph_vertices:
            net._stream_pos_map = {n: pos for n in self._graph_vertices}

    # ------------------------------------------------------------------
    # warmup
    # ------------------------------------------------------------------
    def warmup(self, max_prompt_len: Optional[int] = None,
               steps: int = 2) -> "GenerationEngine":
        """Compile every canonical serving shape before traffic: one
        synthetic greedy request per power-of-two prime bucket up to
        bucket(max_prompt_len) (default: the net's streaming capacity),
        driven to completion. Warms the per-bucket prefill, the
        scatter-join, and the [S, V, 1] decode dispatch, so staggered
        admissions of ANY prompt length <= max_prompt_len afterwards
        cause zero retraces (the PR 3 acceptance bar)."""
        if self._worker is not None and self._worker.is_alive():
            raise RuntimeError("warm up before start(): warmup drives "
                               "step() manually")
        cap = self._cap
        top = max_prompt_len
        if top is None:
            top = (cap - 1) if cap is not None else 64
        top = max(1, int(top))
        lens, n = [], 1
        while n <= top:
            lens.append(n)
            n *= 2
        if top not in lens:
            lens.append(top)      # a non-pow2 top primes at bucket(top)
        if cap is not None:
            lens = sorted({min(v, cap - 1) for v in lens})
        if self._speculation is not None and cap is not None:
            room = cap - self._speculation.gamma + 1 - steps
            lens = sorted({max(1, min(v, room)) for v in lens})
        tok = 1 if self.V > 1 else 0

        def drive(prompt):
            # drain per request: warmup must not depend on queue_limit
            # headroom (block policy would deadlock, fail_fast would
            # reject, with more buckets than queue slots)
            h = self.submit(prompt, steps=steps, top_k=1,
                            rng=np.random.default_rng(0))
            self.run_until_idle()
            h.result(timeout=0)

        # fresh pass: every prime bucket from an empty stream. The
        # prefix cache is bypassed so one bucket's blocks cannot short-
        # circuit a longer bucket's fresh-prime shape out of the warm set
        prefix, self._prefix = self._prefix, None
        try:
            for v in lens:
                drive([tok] * v)
        finally:
            self._prefix = prefix
        top = max(lens)        # post-clamp envelope (capacity, spec)
        if prefix is not None and top > self._ps:
            # prefix pass: warm the hit path — the [1, n_max] page
            # gather plus every WITH-PREFIX suffix-prime bucket a
            # cached-hit admission can reach. Seed one base block
            # (token 0 — disjoint from the fresh pass), then hit it
            # with suffixes covering each bucket; suffix leads cycle
            # the vocab so iterations don't chain-hit each other.
            ps = self._ps
            room = top - ps
            sfx, n = [], 1
            while n <= room:
                sfx.append(n)
                n *= 2
            if room not in sfx:
                sfx.append(room)
            drive([0] * (ps + 1))          # seed: caches the base block
            for j, b in enumerate(sorted(set(sfx))):
                lead = 1 + j % (self.V - 1) if self.V > 1 else 0
                drive([0] * ps + [lead] * b)
        if self._pool is not None and self._page_store is not None:
            # precompile the fleet page-ship seam for every pool leaf
            # by round-tripping the null page (zeros out, zeros back):
            # the export-side one-page gather and the import-side
            # jitted single-page scatter (paging.set_page) both land in
            # the compile cache here, so a later store import/publish
            # causes zero retraces — page-import admissions stay under
            # the same zero-retrace pin as everything else
            idx = jnp.asarray(0, jnp.int32)
            stores = [self._page_store]
            if self._scale_store is not None:
                stores.append(self._scale_store)
            for pools in stores:
                for j, pool in enumerate(pools):
                    z = np.zeros_like(np.asarray(pool[0]))
                    pools[j] = set_page(pool, idx, jnp.asarray(z))
        if self._overload is not None:
            # warmup TTFTs carry compile time — real traffic must not
            # inherit them as breach evidence or an admission rate
            self._overload.reset_observations()
        return self

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> "GenerationEngine":
        """Run the dispatch loop on a background thread (the serving
        deployment shape; manual ``step()`` still works for warmup)."""
        if self._stop.is_set():
            raise EngineShutdown("GenerationEngine shut down")
        if self._worker is not None and self._worker.is_alive():
            return self
        self._worker = threading.Thread(target=self._engine_loop,
                                        daemon=True)
        self._worker.start()
        return self

    def _engine_loop(self):
        try:
            while not self._stop.is_set():
                if not self.step():
                    if self._draining:
                        # the queue is closed while draining: wait()
                        # would return immediately and busy-spin
                        time.sleep(0.02)
                    else:
                        self._pending.wait(0.02)
        except Exception as e:  # noqa: BLE001 — strand no waiters
            log.exception("GenerationEngine loop died")
            self._break(e)

    def _flight_traces(self) -> list:
        """The flight recorder's request context: in-flight traces
        (slots + the pop-to-seat window) first, then recently retired
        ones — newest history the post-mortem most wants."""
        traces = [r.trace for r in self._slots if r is not None]
        if self._seating is not None:
            traces.append(self._seating.trace)
        traces.extend(reversed(self._recent_traces))
        return traces

    def _break(self, exc: BaseException) -> None:
        """Terminal failure: fail every in-flight and queued request
        with the original error and refuse new work. A broken arena is
        not resumable (the failed dispatch may or may not have consumed
        positions). With a supervisor this is the ESCALATION state —
        recovery already declined (budget exhausted / rebuild failed)."""
        with self._lock:
            self._broken = exc
            # stop the loop too: with the queue closed, wait() returns
            # immediately — a broken engine must park, not busy-spin
            self._stop.set()
            self._emit_serving_event("break", error=repr(exc))
            # post-mortem artifact BEFORE the handles are failed and
            # the queue drained — the bundle must show the state the
            # fault found, not the rubble _break leaves. Best-effort
            # and rate-limited inside maybe_dump.
            flightrecorder.maybe_dump(
                "engine_break", error=exc, health=self.health(),
                queue=self._pending.snapshot(),
                traces=self._flight_traces())
            if self._seating is not None:
                # popped but never seated: fail it here or nobody will
                req, self._seating = self._seating, None
                if not req.handle.done:
                    req.handle._fail(exc)
            for s, req in enumerate(self._slots):
                if req is not None:
                    self._retire(s, "error", exc)
            for req in self._pending.close():
                req.handle._fail(exc)

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admission and finish the actives: the clean handoff
        point for a planned restart (config rollout, re-shard, binary
        upgrade). New submits are refused (``EngineShutdown``), queued
        never-prefilled requests fail immediately with the same (their
        callers resubmit to the replacement instance — cheaper than
        making them wait out a drain they cannot benefit from), and
        every ACTIVE request runs to its natural retirement: work
        already prefilled is work worth finishing.

        Works under the background loop (waits for it to finish the
        actives) or in manual mode (drives ``step()`` itself). Returns
        True when the arena emptied within `timeout` (None = wait
        forever); False on timeout or a broken/shut-down engine — the
        handoff then needs the supervisor's escalation story, not a
        clean restart."""
        self._draining = True
        self._emit_serving_event("drain")
        for req in self._pending.close():
            req.handle._fail(EngineShutdown(
                "GenerationEngine draining — resubmit to the "
                "replacement instance"))
        deadline = None if timeout is None else \
            time.monotonic() + float(timeout)
        threaded = self._worker is not None and self._worker.is_alive()
        while self.active_slots() > 0 and self._broken is None \
                and not self._stop.is_set():
            if deadline is not None and time.monotonic() >= deadline:
                break
            if threaded:
                time.sleep(0.005)
            elif not self.step():
                break
        return self.active_slots() == 0 and self._broken is None \
            and not self._stop.is_set()

    def shutdown(self) -> None:
        """Stop the loop and fail everything still in flight — nobody
        blocks forever on a dead server (the ParallelInference
        contract). Idempotent."""
        self._stop.set()
        for req in self._pending.close():
            req.handle._fail(EngineShutdown("GenerationEngine shut down"))
        if self._worker is not None and self._worker.is_alive():
            self._worker.join(timeout=5.0)
        with self._lock:
            if self._seating is not None:
                req, self._seating = self._seating, None
                if not req.handle.done:
                    req.handle._fail(EngineShutdown(
                        "GenerationEngine shut down"))
            for s, req in enumerate(self._slots):
                if req is not None:
                    self._retire(s, "error", EngineShutdown(
                        "GenerationEngine shut down"))
