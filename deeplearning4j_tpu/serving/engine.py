"""Continuous-batching generation engine over a slot-based KV arena.

The one-shot batch decoders (``util/decoding.sample_stream_batch``)
stall a serving batch on its slowest request and re-dispatch from
scratch per call. This engine decomposes the serving batch into
independently admitted/retired micro-units (the μ-batching lever,
arXiv:1804.04806) while keeping the dispatch loop free of per-request
shape work (the framework-overhead lesson of arXiv:2001.04206):

- **Slot arena**: the net's carried streaming state (attention KV
  caches, LSTM h/c) lives at a fixed batch of S slots — ONE canonical
  ``[S, V, 1]`` decode dispatch advances every active request per step,
  so after warmup the steady state never retraces regardless of request
  mix. Per-slot positions ride the per-row ``kv_pos`` vector the
  batched-speculation machinery introduced; free slots idle harmlessly
  (their writes drop, their outputs are discarded).
- **Admission mid-flight**: a request prefills at batch 1 through the
  shared ``_prime_padded`` width buckets (one left-padded dispatch, one
  jit shape per power-of-two bucket) into a detached state that ONE
  jitted scatter joins to the arena at its slot — running requests
  never wait for a newcomer's prompt.
- **Retirement per request**: stop-token / length / capacity /
  deadline / cancellation free the slot immediately (host bookkeeping
  only — no device op); the next queued request takes it on the same
  step.
- **Streaming**: tokens stream to a per-request ``GenerationStream``
  handle as each dispatch retires — TTFT is queue-wait + one prefill,
  not a batch drain.

Greedy (top_k=1) per-request outputs are bit-identical to one-shot
``sample_stream`` with the same rng (test-pinned): the arena feeds each
request exactly the token sequence a dedicated stream would, row
independence makes the math per-slot, and each request draws from its
OWN rng in generation order.

Exactness conditions are ``sample_stream_batch``'s: recurrent (LSTM)
state or attention with rope / no positions. Models with LEARNED
positional tables are rejected at construction (``pos_offset`` is a
scalar shared across the batch — it cannot track per-slot positions).

Chaos/resilience seams (tests/test_serving_engine.py drives these with
``resilience/chaos.py`` injectors): ``prefill_chaos`` fires before each
admission's prefill — a raise fails THAT request only, the arena is
restored untouched; ``decode_chaos`` fires before each decode dispatch
INSIDE the optional ``decode_retry`` RetryPolicy — a transient
mid-stream preemption is retried with numerics identical to a
fault-free run (the fault fires before any state mutates).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.monitoring.metrics import (
    MetricsRegistry, global_registry)
from deeplearning4j_tpu.nn.conf.layers import (
    BATCHED_STREAM_KEYS, PositionalEmbeddingLayer, stream_capacity)
from deeplearning4j_tpu.resilience.chaos import fire as _fire_chaos
from deeplearning4j_tpu.resilience.retry import RetryPolicy, retry_call
from deeplearning4j_tpu.serving.errors import (
    EngineShutdown, InferenceTimeout, RequestCancelled, ServingQueueFull)
from deeplearning4j_tpu.serving.health import (
    SERVING_ACTIVE_SLOTS, SERVING_DEADLINE_EXCEEDED, SERVING_ERRORS,
    SERVING_QUEUE_REJECTED, SERVING_QUEUE_WAIT, SERVING_REQUESTS,
    SERVING_TOKENS, SERVING_TPOT, SERVING_TTFT, register_serving_metrics,
    scrape_probe)
from deeplearning4j_tpu.serving.request import (
    GenerationRequest, GenerationStream)
from deeplearning4j_tpu.serving.scheduler import AdmissionQueue
from deeplearning4j_tpu.util.decoding import (
    _check_seed, _stream_layers, draw, prime_prompt, step_tokens,
    stop_reason)

log = logging.getLogger(__name__)

#: stream-state keys the admission scatter writes into the arena row
#: (kv_mask is deliberately absent: engine prefill is packed/maskless,
#: so per-slot validity is carried by kv_pos alone)
_SCATTER_KEYS = frozenset(BATCHED_STREAM_KEYS | {"kv_pos", "kv_abs"}) \
    - {"kv_mask"}


@jax.jit
def _scatter_rows(arena, primed, slot):
    """Join one primed request's stream state into the arena at `slot`:
    batch-leading leaves take the primed row 0, per-row counters
    (kv_pos [S] <- scalar, kv_abs [S, L] <- [L]) take the primed value.
    One trace per net structure — `slot` rides as a traced scalar."""
    out = []
    for a, p in zip(arena, primed):
        out.append(a.at[slot].set(p[0] if p.ndim == a.ndim else p))
    return out


class GenerationEngine:
    """Continuous-batching generation over a fixed S-slot arena.

    Drive it manually (``submit()`` then ``step()`` /
    ``run_until_idle()`` — deterministic single-threaded serving, the
    test/bench shape) or start the background loop (``start()`` /
    ``shutdown()``) and consume ``GenerationStream`` handles from any
    thread.
    """

    def __init__(self, net, vocab_size: int, slots: int = 8,
                 queue_limit: int = 64, queue_policy: str = "block",
                 prime_padded: bool = True,
                 registry: Optional[MetricsRegistry] = None,
                 name: Optional[str] = None,
                 prefill_chaos=None, decode_chaos=None,
                 decode_retry: Optional[RetryPolicy] = None):
        if not hasattr(net, "rnn_time_step"):
            raise TypeError("GenerationEngine needs a streaming net "
                            "(rnn_time_step / rnn_clear_previous_state)")
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if vocab_size < 1:
            raise ValueError(f"vocab_size must be >= 1, got {vocab_size}")
        if getattr(net, "_initialized", True) is False:
            net.init()
        layers = list(_stream_layers(net))
        for l in layers:
            if isinstance(l, PositionalEmbeddingLayer):
                raise ValueError(
                    "continuous batching needs per-slot positions: "
                    "learned positional tables carry a shared pos_offset "
                    "(use a rope, position-free, or recurrent model)")
        net_inputs = getattr(getattr(net, "conf", None),
                             "network_inputs", None)
        if net_inputs is not None and len(net_inputs) != 1:
            raise ValueError("GenerationEngine serves single-input "
                             "decoder graphs only")
        self.net = net
        self.V = int(vocab_size)
        self.slots = int(slots)
        self._cap = stream_capacity(layers)
        self._prime_padded = bool(prime_padded)
        self._label = name or f"engine:{type(net).__name__}"
        self._graph_vertices = tuple(
            n for n, v in (getattr(net.conf, "vertices", None) or {}).items()
            if getattr(getattr(v, "layer", None), "supports_streaming",
                       False)) if hasattr(net, "conf") else ()
        self._pending = AdmissionQueue(queue_limit, queue_policy)
        self._slots: List[Optional[GenerationRequest]] = [None] * slots
        self._row_pos = np.zeros(slots, np.int64)
        self._arena_ready = False
        self._merge_keys = None
        self._admissions = 0
        self._dispatches = 0
        self._prefill_chaos = prefill_chaos
        self._decode_chaos = decode_chaos
        self._decode_retry = decode_retry
        self._stop = threading.Event()
        self._worker: Optional[threading.Thread] = None
        self._broken: Optional[BaseException] = None
        # ONE lock serializes every arena/net touch: step() may run from
        # the background loop while warmup/manual drivers call in
        self._lock = threading.RLock()
        net.rnn_clear_previous_state()     # the engine owns the stream
        self._register_metrics(registry)

    # ------------------------------------------------------------------
    # telemetry
    # ------------------------------------------------------------------
    def _register_metrics(self, registry) -> None:
        r = registry or global_registry()
        self._handles = register_serving_metrics(self, self._label,
                                                 registry)
        lab = dict(model=self._label)
        self._tokens = r.counter(
            SERVING_TOKENS, "Tokens generated by the serving engine",
            ("model",)).labels(**lab)
        self._ttft_hist = r.histogram(
            SERVING_TTFT, "Seconds from submit to first token",
            ("model",)).labels(**lab)
        self._tpot_hist = r.histogram(
            SERVING_TPOT, "Seconds between consecutive tokens of one "
            "request", ("model",)).labels(**lab)
        self._queue_wait_hist = r.histogram(
            SERVING_QUEUE_WAIT, "Seconds a request waited for admission",
            ("model",)).labels(**lab)
        r.gauge(SERVING_ACTIVE_SLOTS, "Arena slots holding an active "
                "request", ("model",)).set_function(
            scrape_probe(self, lambda s: s.active_slots()),
            model=self._label)

    # ------------------------------------------------------------------
    # health / readiness (the ParallelInference probe contract)
    # ------------------------------------------------------------------
    def is_healthy(self) -> bool:
        if self._broken is not None or self._stop.is_set():
            return False
        if self._worker is not None and not self._worker.is_alive():
            return False
        return True

    def is_ready(self) -> bool:
        return self.is_healthy() and not self._pending.full()

    def queue_depth(self) -> int:
        return self._pending.depth()

    def active_slots(self) -> int:
        return sum(r is not None for r in self._slots)

    def health(self) -> dict:
        return {"healthy": self.is_healthy(), "ready": self.is_ready(),
                "queue_depth": self.queue_depth(),
                "active_slots": self.active_slots(),
                "slots": self.slots}

    # ------------------------------------------------------------------
    # submission
    # ------------------------------------------------------------------
    def submit(self, prompt, steps: int, *, temperature: float = 1.0,
               top_k: Optional[int] = None, top_p: Optional[float] = None,
               stop_tokens=(), rng=None, timeout: Optional[float] = None,
               priority: int = 0,
               max_length: Optional[int] = None) -> GenerationStream:
        """Queue one prompt for up to `steps` generated tokens; returns
        its streaming handle immediately (admission happens on a later
        ``step()``). Arguments mirror ``sample_stream`` — same rng, same
        stop semantics, `max_length` defaulting to the net's streaming
        capacity — plus serving controls: `timeout` (end-to-end deadline
        in seconds; expiry anywhere — queued or mid-generation — fails
        the handle with InferenceTimeout and frees the slot) and
        `priority` (higher admitted first)."""
        if self._broken is not None:
            raise EngineShutdown("GenerationEngine is broken: "
                                 f"{self._broken!r}")
        if self._stop.is_set():
            raise EngineShutdown("GenerationEngine shut down")
        prompt = [int(t) for t in prompt]
        if max_length is None:
            max_length = self._cap
        _check_seed(prompt, steps, max_length)
        if self._cap is not None and len(prompt) > self._cap:
            raise ValueError(
                f"prompt of {len(prompt)} tokens exceeds the net's "
                f"streaming capacity ({self._cap})")
        self._handles[SERVING_REQUESTS].inc()
        deadline = None if timeout is None else \
            time.monotonic() + float(timeout)
        req = GenerationRequest(
            prompt, steps, temperature=temperature, top_k=top_k,
            top_p=top_p, stop_tokens=stop_tokens, rng=rng,
            max_length=max_length, deadline=deadline, priority=priority)
        try:
            self._pending.submit(req)
        except ServingQueueFull:
            self._handles[SERVING_QUEUE_REJECTED].inc()
            raise
        except InferenceTimeout:
            self._handles[SERVING_DEADLINE_EXCEEDED].inc()
            raise
        return req.handle

    # ------------------------------------------------------------------
    # the dispatch cycle
    # ------------------------------------------------------------------
    def step(self) -> bool:
        """One engine cycle: expire/cancel, admit into free slots, one
        decode dispatch over the arena, sample + stream + retire.
        Returns whether any progress was made (False = idle)."""
        with self._lock:
            if self._stop.is_set() or self._broken is not None:
                return False
            now = time.monotonic()
            progress = self._reap(now) > 0
            progress = self._admit_ready(now) > 0 or progress
            active = [s for s, r in enumerate(self._slots)
                      if r is not None]
            if not active:
                return progress
            try:
                probs = self._dispatch_step()
            except Exception as e:  # noqa: BLE001 — fail waiters, not hang
                self._handles[SERVING_ERRORS].inc()
                self._break(e)
                return False
            now = time.monotonic()
            for s in active:
                req = self._slots[s]
                if req is None:        # retired by the capacity guard
                    continue
                tok = draw(probs[s], req.temperature, req.rng,
                           top_k=req.top_k, top_p=req.top_p)
                if req.last_token_t is not None:
                    self._tpot_hist.observe(now - req.last_token_t)
                req.last_token_t = now
                req.handle._push(tok)
                self._tokens.inc()
                reason = stop_reason(tok, len(req.handle._ids), req.want,
                                     req.stop_tokens)
                if reason:
                    self._retire(s, reason)
                else:
                    req.pending_token = tok
            return True

    def run_until_idle(self, max_steps: int = 1_000_000) -> int:
        """Manually drive ``step()`` until nothing is active or
        admissible (single-threaded serving: tests, warmup, offline
        drains). Returns the number of cycles taken."""
        n = 0
        while self.step():
            n += 1
            if n >= max_steps:
                raise RuntimeError(f"engine still busy after {n} steps")
        return n

    def _reap(self, now: float) -> int:
        """Retire expired/cancelled requests, ACTIVE (frees their slots
        — a slow consumer cannot squat the arena) and QUEUED (a full
        arena must not defer a queued request's deadline until a slot
        happens to free)."""
        n = 0
        for req in self._pending.reap(now):
            n += 1
            if req.handle.cancelled:
                req.handle._fail(RequestCancelled(
                    "request cancelled while queued"), reason="cancelled")
            else:
                self._handles[SERVING_DEADLINE_EXCEEDED].inc()
                req.handle._fail(InferenceTimeout(
                    "deadline expired in the admission queue"))
        for s, req in enumerate(self._slots):
            if req is None:
                continue
            if req.handle.cancelled:
                self._retire(s, "cancelled",
                             RequestCancelled("request cancelled"))
                n += 1
            elif req.deadline is not None and now >= req.deadline:
                self._handles[SERVING_DEADLINE_EXCEEDED].inc()
                self._retire(s, "error", InferenceTimeout(
                    "deadline expired mid-generation "
                    f"({len(req.handle._ids) - len(req.prompt)} tokens "
                    "streamed)"))
                n += 1
        return n

    def _admit_ready(self, now: float) -> int:
        """Fill free slots from the admission queue in priority order."""
        n = 0
        while None in self._slots:
            req = self._pending.pop()
            if req is None:
                break
            n += 1
            if req.handle.cancelled:
                req.handle._fail(RequestCancelled(
                    "request cancelled while queued"), reason="cancelled")
                continue
            if req.deadline is not None and now >= req.deadline:
                self._handles[SERVING_DEADLINE_EXCEEDED].inc()
                req.handle._fail(InferenceTimeout(
                    "deadline expired in the admission queue"))
                continue
            req.handle.queue_wait_s = now - req.submit_t
            self._queue_wait_hist.observe(req.handle.queue_wait_s)
            self._admit_one(req, self._slots.index(None))
        return n

    def _admit_one(self, req: GenerationRequest, slot: int) -> None:
        """Prefill `req` at batch 1 and join it to the arena at `slot`.
        A prefill failure fails THAT request only: the arena state is
        restored untouched, so in-flight requests are unaffected."""
        net = self.net
        saved_state = dict(net.state)
        saved_acct = self._save_accounting()
        try:
            _fire_chaos(self._prefill_chaos, self._admissions)
            net.rnn_clear_previous_state()
            p0 = prime_prompt(net, req.prompt, self.V,
                              padded=self._prime_padded)
            primed_pos = self._net_pos(net)
        except Exception as e:  # noqa: BLE001 — per-request failure domain
            net.state = saved_state
            self._restore_accounting(saved_acct)
            self._admissions += 1
            self._handles[SERVING_ERRORS].inc()
            req.handle._fail(e)
            return
        self._admissions += 1
        primed_state = dict(net.state)
        tok = draw(p0, req.temperature, req.rng,
                   top_k=req.top_k, top_p=req.top_p)
        now = time.monotonic()
        req.handle.ttft_s = now - req.submit_t
        self._ttft_hist.observe(req.handle.ttft_s)
        req.last_token_t = now
        req.handle._push(tok)
        self._tokens.inc()
        reason = stop_reason(tok, len(req.handle._ids), req.want,
                             req.stop_tokens)
        if reason is None and self._cap is not None \
                and primed_pos >= self._cap:
            reason = "capacity"    # prompt filled the stream: no room
        if reason:
            # one-token request: never enters the arena at all
            net.state = saved_state
            self._restore_accounting(saved_acct)
            req.handle._finish(reason)
            return
        if not self._arena_ready:
            saved_state = self._build_arena(primed_state, saved_state)
            self._arena_ready = True
        net.state = self._merge(saved_state, primed_state, slot)
        self._slots[slot] = req
        self._row_pos[slot] = primed_pos
        req.pending_token = tok
        self._sync_accounting()

    def _dispatch_step(self):
        """ONE jitted decode dispatch advancing every active slot (free
        rows feed token 0; their outputs are discarded, their writes
        drop). Slots at streaming capacity retire first — they cannot
        consume another position."""
        if self._cap is not None:
            for s, req in enumerate(self._slots):
                if req is not None and self._row_pos[s] >= self._cap:
                    self._retire(s, "capacity")
        toks = np.zeros(self.slots, np.int64)
        for s, req in enumerate(self._slots):
            if req is not None:
                toks[s] = req.pending_token
        if not any(r is not None for r in self._slots):
            return None     # everything retired at the capacity guard
        self._sync_accounting()

        def once():
            # chaos INSIDE the retried callable: the fault fires before
            # any state mutates, so a retried dispatch is numerically
            # identical to a fault-free one
            _fire_chaos(self._decode_chaos, self._dispatches)
            return step_tokens(self.net, toks, self.V)

        probs = (retry_call(once, policy=self._decode_retry,
                            op="serving_decode")
                 if self._decode_retry is not None else once())
        self._dispatches += 1
        for s, req in enumerate(self._slots):
            if req is not None:
                self._row_pos[s] += 1
        self._sync_accounting()
        return probs

    def _retire(self, slot: int, reason: str,
                exc: Optional[BaseException] = None) -> None:
        """Free `slot` immediately — host bookkeeping only, no device
        op: the row's stale cache is invisible (its writes drop, its
        output is discarded) until the next admission overwrites it."""
        req = self._slots[slot]
        self._slots[slot] = None
        self._row_pos[slot] = 0
        if exc is not None:
            req.handle._fail(exc, reason)
        else:
            req.handle._finish(reason)

    # ------------------------------------------------------------------
    # arena state plumbing
    # ------------------------------------------------------------------
    def _build_arena(self, primed_state, base_state):
        """First-admission skeleton: every stream key of the primed
        structure broadcast to S zeroed rows (kv_abs rows start -1 =
        empty, matching a fresh rolling cache), per-row kv_pos vector at
        0. Free rows are inert: nothing reads them until a scatter
        overwrites them."""
        S = self.slots
        arena = {}
        for name, s in primed_state.items():
            if not isinstance(s, dict):
                arena[name] = s
                continue
            if "kv_mask" in s:
                raise RuntimeError(
                    "engine prefill must be maskless (packed padded "
                    "priming) — a kv_mask in the primed state means the "
                    "stream was primed with an explicit mask")
            d = dict(base_state.get(name, {}) if isinstance(
                base_state.get(name), dict) else {})
            d.update({k: v for k, v in s.items()
                      if k not in _SCATTER_KEYS})
            for k, v in s.items():
                if k not in _SCATTER_KEYS:
                    continue
                v = jnp.asarray(v)
                if k == "kv_pos":
                    d[k] = jnp.zeros((S,), v.dtype)
                elif k == "kv_abs":
                    d[k] = jnp.full((S,) + v.shape, -1, v.dtype)
                else:                      # batch-leading cache/state
                    d[k] = jnp.zeros((S,) + v.shape[1:], v.dtype)
            arena[name] = d
        return arena

    def _merge(self, arena_state, primed_state, slot: int):
        if self._merge_keys is None:
            self._merge_keys = [
                (n, k) for n in sorted(primed_state)
                if isinstance(primed_state[n], dict)
                for k in sorted(primed_state[n])
                if k in _SCATTER_KEYS]
        arena_leaves = [arena_state[n][k] for n, k in self._merge_keys]
        primed_leaves = [primed_state[n][k] for n, k in self._merge_keys]
        new_leaves = _scatter_rows(arena_leaves, primed_leaves,
                                   np.int32(slot))
        out = {n: (dict(v) if isinstance(v, dict) else v)
               for n, v in arena_state.items()}
        for (n, k), leaf in zip(self._merge_keys, new_leaves):
            out[n][k] = leaf
        return out

    @staticmethod
    def _net_pos(net) -> int:
        pm = getattr(net, "_stream_pos_map", None)
        if pm:
            return int(max(pm.values()))
        return int(getattr(net, "_stream_pos", 0) or 0)

    def _save_accounting(self):
        net = self.net
        pm = getattr(net, "_stream_pos_map", None)
        return (getattr(net, "_stream_pos", 0),
                getattr(net, "_stream_pos_rows", None),
                dict(pm) if pm is not None else None)

    def _restore_accounting(self, saved) -> None:
        pos, rows, pmap = saved
        net = self.net
        net._stream_pos = pos
        net._stream_pos_rows = rows
        if pmap is not None:
            net._stream_pos_map = pmap

    def _sync_accounting(self) -> None:
        """Engine-owned host position mirrors: active rows carry their
        true positions, free rows pin to 0 so an idle slot can never
        trip the stream-budget guard while its device-side counter
        coasts (those writes drop harmlessly)."""
        net = self.net
        mask = np.array([r is not None for r in self._slots])
        rows = np.where(mask, self._row_pos, 0).astype(np.int64)
        pos = int(rows.max()) if mask.any() else 0
        net._stream_pos = pos
        net._stream_pos_rows = rows
        if self._graph_vertices:
            net._stream_pos_map = {n: pos for n in self._graph_vertices}

    # ------------------------------------------------------------------
    # warmup
    # ------------------------------------------------------------------
    def warmup(self, max_prompt_len: Optional[int] = None,
               steps: int = 2) -> "GenerationEngine":
        """Compile every canonical serving shape before traffic: one
        synthetic greedy request per power-of-two prime bucket up to
        bucket(max_prompt_len) (default: the net's streaming capacity),
        driven to completion. Warms the per-bucket prefill, the
        scatter-join, and the [S, V, 1] decode dispatch, so staggered
        admissions of ANY prompt length <= max_prompt_len afterwards
        cause zero retraces (the PR 3 acceptance bar)."""
        if self._worker is not None and self._worker.is_alive():
            raise RuntimeError("warm up before start(): warmup drives "
                               "step() manually")
        cap = self._cap
        top = max_prompt_len
        if top is None:
            top = (cap - 1) if cap is not None else 64
        top = max(1, int(top))
        lens, n = [], 1
        while n <= top:
            lens.append(n)
            n *= 2
        if top not in lens:
            lens.append(top)      # a non-pow2 top primes at bucket(top)
        if cap is not None:
            lens = sorted({min(v, cap - 1) for v in lens})
        tok = 1 if self.V > 1 else 0
        for v in lens:
            # drain per bucket: warmup must not depend on queue_limit
            # headroom (block policy would deadlock, fail_fast would
            # reject, with more buckets than queue slots)
            h = self.submit([tok] * v, steps=steps, top_k=1,
                            rng=np.random.default_rng(0))
            self.run_until_idle()
            h.result(timeout=0)
        return self

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> "GenerationEngine":
        """Run the dispatch loop on a background thread (the serving
        deployment shape; manual ``step()`` still works for warmup)."""
        if self._stop.is_set():
            raise EngineShutdown("GenerationEngine shut down")
        if self._worker is not None and self._worker.is_alive():
            return self
        self._worker = threading.Thread(target=self._engine_loop,
                                        daemon=True)
        self._worker.start()
        return self

    def _engine_loop(self):
        try:
            while not self._stop.is_set():
                if not self.step():
                    self._pending.wait(0.02)
        except Exception as e:  # noqa: BLE001 — strand no waiters
            log.exception("GenerationEngine loop died")
            self._break(e)

    def _break(self, exc: BaseException) -> None:
        """Terminal failure: fail every in-flight and queued request
        with the original error and refuse new work. A broken arena is
        not resumable (the failed dispatch may or may not have consumed
        positions)."""
        with self._lock:
            self._broken = exc
            # stop the loop too: with the queue closed, wait() returns
            # immediately — a broken engine must park, not busy-spin
            self._stop.set()
            for s, req in enumerate(self._slots):
                if req is not None:
                    self._retire(s, "error", exc)
            for req in self._pending.close():
                req.handle._fail(exc)

    def shutdown(self) -> None:
        """Stop the loop and fail everything still in flight — nobody
        blocks forever on a dead server (the ParallelInference
        contract). Idempotent."""
        self._stop.set()
        for req in self._pending.close():
            req.handle._fail(EngineShutdown("GenerationEngine shut down"))
        if self._worker is not None and self._worker.is_alive():
            self._worker.join(timeout=5.0)
        with self._lock:
            for s, req in enumerate(self._slots):
                if req is not None:
                    self._retire(s, "error", EngineShutdown(
                        "GenerationEngine shut down"))
