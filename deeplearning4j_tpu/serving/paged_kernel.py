"""Pallas TPU paged-attention decode kernel (+ the XLA reference).

The direct-paged-decode counterpart of ``nn/layers/pallas_attention.py``:
where that module fuses the *training/prefill* attention schedule, this
one fuses the *serving decode* read path over the block-paged KV pool
(``serving/paging.py``). The engine's steady-state step used to wrap the
canonical decode in a full-arena ``gather_pages → dispatch →
scatter_pages`` round trip — every generated token moved 2× the entire
token-budget pool per attention leaf through HBM regardless of how much
context was actually live. Here the page table IS the access path
(cuDNN's fused-primitive lesson, PAPERS.md: fold the memory movement
into the consuming op):

- grid ``(slot, kv-head, page-block)`` with the per-slot page table and
  per-row lengths prefetched as SCALAR refs
  (``pltpu.PrefetchScalarGridSpec``): the K/V block specs index the pool
  *through the table* (``table[s, b]``), so each grid step DMAs exactly
  one mapped page into VMEM — the pool is never materialized densely.
- online-softmax accumulators (m, l, acc) live in VMEM scratch across
  the page-block axis: one HBM read per live page, one HBM write per
  output block (the flash-attention schedule applied to paged decode).
- blocks at or past a row's length are skipped (``pl.when``) — dead
  table entries point at the reserved null page 0, so even their
  prefetch touches only the one always-resident page. Cost is
  O(active context), not O(token budget).
- the query axis is ``reps × W`` rows per kv head (GQA grouping ×
  query width), with W static: W = 1 is the plain decode step and
  W = 1 + γ is the widened speculative verify dispatch ``[S, V, 1+γ]``
  — the SAME kernel serves both, so brownout gamma changes and
  speculation toggles never switch kernels. In-block causality masks
  query w to keys ≤ length - W + w.
- ``interpret=True`` runs the kernel on CPU for the exactness suite
  (tests/test_serving_paged_kernel.py), mirroring pallas_attention's
  testing contract.

The XLA fallback for the same seam lives in
``SelfAttentionLayer._stream_attend_paged`` (nn/conf/layers.py): it
folds the ``pool[table]`` gather into the attention dispatch and shares
``_grouped_attend`` with the dense arena bit-for-bit.
``paged_ref_attention`` here is the standalone dense-gather reference
the kernel tests compare against.

Appends are NOT this kernel's job: the new token's K/V lands in the
pool via a one-token ``[S, Hkv, W, D]`` scatter at ``(page, offset)``
computed from each row's position (the layer does it before attending),
replacing the donated full-arena ``scatter_pages`` with an
O(one-token) write. Prefix-shared read-only blocks stay safe by block
alignment: a slot only ever appends at positions ≥ its own fresh
blocks (copy-on-extend falls out of the allocation math, the same
argument as the legacy scatter's).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30   # finite: exp(NEG_INF - NEG_INF) inside a fully-masked
#                   row must not produce NaN (explicit re-zeroing below)

__all__ = ["paged_attention", "paged_attention_supported",
           "paged_ref_attention"]


def _decode_kernel(tbl_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_scr, m_scr, l_scr, *, ps, qw, nb, scale):
    """One (slot, kv-head, page-block) grid step: score the row's
    grouped queries against ONE mapped page, fold into the online
    softmax, emit at the last block."""
    s, b = pl.program_id(0), pl.program_id(2)

    @pl.when(b == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    length = len_ref[s]

    @pl.when(b * ps < length)
    def _compute():
        qb = q_ref[0, 0]                              # [reps*W, D]
        sblk = jax.lax.dot_general(
            qb, k_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [reps*W, ps]
        rw = qb.shape[0]
        kpos = b * ps + jax.lax.broadcasted_iota(jnp.int32, (rw, ps), 1)
        # query row r = rep * W + w sits at absolute position
        # length - W + w; causality within the appended chunk means
        # query w sees keys ≤ its own position (kpos < length follows:
        # the last query position IS length - 1)
        w = jax.lax.broadcasted_iota(jnp.int32, (rw, ps), 0) % qw
        valid = kpos <= length - qw + w
        sblk = jnp.where(valid, sblk, NEG_INF)
        m_prev = m_scr[:][:, :1]
        l_prev = l_scr[:][:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(sblk, axis=1, keepdims=True))
        # explicit zeroing: a row whose whole block is masked would see
        # exp(NEG_INF - NEG_INF) = 1 — keep those probabilities at 0
        p = jnp.exp(sblk - m_new) * valid.astype(jnp.float32)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [reps*W, D]
        acc_scr[:] = acc_scr[:] * corr + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(b == nb - 1)
    def _finish():
        o_ref[0, 0] = (acc_scr[:] / jnp.maximum(l_scr[:][:, :1], 1e-30)
                       ).astype(o_ref.dtype)


def _decode_kernel_quant(tbl_ref, len_ref, ks_ref, vs_ref, q_ref, k_ref,
                         v_ref, o_ref, acc_scr, m_scr, l_scr, *, ps, qw,
                         nb, scale):
    """The int8-pool variant of _decode_kernel: K/V blocks arrive in
    VMEM as int8 (the DMA moves half the bytes — the real win, not
    just the model's), with the per-(page, head) amax scales riding
    the scalar prefetch (ks/vs: [P, Hkv] float32 in SMEM, indexed by
    the very page id the table prefetch routed this block through).
    Dequantization folds into the existing fp32 math for free: the
    K scale multiplies the score block alongside 1/sqrt(d), and the
    V scale multiplies the block's pv contribution before it enters
    the accumulator — per-page-constant scales commute with both
    dots, so this IS dequant(int8) attention, not an approximation
    of it."""
    s, h, b = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(b == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    length = len_ref[s]

    @pl.when(b * ps < length)
    def _compute():
        page = tbl_ref[s, b]
        sk = ks_ref[page, h]
        sv = vs_ref[page, h]
        qb = q_ref[0, 0].astype(jnp.float32)          # [reps*W, D]
        # int8 operands are EXPLICITLY widened before any arithmetic
        # (the int8-promotion-in-dispatch lint contract): the dot runs
        # in fp32, the page's scale rides the existing score scaling
        sblk = jax.lax.dot_general(
            qb, k_ref[0, 0].astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * (scale * sk)
        rw = qb.shape[0]
        kpos = b * ps + jax.lax.broadcasted_iota(jnp.int32, (rw, ps), 1)
        w = jax.lax.broadcasted_iota(jnp.int32, (rw, ps), 0) % qw
        valid = kpos <= length - qw + w
        sblk = jnp.where(valid, sblk, NEG_INF)
        m_prev = m_scr[:][:, :1]
        l_prev = l_scr[:][:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(sblk, axis=1, keepdims=True))
        p = jnp.exp(sblk - m_new) * valid.astype(jnp.float32)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v_ref[0, 0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * sv      # [reps*W, D]
        acc_scr[:] = acc_scr[:] * corr + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(b == nb - 1)
    def _finish():
        o_ref[0, 0] = (acc_scr[:] / jnp.maximum(l_scr[:][:, :1], 1e-30)
                       ).astype(o_ref.dtype)


def paged_attention(q, k_pool, v_pool, table, lengths, *, query_width: int,
                    interpret: bool = False, k_scales=None,
                    v_scales=None):
    """Paged-attention decode over the block-paged KV pool.

    - ``q``: ``[S, Hkv, reps*W, D]`` — queries grouped by kv head (GQA:
      ``reps = n_heads // n_kv_heads`` query heads share each kv head),
      W = ``query_width`` appended positions per row, rope already
      applied. Row ``rep * W + w`` sits at absolute position
      ``lengths[s] - W + w``.
    - ``k_pool`` / ``v_pool``: ``[P, Hkv, page_size, D]`` — the pools,
      already holding this step's appended tokens (append-then-attend,
      the dense ``_stream_attend`` order).
    - ``table``: ``[S, n_max]`` int32 page ids (0 = reserved null page —
      dead blocks all route there).
    - ``lengths``: ``[S]`` int32 valid KV positions per row INCLUDING
      the appended chunk (engine: ``kv_pos + W``).
    - ``k_scales`` / ``v_scales``: ``[P, Hkv]`` float32 — the int8
      pool's per-(page, head) amax-scale sidecars (serving/quant.py).
      Passing them selects the quantized kernel: pools must be int8,
      blocks DMA at half the bytes, and dequantization happens in
      VMEM with the scales riding the scalar-prefetch refs.

    Returns ``[S, Hkv, reps*W, D]`` in ``q.dtype`` (fp32 accumulation).
    Free/garbage rows produce finite garbage the engine discards — the
    same contract as the dense arena's idle slots.
    """
    S, hkv, rw, d = q.shape
    _, _, ps, _ = k_pool.shape
    nb = table.shape[1]
    qw = int(query_width)
    if qw < 1 or rw % qw:
        raise ValueError(f"query rows {rw} not divisible by "
                         f"query_width {qw}")
    quant = k_scales is not None or v_scales is not None
    if quant and (k_scales is None or v_scales is None):
        raise ValueError("k_scales and v_scales travel together")
    if quant and k_pool.dtype != jnp.int8:
        raise ValueError(
            f"scale sidecars describe an int8 pool, got "
            f"{k_pool.dtype}")
    scale = float(1.0 / np.sqrt(d))
    if quant:
        kernel = functools.partial(_decode_kernel_quant, ps=ps, qw=qw,
                                   nb=nb, scale=scale)
        n_pref = 4
        pref = (jnp.asarray(table, jnp.int32),
                jnp.asarray(lengths, jnp.int32),
                jnp.asarray(k_scales, jnp.float32),
                jnp.asarray(v_scales, jnp.float32))
    else:
        kernel = functools.partial(_decode_kernel, ps=ps, qw=qw, nb=nb,
                                   scale=scale)
        n_pref = 2
        pref = (jnp.asarray(table, jnp.int32),
                jnp.asarray(lengths, jnp.int32))

    def _q_map(s, h, b, tbl, *_):
        return (s, h, 0, 0)

    def _pool_map(s, h, b, tbl, *_):
        # the page table IS the index map: block b of row s loads
        # pool page table[s, b] — the paged read path, fused
        return (tbl[s, b], h, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_pref,
        grid=(S, hkv, nb),
        in_specs=[
            pl.BlockSpec((1, 1, rw, d), _q_map),
            pl.BlockSpec((1, 1, ps, d), _pool_map),
            pl.BlockSpec((1, 1, ps, d), _pool_map),
        ],
        out_specs=pl.BlockSpec((1, 1, rw, d), _q_map),
        scratch_shapes=[pltpu.VMEM((rw, d), jnp.float32),
                        pltpu.VMEM((rw, 128), jnp.float32),
                        pltpu.VMEM((rw, 128), jnp.float32)],
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, hkv, rw, d), q.dtype),
        interpret=interpret,
    )(*pref, q, k_pool, v_pool)


def paged_attention_supported(pool_shape: Tuple[int, ...],
                              query_rows: int, *,
                              kv_dtype: str = "bf16") -> bool:
    """Shape gate for the REAL-CHIP kernel path (mirrors
    flash_attention_supported): head dim lane-tileable, page rows
    sublane-tileable. An int8 pool tightens both (the int8 minimum
    tile is (32, 128) vs fp32's (8, 128) — a page block must still be
    a whole tile multiple). Interpret mode (CPU tests) has no such
    limits — this gate only decides the ``decode_impl="auto"``
    resolution on a TPU backend."""
    if len(pool_shape) != 4:
        return False
    _, _, ps, d = pool_shape
    if kv_dtype == "int8":
        return d in (128, 256) and ps % 32 == 0 and query_rows >= 1
    return d in (64, 128, 256) and ps % 8 == 0 and query_rows >= 1


def paged_ref_attention(q, k_pool, v_pool, table, lengths, *,
                        query_width: int):
    """Dense-gather XLA reference for the kernel tests: materialize
    ``pool[table]``, mask keys past each query's position, softmax in
    fp32 — the same math ``SelfAttentionLayer._grouped_attend`` runs on
    the gathered view, as a standalone function."""
    S, hkv, rw, d = q.shape
    _, _, ps, _ = k_pool.shape
    nb = table.shape[1]
    qw = int(query_width)
    kd = jnp.moveaxis(k_pool[table], 2, 1).reshape(S, hkv, nb * ps, d)
    vd = jnp.moveaxis(v_pool[table], 2, 1).reshape(S, hkv, nb * ps, d)
    kpos = jnp.arange(nb * ps)
    qpos = (jnp.asarray(lengths)[:, None] - qw
            + jnp.arange(rw)[None, :] % qw)              # [S, rw]
    valid = kpos[None, None, :] <= qpos[..., None]       # [S, rw, L]
    s = jnp.einsum("nhrd,nhld->nhrl", q.astype(jnp.float32),
                   kd.astype(jnp.float32)) / np.sqrt(d)
    s = jnp.where(valid[:, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("nhrl,nhld->nhrd", p, vd.astype(jnp.float32))
    return o.astype(q.dtype)
