"""int8 KV page-pool quantization (``PagedKVConfig(kv_dtype="int8")``).

Paged decode is memory-bandwidth-bound: after PR 10 removed the
gather/scatter round trip, what every step still moves is the pool
bytes themselves. Storing the pool in symmetric int8 halves that
traffic AND doubles the token budget a fixed byte budget admits — the
arithmetic-intensity lever of the reference framework's compression
subsystem (``Nd4j.getCompressor()``) applied to serving KV state.

Scheme — symmetric per-(page, kv-head) power-of-two scales:

- each kv leaf's pool becomes ``[P, Hkv, page_size, D]`` **int8** with
  a ``[P, Hkv]`` float32 amax-scale sidecar (page 0 stays the null
  page; its scale stays whatever collided writes left — nothing valid
  ever reads through it);
- a page's scale is established from its BASE token (the token at
  ``q_pos % page_size == 0``): ``sigma = pow2ceil(amax / 127)``. Every
  later token of the page quantizes with the base's sigma —
  ``q = clip(round(x / sigma), -127, 127)`` — so a page is priced
  once and never rescaled (quantize-once: re-quantizing on every
  append would make pool bytes depend on visit order);
- power-of-two sigma makes ``dequant(q) = q * sigma`` EXACT in float
  (a mantissa shift), and exactly representable even in bf16
  (|q| <= 127 needs 7 mantissa bits) — so reading a page twice, or
  re-priming the same committed tokens after a rebuild / migration,
  reproduces bit-identical dequantized values. That is what keeps the
  prefix-cache hit==miss and ledger-rebuild pins bitwise under int8.

Accuracy is an explicitly pinned ENVELOPE (greedy-divergence step +
logit MAE on the test models — tests/test_serving_quant.py), never
bit-parity with bf16: the round-trip error per element is bounded by
sigma / 2 <= amax * 2 / 127 (pow2ceil at most doubles amax / 127).

The write path lives in ``SelfAttentionLayer._stream_attend_paged``
(quantize_chunk below is its per-leaf worker); the read paths dequant
in ``_stream_attend_paged``'s folded gather (XLA) and in
``serving/paged_kernel.py``'s VMEM inner loop (Pallas, scales riding
the scalar-prefetch refs).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax.numpy as jnp

__all__ = ["KV_DTYPES", "dequantize", "kv_page_bytes", "pool_leaves",
           "pow2ceil", "quantize", "quantize_chunk"]

#: the PagedKVConfig.kv_dtype vocabulary: "bf16" = the unquantized
#: pool in the net's native leaf dtype (the name of the default, not a
#: cast); "int8" = this module; "auto" = the measured
#: paged_decode_quant crossover entry decides (tuning/plan.py)
KV_DTYPES = ("bf16", "int8", "auto")


def pow2ceil(x):
    """Smallest power of two >= x, elementwise (x >= 0; 0 -> 0).

    frexp writes x = m * 2**e with m in [0.5, 1): an exact power of
    two has m == 0.5 (its own value), anything else rounds up to 2**e.
    Built from frexp/ldexp rather than log2/exp2 so the result is
    exact for every representable input — the scale must be a true
    power of two for dequantization to be a mantissa shift."""
    x = jnp.asarray(x, jnp.float32)
    m, e = jnp.frexp(x)
    out = jnp.ldexp(jnp.ones_like(x), jnp.where(m == 0.5, e - 1, e))
    return jnp.where(x > 0, out, 0.0)


def quantize(x, sigma):
    """Symmetric int8 quantization of ``x`` under (broadcastable)
    scales ``sigma``: clip(round(x / sigma), -127, 127). sigma == 0
    (an all-zero page base) quantizes to 0."""
    sigma = jnp.asarray(sigma, jnp.float32)
    safe = jnp.where(sigma > 0, sigma, 1.0)
    q = jnp.round(jnp.asarray(x, jnp.float32) / safe)
    q = jnp.where(sigma > 0, q, 0.0)
    return jnp.clip(q, -127, 127).astype(jnp.int8)


def dequantize(q, sigma, dtype=jnp.float32):
    """q * sigma — exact for power-of-two sigma (and exactly
    representable in bf16: |q| <= 127 fits 7 mantissa bits)."""
    out = jnp.asarray(q, jnp.float32) * jnp.asarray(sigma, jnp.float32)
    return out.astype(dtype)


def quantize_chunk(xt, scales, page, q_pos, pos, writable, *, page_size,
                   chunk0):
    """Quantize one appended chunk of a kv leaf and ratchet the scale
    sidecar — the per-leaf worker of the paged append
    (``_stream_attend_paged``).

    - ``xt``: [N, T, Hkv, D] — the chunk's k or v, rope applied,
      already transposed to the pool's write layout;
    - ``scales``: [P, Hkv] float32 sidecar (pre-chunk);
    - ``page``: [N, T] int32 target page per token (already masked to
      the null page 0 for non-writable positions);
    - ``q_pos``: [N, T] absolute position per token (pads: pos - 1);
    - ``pos``: [N] each row's pre-chunk stream position;
    - ``writable``: [N, T] bool — real, in-capacity tokens;
    - ``chunk0``: chunk index of the first REAL token (pad_left for a
      left-padded prime chunk, 0 otherwise; may be traced).

    Returns ``(xq [N,T,Hkv,D] int8, new_scales [P,Hkv])``.

    A token's scale is its page BASE's sigma. The base is either in
    this very chunk (prefill / wide speculative verify: look it up by
    chunk index — the base token of position b sits at chunk index
    chunk0 + (b - pos)) or already committed (plain decode appends mid
    page: read the sidecar). Base tokens OVERWRITE their page's
    sidecar entry, so a speculative rewind that re-appends a different
    base re-prices the page from the token that actually committed —
    pool bytes stay a pure function of the committed token stream."""
    n, t, _, _ = xt.shape
    ps = page_size
    amax = jnp.max(jnp.abs(xt.astype(jnp.float32)), axis=-1)  # [N,T,Hkv]
    s_tok = pow2ceil(amax / 127.0)
    base_pos = (q_pos // ps) * ps
    in_chunk = base_pos >= pos[:, None]                       # [N, T]
    idx = jnp.clip(base_pos - pos[:, None] + chunk0, 0, t - 1)
    idx3 = jnp.broadcast_to(idx[:, :, None], s_tok.shape)
    s_base = jnp.take_along_axis(s_tok, idx3.astype(jnp.int32), axis=1)
    sigma = jnp.where(in_chunk[:, :, None], s_base, scales[page])
    xq = quantize(xt, sigma[:, :, :, None])
    is_base = (q_pos % ps == 0) & writable
    # non-base (and pad) rows collide at the null page 0 — garbage
    # there is never dequantized into anything a validity mask shows
    upd = jnp.where(is_base, page, 0)
    return xq, scales.at[upd].set(s_tok)


def kv_page_bytes(leaf_dims: Sequence[Tuple[int, int]], page_size: int,
                  kv_dtype: str, native_dtype: str) -> int:
    """Bytes ONE pool page costs across every kv leaf (k and v per
    attention layer — ``leaf_dims`` holds one (Hkv, D) per LAYER),
    including the int8 scale-sidecar rows. The unit of
    ``PagedKVConfig(total_bytes=...)`` capacity resolution: the same
    byte budget admits ~2x the pages under int8."""
    if kv_dtype == "int8":
        item, scale = 1, 4
    else:
        item = 2 if native_dtype in ("bfloat16", "bf16", "float16") else 4
        scale = 0
    total = 0
    for hkv, d in leaf_dims:
        total += 2 * (hkv * int(page_size) * d * item + hkv * scale)
    return total


def pool_leaves(total_pages: int, page_size: int,
                leaf_dims: Sequence[Tuple[int, int]]) -> Tuple[List, List]:
    """Freshly zeroed int8 pools + scale sidecars, two leaves (k, v)
    per (Hkv, D) layer entry, in layer order — the engine's eager
    store build (int8 pools must exist BEFORE the first prime: the
    prefill itself writes through the paged path)."""
    pools, scales = [], []
    for hkv, d in leaf_dims:
        for _ in ("kv_k", "kv_v"):
            pools.append(jnp.zeros((total_pages, hkv, int(page_size), d),
                                   jnp.int8))
            scales.append(jnp.zeros((total_pages, hkv), jnp.float32))
    return pools, scales
