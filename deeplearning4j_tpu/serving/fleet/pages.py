"""Content-addressed KV page store on the fleet root — the channel
that ships primed prefix pages between OS processes.

The journal moves tokens; this moves KV. One store entry is one FULL
prompt block's K/V for every paged layer, named by its prefix-chain
digest (``prefix_cache.block_digest`` chained from ``ROOT_DIGEST``):
the digest pins the ENTIRE token prefix, so on a homogeneous fleet —
same net, same page size, same kv_dtype — the bytes a prefill replica
publishes under a digest are bit-identical to what the importing decode
replica would have primed itself. That identity is the whole exactness
argument for disaggregation: importing a page is not an approximation
of local prefill, it IS local prefill's output, moved.

On-disk contract (mirrors the mailbox):

- ``<root>/pages/pg_<kvdtype>_<digest>.bin`` — the raw page bytes,
  every leaf's ``np.ndarray.tobytes()`` concatenated in manifest
  order. int8 entries interleave the per-(page, kv-head) amax-scale
  sidecar rows (``role: "scale"``) after each quantized leaf.
- ``<root>/pages/pg_<kvdtype>_<digest>.json`` — the manifest:
  ``{version, digest, parent, tokens, kv_dtype, page_size, checksum,
  nbytes, created, leaves: [{name, leaf, role, shape, dtype, offset,
  nbytes}]}``. ``checksum`` is the sha256 hex of the complete bin.

Writers are atomic-rename only (tmp + ``os.replace``), and the
manifest lands AFTER its bin — a visible manifest implies a fully
renamed bin, so a reader never races a half-written entry. Every load
re-verifies checksum, sizes, and shapes anyway (a crashed writer, bit
rot, or chaos injection can still tear files): ANY mismatch moves both
files into ``pages/quarantine/`` with a ``.why`` breadcrumb — exactly
the mailbox contract — and the load returns None, which callers treat
as a store miss (fresh prefill; bit-exact by construction, just
slower). A torn file can delay disaggregation, never corrupt a stream.

The kv_dtype lives in the FILENAME, not the digest: locality
advertisements stay dtype-agnostic, while a mixed fleet can never
import bytes quantized for a different pool. Content addressing also
dedupes publishes fleet-wide — ``has(digest)`` before write means N
replicas priming the same system prompt ship it once.

Entries are plain copies (imports copy into the local pool; nothing
maps store files), so ``sweep`` — TTL by mtime plus an LRU-ish
max-entries cap — can delete any entry at any time without a refcount
protocol. A concurrent reader that loses the race gets a miss.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.resilience.durable import (atomic_write_bytes,
                                                   atomic_write_json)
from deeplearning4j_tpu.serving.fleet.transport import fleet_paths

__all__ = ["PageStore", "STORE_VERSION"]

STORE_VERSION = 1

_PAGE_PREFIX = "pg_"
_QUARANTINE = "quarantine"


def _resolve_dtype(name: str) -> np.dtype:
    """Rebuild a dtype from its manifest name. Non-numpy-native names
    (bfloat16) resolve through ml_dtypes — the same registry jax uses,
    so ``np.frombuffer`` round-trips bf16 leaves bit-exactly."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


class PageStore:
    """The fleet-shared KV page tier rooted at ``<root>/pages/``."""

    def __init__(self, root: str):
        self.path = fleet_paths(root)["pages"]
        self.quarantine_path = os.path.join(self.path, _QUARANTINE)
        os.makedirs(self.quarantine_path, exist_ok=True)
        self._lock = threading.Lock()
        # observability (scraped into agent status + /metrics)
        self.published = 0          # entries this process wrote
        self.publish_bytes = 0      # bin bytes this process wrote
        self.dedup_skips = 0        # publishes skipped: already present
        self.corrupt = 0            # entries quarantined on load

    # -- naming --------------------------------------------------------
    def _stem(self, kv_dtype: str, digest: str) -> str:
        return f"{_PAGE_PREFIX}{kv_dtype}_{digest}"

    def _bin_path(self, kv_dtype: str, digest: str) -> str:
        return os.path.join(self.path,
                            self._stem(kv_dtype, digest) + ".bin")

    def _manifest_path(self, kv_dtype: str, digest: str) -> str:
        return os.path.join(self.path,
                            self._stem(kv_dtype, digest) + ".json")

    # -- write side (prefill replicas / publishing decoders) ----------
    def has(self, digest: str, kv_dtype: str) -> bool:
        return os.path.exists(self._manifest_path(kv_dtype, digest))

    def publish(self, digest: str, *, parent: str,
                tokens: Sequence[int], kv_dtype: str, page_size: int,
                arrays: Sequence[Tuple[str, str, str, np.ndarray]]
                ) -> bool:
        """Write one block entry: `arrays` is ``[(layer name, leaf key,
        role "kv"|"scale", ndarray), ...]`` in a deterministic order.
        Returns False (and writes nothing) if the entry already exists
        — content addressing makes re-publish a no-op, so concurrent
        publishers across the fleet are safe without coordination (the
        losing ``os.replace`` just rewrites identical bytes)."""
        if self.has(digest, kv_dtype):
            self.dedup_skips += 1
            return False
        leaves: List[dict] = []
        chunks: List[bytes] = []
        off = 0
        for name, leaf, role, arr in arrays:
            arr = np.ascontiguousarray(arr)
            raw = arr.tobytes()
            leaves.append({
                "name": name, "leaf": leaf, "role": role,
                "shape": list(arr.shape), "dtype": arr.dtype.name,
                "offset": off, "nbytes": len(raw),
            })
            chunks.append(raw)
            off += len(raw)
        blob = b"".join(chunks)
        manifest = {
            "version": STORE_VERSION,
            "digest": digest,
            "parent": parent,
            "tokens": [int(t) for t in tokens],
            "kv_dtype": kv_dtype,
            "page_size": int(page_size),
            "checksum": hashlib.sha256(blob).hexdigest(),
            "nbytes": len(blob),
            "created": time.time(),
            "leaves": leaves,
        }
        with self._lock:
            # bin first, manifest second: a visible manifest implies a
            # complete bin. A crash between the two leaves an orphan
            # bin that sweep() reaps (no manifest -> never loaded).
            atomic_write_bytes(self._bin_path(kv_dtype, digest), blob)
            atomic_write_json(self._manifest_path(kv_dtype, digest),
                              manifest)
        self.published += 1
        self.publish_bytes += len(blob)
        return True

    # -- read side (importing decode replicas) -------------------------
    def load(self, digest: str, kv_dtype: str) -> Optional[dict]:
        """Verified load: returns ``{"digest", "parent", "tokens",
        "page_size", "nbytes", "arrays": [(name, leaf, role, ndarray),
        ...]}`` or None on miss OR on any integrity failure (failure
        quarantines the entry — it will never be offered again)."""
        mpath = self._manifest_path(kv_dtype, digest)
        try:
            with open(mpath, "r", encoding="utf-8") as f:
                manifest = json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, ValueError) as e:
            self._quarantine(kv_dtype, digest,
                             f"undecodable manifest: {e!r}")
            return None
        try:
            with open(self._bin_path(kv_dtype, digest), "rb") as f:
                blob = f.read()
        except OSError as e:
            self._quarantine(kv_dtype, digest,
                             f"unreadable page bin: {e!r}")
            return None
        why = self._verify(manifest, blob, digest, kv_dtype)
        if why is not None:
            self._quarantine(kv_dtype, digest, why)
            return None
        arrays: List[Tuple[str, str, str, np.ndarray]] = []
        for lf in manifest["leaves"]:
            raw = blob[lf["offset"]:lf["offset"] + lf["nbytes"]]
            arr = np.frombuffer(raw, dtype=_resolve_dtype(lf["dtype"]))
            arrays.append((lf["name"], lf["leaf"], lf["role"],
                           arr.reshape(lf["shape"])))
        return {
            "digest": digest,
            "parent": manifest["parent"],
            "tokens": list(manifest["tokens"]),
            "page_size": int(manifest["page_size"]),
            "nbytes": int(manifest["nbytes"]),
            "arrays": arrays,
        }

    def _verify(self, manifest: dict, blob: bytes, digest: str,
                kv_dtype: str) -> Optional[str]:
        """None if the entry is intact, else the quarantine reason."""
        try:
            if int(manifest["version"]) != STORE_VERSION:
                return (f"version {manifest['version']} != "
                        f"{STORE_VERSION}")
            if manifest["digest"] != digest:
                return "manifest digest != filename digest"
            if manifest["kv_dtype"] != kv_dtype:
                return "manifest kv_dtype != filename kv_dtype"
            if len(blob) != int(manifest["nbytes"]):
                return (f"bin is {len(blob)} bytes, manifest says "
                        f"{manifest['nbytes']} (torn write?)")
            if hashlib.sha256(blob).hexdigest() != manifest["checksum"]:
                return "checksum mismatch"
            off = 0
            for lf in manifest["leaves"]:
                if int(lf["offset"]) != off:
                    return f"leaf {lf['name']}/{lf['leaf']} offset gap"
                dt = _resolve_dtype(lf["dtype"])
                want = int(np.prod(lf["shape"])) * dt.itemsize
                if int(lf["nbytes"]) != want:
                    return (f"leaf {lf['name']}/{lf['leaf']} shape "
                            f"{lf['shape']} x {lf['dtype']} needs "
                            f"{want} bytes, manifest says "
                            f"{lf['nbytes']}")
                off += int(lf["nbytes"])
            if off != len(blob):
                return "leaves do not tile the bin"
        except (KeyError, TypeError, ValueError) as e:
            return f"malformed manifest: {e!r}"
        return None

    def _quarantine(self, kv_dtype: str, digest: str, why: str) -> None:
        self.corrupt += 1
        stem = self._stem(kv_dtype, digest)
        for ext in (".json", ".bin"):
            try:
                os.replace(os.path.join(self.path, stem + ext),
                           os.path.join(self.quarantine_path,
                                        stem + ext))
            except OSError:
                try:
                    os.unlink(os.path.join(self.path, stem + ext))
                except OSError:
                    pass
        # a breadcrumb beside the quarantined files, for post-mortems
        try:
            atomic_write_json(
                os.path.join(self.quarantine_path, stem + ".why"),
                {"name": stem, "why": why})
        except OSError:
            pass

    def quarantined(self) -> List[str]:
        """Stems of quarantined entries (sorted)."""
        try:
            return sorted(n[:-len(".why")]
                          for n in os.listdir(self.quarantine_path)
                          if n.startswith(_PAGE_PREFIX)
                          and n.endswith(".why"))
        except OSError:
            return []

    # -- enumeration / retention ---------------------------------------
    def digests(self, kv_dtype: Optional[str] = None) -> List[str]:
        """Digests with a visible manifest (any dtype, or one)."""
        out = []
        try:
            names = os.listdir(self.path)
        except OSError:
            return out
        for n in names:
            if not (n.startswith(_PAGE_PREFIX) and n.endswith(".json")):
                continue
            stem = n[len(_PAGE_PREFIX):-len(".json")]
            dt, _, dig = stem.partition("_")
            if dig and (kv_dtype is None or dt == kv_dtype):
                out.append(dig)
        return out

    def entries(self) -> int:
        return sum(1 for n in os.listdir(self.path)
                   if n.startswith(_PAGE_PREFIX)
                   and n.endswith(".json"))

    def sweep(self, ttl_s: Optional[float] = None,
              max_entries: Optional[int] = None) -> int:
        """Retention pass: drop entries older than `ttl_s` (manifest
        mtime), then oldest-first down to `max_entries`; orphan bins
        (no manifest — a writer died between renames) always go.
        Returns entries removed. Safe against concurrent readers —
        worst case they take a miss and prefill fresh."""
        now = time.time()
        removed = 0
        try:
            names = os.listdir(self.path)
        except OSError:
            return 0
        manifests: List[Tuple[float, str]] = []   # (mtime, stem)
        stems = set()
        for n in names:
            if n.startswith(_PAGE_PREFIX) and n.endswith(".json"):
                stem = n[:-len(".json")]
                stems.add(stem)
                try:
                    manifests.append(
                        (os.path.getmtime(os.path.join(self.path, n)),
                         stem))
                except OSError:
                    pass
        for n in names:
            if (n.startswith(_PAGE_PREFIX) and n.endswith(".bin")
                    and n[:-len(".bin")] not in stems):
                try:
                    os.unlink(os.path.join(self.path, n))
                except OSError:
                    pass
        manifests.sort()
        drop: List[str] = []
        if ttl_s is not None:
            drop.extend(s for mt, s in manifests if now - mt > ttl_s)
        if max_entries is not None and len(manifests) > max_entries:
            keep_from = len(manifests) - max_entries
            drop.extend(s for _, s in manifests[:keep_from])
        for stem in dict.fromkeys(drop):     # dedupe, keep order
            # manifest FIRST so a concurrent reader can't see a
            # manifest whose bin we already deleted
            for ext in (".json", ".bin"):
                try:
                    os.unlink(os.path.join(self.path, stem + ext))
                except OSError:
                    pass
            removed += 1
        return removed
