"""Serving fleet: multi-replica router, live migration, autoscaling.

One ``FleetRouter`` fronts N ``GenerationEngine`` replicas behind the
familiar submit/stream API: placement routes by prefix-cache affinity
(requests sharing a system-prompt block land where their pages are
warm) with a least-loaded fallback scored from public engine accessors;
live migration moves in-flight requests between replicas as
``RequestLedgerEntry`` records — the PR 9 rebuild payload made public —
so every stream continues bit-identically after a replica death,
drain, or rebalance; and a signal-driven autoscaler turns the existing
queue/page-pressure/brownout signals into hysteresis-guarded
scale-out/in, draining through migration on the way down. Replica
membership rides the PR 8 elastic lease ledger in replica mode
(``role="serving"``). See ARCHITECTURE.md "Serving fleet".
"""

from deeplearning4j_tpu.serving.fleet.autoscale import (  # noqa: F401
    AutoscaleConfig, FleetAutoscaler, FleetSignals)
from deeplearning4j_tpu.serving.fleet.membership import (  # noqa: F401
    REPLICA_ROLE, FleetMembership)
from deeplearning4j_tpu.serving.fleet.migration import (  # noqa: F401
    MigrationReport, readmit_entries)
from deeplearning4j_tpu.serving.fleet.router import (  # noqa: F401
    FleetConfig, FleetReplica, FleetRouter)

__all__ = ["AutoscaleConfig", "FleetAutoscaler", "FleetConfig",
           "FleetMembership", "FleetReplica", "FleetRouter",
           "FleetSignals", "MigrationReport", "REPLICA_ROLE",
           "readmit_entries"]
