"""Serving fleet: multi-replica router, live migration, autoscaling.

One ``FleetRouter`` fronts N ``GenerationEngine`` replicas behind the
familiar submit/stream API: placement routes by prefix-cache affinity
(requests sharing a system-prompt block land where their pages are
warm) with a least-loaded fallback scored from public engine accessors;
live migration moves in-flight requests between replicas as
``RequestLedgerEntry`` records — the PR 9 rebuild payload made public —
so every stream continues bit-identically after a replica death,
drain, or rebalance; and a signal-driven autoscaler turns the existing
queue/page-pressure/brownout signals into hysteresis-guarded
scale-out/in, draining through migration on the way down. Replica
membership rides the PR 8 elastic lease ledger in replica mode
(``role="serving"``). See ARCHITECTURE.md "Serving fleet".

The CROSS-PROCESS shape puts each replica in its own OS process: a
``ReplicaAgent`` (``agent.py``, spawned by the ``worker.py``
entrypoint) wraps one engine behind a lease heartbeat
(``role="replica"``), a shared-filesystem command mailbox, and an
append-only stream journal (``transport.py``); a ``ProcessFleetRouter``
discovers agents through the leases alone, submits by mailing ledger
payloads, relays journal events into local stream handles, and
re-places a dead replica's work onto survivors with no cooperation from
the corpse — ``kill -9`` survivable by construction. See
ARCHITECTURE.md "Cross-process fleet".

DISAGGREGATED serving splits the cross-process fleet by role: prompts
long enough to ship are mailed to a ``PrefillAgent``
(``role="prefill"``), which primes through the ordinary admission
path, publishes the prompt's full-block KV pages to a
content-addressed fleet ``PageStore`` (``pages.py``), and journals the
first token + rng state; the router relays that token and re-places
the stream on a decode replica scored by PAGE LOCALITY (advertised
prefix-chain digests), whose admission imports the shipped pages and
primes only the suffix — bit-identical to unified serving, with
prefill FLOPs off the decode replicas entirely. See ARCHITECTURE.md
"Disaggregated serving".
"""

from deeplearning4j_tpu.serving.fleet.agent import (  # noqa: F401
    ReplicaAgent)
from deeplearning4j_tpu.serving.fleet.autoscale import (  # noqa: F401
    AutoscaleConfig, FleetAutoscaler, FleetSignals)
from deeplearning4j_tpu.serving.fleet.membership import (  # noqa: F401
    AGENT_ROLE, PREFILL_ROLE, REPLICA_ROLE, FleetMembership)
from deeplearning4j_tpu.serving.fleet.migration import (  # noqa: F401
    MigrationReport, readmit_entries)
from deeplearning4j_tpu.serving.fleet.pages import (  # noqa: F401
    PageStore)
from deeplearning4j_tpu.serving.fleet.prefill import (  # noqa: F401
    PrefillAgent)
from deeplearning4j_tpu.serving.fleet.router import (  # noqa: F401
    FleetConfig, FleetReplica, FleetRouter, ProcessFleetRouter)
from deeplearning4j_tpu.serving.fleet.transport import (  # noqa: F401
    AgentStatus, JournalReader, JournalWriter, Mailbox, fleet_paths)

__all__ = ["AGENT_ROLE", "AgentStatus", "AutoscaleConfig",
           "FleetAutoscaler", "FleetConfig", "FleetMembership",
           "FleetReplica", "FleetRouter", "FleetSignals",
           "JournalReader", "JournalWriter", "Mailbox",
           "MigrationReport", "PREFILL_ROLE", "PageStore",
           "PrefillAgent", "ProcessFleetRouter", "REPLICA_ROLE",
           "ReplicaAgent", "fleet_paths", "readmit_entries"]
