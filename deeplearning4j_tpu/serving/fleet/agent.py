"""ReplicaAgent: one GenerationEngine as its own fleet process.

The cross-process fleet's replica half: an agent wraps ONE engine and
exposes it to an out-of-process router purely through the shared
filesystem —

- a **lease heartbeat** stamped ``role="replica"``
  (``membership.AGENT_ROLE``) through the same
  ``resilience/elastic.py`` ledger the elastic trainer's ranks beat
  on, advertising the agent's pid; an expired lease IS the death
  signal (a ``kill -9``'d process simply stops beating — there is no
  cooperative shutdown path to rely on);
- a **mailbox consumer**: admission/migration commands carry
  ``RequestLedgerEntry.payload()`` wire forms, deduped by
  ``(request id, attempt)`` — at-least-once delivery made effectively
  exactly-once — and admitted through the ONE engine re-admission
  path (``admit_from_ledger``: streamed entries re-prime
  ``ids[:-1]`` with their pending token and restored rng, fresh
  entries admit normally). Undecodable command files are quarantined
  by the mailbox, never crashing this loop;
- a **journal publisher**: after every engine step the agent writes
  one ``tok`` line per progressed request — the step's new tokens,
  their absolute indices, and the request's post-step rng state (one
  line = one consistency unit) — plus ``done``/``nack`` lines, which
  the router relays into the caller's local ``GenerationStream``
  handles.

The agent drives ``engine.step()`` from its OWN loop (never
``engine.start()``): between steps the engine is quiescent, so the
(committed ids, rng state) pair each journal line snapshots is exactly
consistent — the property that makes a survivor's re-prime
bit-identical. Telemetry rides the shared ``dl4jtpu_fleet_transport_*``
series and the ``transport`` event category.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Dict, Optional

from deeplearning4j_tpu.monitoring.events import emit as emit_event
from deeplearning4j_tpu.monitoring.metrics import (
    MetricsRegistry, global_registry)
from deeplearning4j_tpu.serving.fleet import transport
from deeplearning4j_tpu.serving.fleet.membership import (
    AGENT_ROLE, FleetMembership)
from deeplearning4j_tpu.serving.health import (
    FLEET_TRANSPORT_COMMANDS, FLEET_TRANSPORT_DUPLICATES,
    FLEET_TRANSPORT_QUARANTINED)
from deeplearning4j_tpu.serving.request import (
    RequestLedgerEntry, rng_state_payload)

log = logging.getLogger(__name__)

__all__ = ["ReplicaAgent"]


class _Tracked:
    """One in-flight request the agent journals progress for."""

    __slots__ = ("request", "attempt", "emitted")

    def __init__(self, request, attempt: int, emitted: int):
        self.request = request
        self.attempt = int(attempt)
        self.emitted = int(emitted)     # generated tokens journaled


class ReplicaAgent:
    """One engine + lease + mailbox + journal = one fleet process.

    Drive it with :meth:`run` (the worker entrypoint's loop) or
    manually with :meth:`poll_once` + :meth:`step` (the deterministic
    in-process test shape — same transport mechanics, no subprocess).
    """

    def __init__(self, engine, root: str, rid: int, *,
                 ttl: float = 2.0,
                 status_interval_s: float = 0.1,
                 registry: Optional[MetricsRegistry] = None,
                 label: str = "fleet"):
        self.engine = engine
        self.rid = int(rid)
        self.root = root
        paths = transport.fleet_paths(root)
        engine.replica_tag = self.rid
        self.membership = FleetMembership(
            paths["leases"], ttl=ttl, role=AGENT_ROLE,
            extra={"pid": os.getpid()})
        self.mailbox = transport.Mailbox(root, self.rid)
        self.journal = transport.JournalWriter(root, self.rid)
        self.status = transport.AgentStatus(root)
        self.status_interval_s = float(status_interval_s)
        self._last_status_t = 0.0
        self._label = label
        self._inflight: Dict[str, _Tracked] = {}
        self._seen: set = set()          # (request id, attempt) dedupe
        self._shutdown = False
        self.duplicates = 0
        self.commands = 0
        #: compile count recorded by :meth:`mark_warm` — the status
        #: file reports compiles SINCE warmup, the cross-process form
        #: of the zero-retrace pin (a parent test can't read a child's
        #: in-process counter)
        self._warm_compiles: Optional[float] = None
        r = registry or global_registry()
        lab = dict(fleet=self._label, replica=str(self.rid))
        self._cmd_c = r.counter(
            FLEET_TRANSPORT_COMMANDS, "Mailbox commands consumed, "
            "by kind", ("fleet", "replica", "kind"))
        self._dup_c = r.counter(
            FLEET_TRANSPORT_DUPLICATES, "Duplicate deliveries dropped "
            "by request-id dedupe", ("fleet", "replica")).labels(**lab)
        self._quar_c = r.counter(
            FLEET_TRANSPORT_QUARANTINED, "Torn/undecodable command "
            "files quarantined", ("fleet", "replica")).labels(**lab)
        self._quarantined_seen = 0
        self.membership.join(self.rid)
        self.write_status()

    # -- the zero-retrace bookkeeping ----------------------------------
    @staticmethod
    def _compile_total() -> float:
        from deeplearning4j_tpu.monitoring import runtime
        c = global_registry().get(runtime.COMPILE_COUNTER)
        return 0.0 if c is None else c.total()

    def mark_warm(self) -> None:
        """Record the post-warmup compile count; the status file then
        advertises ``compiles_since_warm`` (must stay 0 — re-primes
        land in warm buckets)."""
        self._warm_compiles = self._compile_total()

    # -- status advertisement ------------------------------------------
    def status_payload(self) -> dict:
        out = {"rid": self.rid, "pid": os.getpid(),
               "ts": time.time(),
               "healthy": self.engine.is_healthy(),
               "ready": self.engine.is_ready(),
               "load": self.engine.load_stats(),
               "inflight": len(self._inflight),
               "commands": self.commands,
               "duplicates": self.duplicates,
               "quarantined": len(self.mailbox.quarantined())}
        kv = self.engine.health().get("kv_pages")
        if kv:
            out["kv_page_size"] = kv["page_size"]
        if self._warm_compiles is not None:
            out["compiles_since_warm"] = \
                self._compile_total() - self._warm_compiles
        return out

    def write_status(self, force: bool = True) -> None:
        now = time.monotonic()
        if not force and now - self._last_status_t \
                < self.status_interval_s:
            return
        self._last_status_t = now
        self.status.write(self.rid, self.status_payload())

    # -- the command loop ----------------------------------------------
    def poll_once(self) -> int:
        """Consume every pending mailbox command; returns how many were
        processed. Never raises on bad input — a torn command was
        quarantined by the mailbox before this sees it."""
        before = len(self.mailbox.quarantined())
        cmds = self.mailbox.receive()
        newly_quarantined = len(self.mailbox.quarantined()) - before
        if newly_quarantined > 0:
            self._quar_c.inc(newly_quarantined)
            emit_event("transport", "quarantine", replica=self.rid,
                       count=newly_quarantined)
        for _, cmd in cmds:
            self.commands += 1
            kind = str(cmd.get("kind"))
            self._cmd_c.labels(fleet=self._label,
                               replica=str(self.rid), kind=kind).inc()
            if kind == transport.CMD_ADMIT:
                self._handle_admit(cmd)
            elif kind == transport.CMD_REVOKE:
                self._handle_revoke(cmd)
            elif kind == transport.CMD_SHUTDOWN:
                self._shutdown = True
            else:
                log.warning("agent %d: unknown command kind %r "
                            "ignored", self.rid, kind)
        return len(cmds)

    def _handle_admit(self, cmd: dict) -> None:
        req_id = str(cmd.get("req"))
        attempt = int(cmd.get("attempt", 0))
        key = (req_id, attempt)
        if key in self._seen:
            # at-least-once delivery: the SAME (request, attempt) may
            # arrive twice; admission must be idempotent
            self.duplicates += 1
            self._dup_c.inc()
            emit_event("transport", "duplicate", replica=self.rid,
                       req=req_id, attempt=attempt)
            return
        self._seen.add(key)
        try:
            entry = RequestLedgerEntry.from_payload(cmd["entry"])
        except (KeyError, ValueError, TypeError) as e:
            # a well-formed envelope around a bad payload: nack it so
            # the router resolves the caller instead of hanging
            self.journal.append([{"kind": transport.EV_NACK,
                                  "req": req_id, "attempt": attempt,
                                  "error": repr(e)}])
            emit_event("transport", "nack", replica=self.rid,
                       req=req_id, error=repr(e))
            return
        req = entry.request
        rec = _Tracked(req, attempt,
                       emitted=len(req.handle.generated))
        try:
            self.engine.admit_from_ledger(
                [entry], where="over the fleet transport")
        except Exception as e:      # noqa: BLE001 — nack, never crash
            # EngineShutdown (draining/broken) or any admission fault:
            # the router re-places on another replica; the agent's
            # poll loop must survive every command
            self.journal.append([{"kind": transport.EV_NACK,
                                  "req": req_id, "attempt": attempt,
                                  "error": repr(e)}])
            emit_event("transport", "nack", replica=self.rid,
                       req=req_id, error=repr(e))
            return
        emit_event("transport", "admit", replica=self.rid, req=req_id,
                   attempt=attempt, streamed=entry.streamed)
        self._inflight[req_id] = rec
        if req.handle.done:
            # resolved during admission (expired deadline, cancel):
            # publish the terminal event right away
            self.publish_progress()

    def _handle_revoke(self, cmd: dict) -> None:
        req_id = str(cmd.get("req"))
        attempt = int(cmd.get("attempt", 0))
        rec = self._inflight.get(req_id)
        if rec is None or rec.attempt != attempt:
            return                      # stale fence: nothing to do
        rec.request.handle.cancel()
        emit_event("transport", "revoke", replica=self.rid,
                   req=req_id, attempt=attempt)

    # -- the journal publisher -----------------------------------------
    def publish_progress(self) -> int:
        """Journal every tracked request's new tokens (absolute
        indices + post-step rng state, one line per request) and any
        retirements; returns the number of events written."""
        events = []
        done_ids = []
        for req_id, rec in self._inflight.items():
            handle = rec.request.handle
            gen = handle.generated
            if len(gen) > rec.emitted:
                events.append({
                    "kind": transport.EV_TOK, "req": req_id,
                    "attempt": rec.attempt, "start": rec.emitted,
                    "toks": gen[rec.emitted:],
                    "rng": rng_state_payload(rec.request.rng)})
                rec.emitted = len(gen)
            if handle.done:
                err = handle.error
                events.append({
                    "kind": transport.EV_DONE, "req": req_id,
                    "attempt": rec.attempt,
                    "reason": handle.finish_reason,
                    "error": None if err is None else repr(err)})
                done_ids.append(req_id)
        for req_id in done_ids:
            del self._inflight[req_id]
        return self.journal.append(events)

    # -- driving -------------------------------------------------------
    def step(self) -> bool:
        """One engine cycle + journal flush (the in-process drive)."""
        progressed = self.engine.step()
        self.publish_progress()
        self.write_status(force=False)
        return progressed

    def run(self, idle_sleep_s: float = 0.005,
            step_delay_s: float = 0.0) -> None:
        """The worker-process main loop: poll the mailbox, step the
        engine, publish, until a ``shutdown`` command arrives.
        `step_delay_s` throttles progressing steps — the kill-mid-trace
        tests' pacing knob (a tiny warm model otherwise finishes a
        whole trace inside one observer poll interval)."""
        while not self._shutdown:
            handled = self.poll_once()
            progressed = self.step()
            if progressed and step_delay_s > 0:
                time.sleep(step_delay_s)
            if not handled and not progressed:
                time.sleep(idle_sleep_s)
        self.close()

    def close(self) -> None:
        """Orderly leave: withdraw the lease, flush status, shut the
        engine down. (A crash never runs this — that is the point.)"""
        self._shutdown = True
        try:
            self.write_status()
        except OSError:
            pass
        self.membership.stop()
        self.journal.close()
        self.engine.shutdown()
