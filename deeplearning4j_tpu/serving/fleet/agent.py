"""ReplicaAgent: one GenerationEngine as its own fleet process.

The cross-process fleet's replica half: an agent wraps ONE engine and
exposes it to an out-of-process router purely through the shared
filesystem —

- a **lease heartbeat** stamped ``role="replica"``
  (``membership.AGENT_ROLE``) through the same
  ``resilience/elastic.py`` ledger the elastic trainer's ranks beat
  on, advertising the agent's pid; an expired lease IS the death
  signal (a ``kill -9``'d process simply stops beating — there is no
  cooperative shutdown path to rely on);
- a **mailbox consumer**: admission/migration commands carry
  ``RequestLedgerEntry.payload()`` wire forms, deduped by
  ``(request id, attempt)`` — at-least-once delivery made effectively
  exactly-once — and admitted through the ONE engine re-admission
  path (``admit_from_ledger``: streamed entries re-prime
  ``ids[:-1]`` with their pending token and restored rng, fresh
  entries admit normally). Undecodable command files are quarantined
  by the mailbox, never crashing this loop;
- a **journal publisher**: after every engine step the agent writes
  one ``tok`` line per progressed request — the step's new tokens,
  their absolute indices, and the request's post-step rng state (one
  line = one consistency unit) — plus ``done``/``nack`` lines, which
  the router relays into the caller's local ``GenerationStream``
  handles.

The agent drives ``engine.step()`` from its OWN loop (never
``engine.start()``): between steps the engine is quiescent, so the
(committed ids, rng state) pair each journal line snapshots is exactly
consistent — the property that makes a survivor's re-prime
bit-identical. Telemetry rides the shared ``dl4jtpu_fleet_transport_*``
series and the ``transport`` event category.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Dict, Optional

from deeplearning4j_tpu.monitoring.events import emit as emit_event
from deeplearning4j_tpu.monitoring.metrics import (
    MetricsRegistry, global_registry)
from deeplearning4j_tpu.serving.fleet import transport
from deeplearning4j_tpu.serving.fleet.membership import (
    AGENT_ROLE, FleetMembership)
from deeplearning4j_tpu.serving.health import (
    FLEET_PAGE_SHIP_BYTES, FLEET_PAGE_STORE_HITS,
    FLEET_PAGE_STORE_MISSES, FLEET_PAGES_IMPORTED,
    FLEET_PAGES_PUBLISHED, FLEET_PAGES_QUARANTINED,
    FLEET_TRANSPORT_COMMANDS, FLEET_TRANSPORT_DUPLICATES,
    FLEET_TRANSPORT_QUARANTINED)
from deeplearning4j_tpu.serving.prefix_cache import chain_digests
from deeplearning4j_tpu.serving.request import (
    RequestLedgerEntry, rng_state_payload)

log = logging.getLogger(__name__)

__all__ = ["ReplicaAgent"]


class _Tracked:
    """One in-flight request the agent journals progress for."""

    __slots__ = ("request", "attempt", "emitted")

    def __init__(self, request, attempt: int, emitted: int):
        self.request = request
        self.attempt = int(attempt)
        self.emitted = int(emitted)     # generated tokens journaled


class ReplicaAgent:
    """One engine + lease + mailbox + journal = one fleet process.

    Drive it with :meth:`run` (the worker entrypoint's loop) or
    manually with :meth:`poll_once` + :meth:`step` (the deterministic
    in-process test shape — same transport mechanics, no subprocess).
    """

    def __init__(self, engine, root: str, rid: int, *,
                 ttl: float = 2.0,
                 status_interval_s: float = 0.1,
                 registry: Optional[MetricsRegistry] = None,
                 label: str = "fleet",
                 page_store=None, import_pages: bool = True,
                 publish_pages: bool = False,
                 advertise_digests: int = 32):
        self.engine = engine
        self.rid = int(rid)
        self.root = root
        paths = transport.fleet_paths(root)
        engine.replica_tag = self.rid
        self.membership = FleetMembership(
            paths["leases"], ttl=ttl, role=AGENT_ROLE,
            extra={"pid": os.getpid()})
        self.mailbox = transport.Mailbox(root, self.rid)
        self.journal = transport.JournalWriter(root, self.rid)
        self.status = transport.AgentStatus(root)
        self.status_interval_s = float(status_interval_s)
        self._last_status_t = 0.0
        self._label = label
        self._inflight: Dict[str, _Tracked] = {}
        self._seen: set = set()          # (request id, attempt) dedupe
        self._shutdown = False
        self._drain_requested = False
        self.duplicates = 0
        self.commands = 0
        #: fleet page-store seam (serving/fleet/pages.py): with a
        #: store, admission probes it for shipped prefix blocks before
        #: priming (``import_pages``) and prefix-cache inserts publish
        #: back (``publish_pages``) — either side is independently
        #: optional; both are best-effort (a store fault degrades to a
        #: fresh prefill, never a failed admission)
        self._page_store = page_store
        self._import_pages = bool(import_pages)
        self._advertise_digests = int(advertise_digests)
        kv = engine.health().get("kv_pages")
        self._ps = kv["page_size"] if kv else None
        self._kv_dtype = (engine.health()
                          .get("kv_traffic", {}).get("kv_dtype"))
        self.store_hits = 0
        self.store_misses = 0
        self.pages_imported = 0
        self.import_bytes = 0
        self.pages_published = 0
        self.publish_bytes = 0
        self._store_corrupt_seen = 0
        #: compile count recorded by :meth:`mark_warm` — the status
        #: file reports compiles SINCE warmup, the cross-process form
        #: of the zero-retrace pin (a parent test can't read a child's
        #: in-process counter)
        self._warm_compiles: Optional[float] = None
        r = registry or global_registry()
        lab = dict(fleet=self._label, replica=str(self.rid))
        self._cmd_c = r.counter(
            FLEET_TRANSPORT_COMMANDS, "Mailbox commands consumed, "
            "by kind", ("fleet", "replica", "kind"))
        self._dup_c = r.counter(
            FLEET_TRANSPORT_DUPLICATES, "Duplicate deliveries dropped "
            "by request-id dedupe", ("fleet", "replica")).labels(**lab)
        self._quar_c = r.counter(
            FLEET_TRANSPORT_QUARANTINED, "Torn/undecodable command "
            "files quarantined", ("fleet", "replica")).labels(**lab)
        self._quarantined_seen = 0
        self._hit_c = r.counter(
            FLEET_PAGE_STORE_HITS, "Page-store probes that found a "
            "shipped prefix block", ("fleet", "replica")).labels(**lab)
        self._miss_c = r.counter(
            FLEET_PAGE_STORE_MISSES, "Page-store probes that missed",
            ("fleet", "replica")).labels(**lab)
        self._imp_c = r.counter(
            FLEET_PAGES_IMPORTED, "Shipped KV pages mapped into the "
            "local pool", ("fleet", "replica")).labels(**lab)
        self._pub_c = r.counter(
            FLEET_PAGES_PUBLISHED, "KV pages published to the fleet "
            "store", ("fleet", "replica")).labels(**lab)
        self._ship_c = r.counter(
            FLEET_PAGE_SHIP_BYTES, "Page bytes moved through the "
            "store, by direction", ("fleet", "replica", "direction"))
        self._squar_c = r.counter(
            FLEET_PAGES_QUARANTINED, "Torn/mismatched store entries "
            "quarantined", ("fleet", "replica")).labels(**lab)
        if page_store is not None and publish_pages:
            # bind the private pieces here, where `self` access is the
            # sanctioned seam — the closure itself only touches public
            # agent surface
            ship_pub = self._ship_c.labels(
                fleet=self._label, replica=str(self.rid),
                direction="publish")
            def _publish(prompt, table, _agent=self, _store=page_store,
                         _pub_c=self._pub_c, _ship_pub=ship_pub):
                res = _agent.engine.export_prefix_chain(
                    prompt, table, _store)
                if res["published"]:
                    _agent.pages_published += res["published"]
                    _agent.publish_bytes += res["bytes"]
                    _pub_c.inc(res["published"])
                    _ship_pub.inc(res["bytes"])
                    emit_event("transport", "page_publish",
                               replica=_agent.rid,
                               blocks=res["published"],
                               bytes=res["bytes"])
            engine.page_publisher = _publish
        self.membership.join(self.rid)
        self.write_status()

    # -- the zero-retrace bookkeeping ----------------------------------
    @staticmethod
    def _compile_total() -> float:
        from deeplearning4j_tpu.monitoring import runtime
        c = global_registry().get(runtime.COMPILE_COUNTER)
        return 0.0 if c is None else c.total()

    def mark_warm(self) -> None:
        """Record the post-warmup compile count; the status file then
        advertises ``compiles_since_warm`` (must stay 0 — re-primes
        land in warm buckets)."""
        self._warm_compiles = self._compile_total()

    # -- status advertisement ------------------------------------------
    def status_payload(self) -> dict:
        out = {"rid": self.rid, "pid": os.getpid(),
               "ts": time.time(),
               "role": "replica",
               "healthy": self.engine.is_healthy(),
               "ready": self.engine.is_ready(),
               "load": self.engine.load_stats(),
               "inflight": len(self._inflight),
               "commands": self.commands,
               "duplicates": self.duplicates,
               "quarantined": len(self.mailbox.quarantined())}
        kv = self.engine.health().get("kv_pages")
        if kv:
            out["kv_page_size"] = kv["page_size"]
            # page-locality advertisement: the digests of cached
            # prefix blocks, LRU order — the router scores decode
            # placement by the longest leading run of a prompt's chain
            # found here
            out["prefix_digests"] = self.engine.prefix_digests(
                self._advertise_digests)
        if self._page_store is not None:
            out["page_store"] = {
                "hits": self.store_hits,
                "misses": self.store_misses,
                "imported": self.pages_imported,
                "import_bytes": self.import_bytes,
                "published": self.pages_published,
                "publish_bytes": self.publish_bytes,
                "quarantined": self._page_store.corrupt}
        if self._warm_compiles is not None:
            out["compiles_since_warm"] = \
                self._compile_total() - self._warm_compiles
        return out

    def write_status(self, force: bool = True) -> None:
        now = time.monotonic()
        if not force and now - self._last_status_t \
                < self.status_interval_s:
            return
        self._last_status_t = now
        self.status.write(self.rid, self.status_payload())

    # -- the command loop ----------------------------------------------
    def poll_once(self) -> int:
        """Consume every pending mailbox command; returns how many were
        processed. Never raises on bad input — a torn command was
        quarantined by the mailbox before this sees it."""
        before = len(self.mailbox.quarantined())
        cmds = self.mailbox.receive()
        newly_quarantined = len(self.mailbox.quarantined()) - before
        if newly_quarantined > 0:
            self._quar_c.inc(newly_quarantined)
            emit_event("transport", "quarantine", replica=self.rid,
                       count=newly_quarantined)
        for _, cmd in cmds:
            self.commands += 1
            kind = str(cmd.get("kind"))
            self._cmd_c.labels(fleet=self._label,
                               replica=str(self.rid), kind=kind).inc()
            if kind == transport.CMD_ADMIT:
                self._handle_admit(cmd)
            elif kind == transport.CMD_REVOKE:
                self._handle_revoke(cmd)
            elif kind == transport.CMD_SHUTDOWN:
                self._shutdown = True
            else:
                log.warning("agent %d: unknown command kind %r "
                            "ignored", self.rid, kind)
        return len(cmds)

    def _handle_admit(self, cmd: dict) -> None:
        req_id = str(cmd.get("req"))
        attempt = int(cmd.get("attempt", 0))
        key = (req_id, attempt)
        if key in self._seen:
            # at-least-once delivery: the SAME (request, attempt) may
            # arrive twice; admission must be idempotent
            self.duplicates += 1
            self._dup_c.inc()
            emit_event("transport", "duplicate", replica=self.rid,
                       req=req_id, attempt=attempt)
            return
        self._seen.add(key)
        try:
            entry = RequestLedgerEntry.from_payload(cmd["entry"])
        except (KeyError, ValueError, TypeError) as e:
            # a well-formed envelope around a bad payload: nack it so
            # the router resolves the caller instead of hanging
            self.journal.append([{"kind": transport.EV_NACK,
                                  "req": req_id, "attempt": attempt,
                                  "error": repr(e)}])
            emit_event("transport", "nack", replica=self.rid,
                       req=req_id, error=repr(e))
            return
        req = entry.request
        rec = _Tracked(req, attempt,
                       emitted=len(req.handle.generated))
        if self._page_store is not None and self._import_pages:
            try:
                self._import_shipped_prefix(req)
            except Exception:   # noqa: BLE001 — import is best-effort
                log.exception("agent %d: page import failed; admitting "
                              "with a fresh prefill", self.rid)
        try:
            self.engine.admit_from_ledger(
                [entry], where="over the fleet transport")
        except Exception as e:      # noqa: BLE001 — nack, never crash
            # EngineShutdown (draining/broken) or any admission fault:
            # the router re-places on another replica; the agent's
            # poll loop must survive every command
            self.journal.append([{"kind": transport.EV_NACK,
                                  "req": req_id, "attempt": attempt,
                                  "error": repr(e)}])
            emit_event("transport", "nack", replica=self.rid,
                       req=req_id, error=repr(e))
            return
        emit_event("transport", "admit", replica=self.rid, req=req_id,
                   attempt=attempt, streamed=entry.streamed)
        self._inflight[req_id] = rec
        if req.handle.done:
            # resolved during admission (expired deadline, cancel):
            # publish the terminal event right away
            self.publish_progress()

    def _import_shipped_prefix(self, req) -> None:
        """Pre-admission store probe: compute the prompt's chain
        digests, skip the blocks the local prefix cache already holds,
        load the rest from the store (verified — a torn entry
        quarantines and reads as a miss), and map them into the pool.
        The admission that follows then takes an ordinary prefix-cache
        hit and primes only the suffix: ZERO full-block prefill steps
        run here for shipped blocks. A partial chain (store miss
        mid-run) imports the leading run it did find."""
        if self._ps is None or not self.engine.pages_importable():
            # un-warmed bf16 pools materialize at the first prime —
            # that admission goes fresh, everything after imports
            return
        prompt = req.prompt
        limit = (len(prompt) - 1) // self._ps   # usable full blocks
        if limit <= 0:
            return
        held = self.engine.prefix_held_blocks(prompt)
        if held >= limit:
            return                  # everything useful is local
        digs = chain_digests(prompt, self._ps)
        blocks = []
        for i in range(held, limit):
            entry = self._page_store.load(digs[i], self._kv_dtype)
            if entry is None:
                self.store_misses += 1
                self._miss_c.inc()
                break
            self.store_hits += 1
            self._hit_c.inc()
            blocks.append(entry)
        newq = self._page_store.corrupt - self._store_corrupt_seen
        if newq > 0:
            self._store_corrupt_seen = self._page_store.corrupt
            self._squar_c.inc(newq)
            emit_event("transport", "page_quarantine",
                       replica=self.rid, count=newq)
        if not blocks:
            return
        res = self.engine.import_prefix_chain(prompt, held, blocks)
        if res["blocks"]:
            self.pages_imported += res["blocks"]
            self.import_bytes += res["bytes"]
            self._imp_c.inc(res["blocks"])
            self._ship_c.labels(fleet=self._label,
                                replica=str(self.rid),
                                direction="import").inc(res["bytes"])
            emit_event("transport", "page_import", replica=self.rid,
                       blocks=res["blocks"], bytes=res["bytes"])

    def _handle_revoke(self, cmd: dict) -> None:
        req_id = str(cmd.get("req"))
        attempt = int(cmd.get("attempt", 0))
        rec = self._inflight.get(req_id)
        if rec is None or rec.attempt != attempt:
            return                      # stale fence: nothing to do
        rec.request.handle.cancel()
        emit_event("transport", "revoke", replica=self.rid,
                   req=req_id, attempt=attempt)

    # -- the journal publisher -----------------------------------------
    def publish_progress(self) -> int:
        """Journal every tracked request's new tokens (absolute
        indices + post-step rng state, one line per request) and any
        retirements; returns the number of events written."""
        events = []
        done_ids = []
        for req_id, rec in self._inflight.items():
            handle = rec.request.handle
            gen = handle.generated
            if len(gen) > rec.emitted:
                events.append({
                    "kind": transport.EV_TOK, "req": req_id,
                    "attempt": rec.attempt, "start": rec.emitted,
                    "toks": gen[rec.emitted:],
                    "rng": rng_state_payload(rec.request.rng)})
                rec.emitted = len(gen)
            if handle.done:
                err = handle.error
                events.append({
                    "kind": transport.EV_DONE, "req": req_id,
                    "attempt": rec.attempt,
                    "reason": handle.finish_reason,
                    "error": None if err is None else repr(err)})
                done_ids.append(req_id)
        for req_id in done_ids:
            del self._inflight[req_id]
        return self.journal.append(events)

    # -- driving -------------------------------------------------------
    def step(self) -> bool:
        """One engine cycle + journal flush (the in-process drive)."""
        progressed = self.engine.step()
        self.publish_progress()
        self.write_status(force=False)
        return progressed

    # -- graceful scale-in ---------------------------------------------
    def request_drain(self) -> None:
        """Async-signal-safe drain request (the worker entrypoint's
        SIGTERM handler calls ONLY this): sets a flag the run loop acts
        on between steps — the handler itself must not touch the
        journal or the engine mid-dispatch."""
        self._drain_requested = True

    def drain(self) -> None:
        """Planned scale-in, no corpse protocol needed: stop taking
        commands, journal every committed (ids, rng) consistency unit
        FIRST, then nack each in-flight request — the router's normal
        nack path re-places every stream on a survivor bit-exactly
        (re-prime from exactly the journaled state, in order BEFORE
        the nack in this rid's journal stream). Finally withdraw the
        lease and shut down: peers see an orderly leave at their next
        read instead of waiting out the lease TTL."""
        self._shutdown = True
        try:
            # the engine is quiescent between agent-driven steps, so
            # this snapshot is the complete committed state
            self.publish_progress()
            events = []
            # admissions still sitting unread in the mailbox never
            # started — hand them back too, or they hang forever
            for _, cmd in self.mailbox.receive():
                if str(cmd.get("kind")) != transport.CMD_ADMIT:
                    continue
                events.append({"kind": transport.EV_NACK,
                               "req": str(cmd.get("req")),
                               "attempt": int(cmd.get("attempt", 0)),
                               "error": "replica draining (planned "
                                        "scale-in)"})
                emit_event("transport", "drain_nack", replica=self.rid,
                           req=str(cmd.get("req")))
            for req_id, rec in self._inflight.items():
                events.append({"kind": transport.EV_NACK,
                               "req": req_id, "attempt": rec.attempt,
                               "error": "replica draining (planned "
                                        "scale-in)"})
                emit_event("transport", "drain_nack", replica=self.rid,
                           req=req_id)
            if events:
                self.journal.append(events)
            self._inflight.clear()
            emit_event("transport", "drain", replica=self.rid,
                       requeued=len(events))
        finally:
            self.close()

    def run(self, idle_sleep_s: float = 0.005,
            step_delay_s: float = 0.0) -> None:
        """The worker-process main loop: poll the mailbox, step the
        engine, publish, until a ``shutdown`` command arrives (or a
        drain request — SIGTERM — hands every stream back through the
        ledger first)."""
        while not self._shutdown:
            if self._drain_requested:
                self.drain()
                return
            handled = self.poll_once()
            progressed = self.step()
            if progressed and step_delay_s > 0:
                time.sleep(step_delay_s)
            if not handled and not progressed:
                time.sleep(idle_sleep_s)
        self.close()

    def close(self) -> None:
        """Orderly leave: withdraw the lease, flush status, shut the
        engine down. (A crash never runs this — that is the point.)"""
        self._shutdown = True
        try:
            self.write_status()
        except OSError:
            pass
        self.membership.stop()
        self.journal.close()
        self.engine.shutdown()
