"""fleet_worker: run one ReplicaAgent as an OS process.

The process entrypoint the cross-process fleet spawns one-per-replica
(one per chip in production)::

    python -m deeplearning4j_tpu.serving.fleet.worker \\
        --root /shared/fleet --rid 0 \\
        --builder mypkg.serving:build_engine [--warmup] [--ttl 2.0]

``--builder`` names a ``module:function`` import path; the function is
called with the replica id and must return a ready (un-started)
``GenerationEngine`` over the fleet's shared checkpoint — replicas are
HOMOGENEOUS by contract (identical params ⇒ any replica continues any
stream bit-identically), and the builder seam is how every process
constructs the same engine without pickling one across. With
``--warmup`` the engine pre-compiles every canonical serving shape
before the lease goes live, and the agent's status file advertises
``compiles_since_warm`` (pinned 0 by the kill-survivability suite: a
migrated re-prime must land in warm buckets, cross-process or not).

The agent loop then serves until a ``shutdown`` mailbox command (or
until killed — the survivable case the transport exists for).
``SIGTERM`` is the PLANNED exit: the worker drains — stops admitting,
journals progress, nacks its in-flight streams back through the ledger
(the router re-places them bit-identically on survivors), withdraws
its lease, and exits 0.

``--role prefill`` runs a ``PrefillAgent`` instead (DistServe-style
disaggregation): same builder contract, but the process serves
``prefill`` commands only, publishing KV pages to the fleet page store
and never decoding. ``--pages import|publish|full`` attaches the store
to a replica worker (import shipped pages on admission / publish
prefix inserts / both).
"""

from __future__ import annotations

import argparse
import importlib
import signal
import subprocess
import sys


def spawn(root: str, rid: int, builder: str, *, warmup: bool = False,
          ttl: float = 2.0, throttle: float = 0.0, python: str = None,
          role: str = "replica", pages: str = "off",
          **popen_kw) -> "subprocess.Popen":
    """Launch one fleet worker as a subprocess (the test/bench
    helper): ``spawn(root, 0, "mypkg.serving:build_engine")``. The
    child is a full OS process — its own interpreter, its own GIL,
    its own engine — and the ONLY thing shared with the parent is the
    fleet root. Kill it with ``proc.kill()`` (SIGKILL: the
    survivability case), ``proc.terminate()`` (SIGTERM: the planned
    drain), or mail it a ``shutdown`` command."""
    cmd = [python or sys.executable, "-m",
           "deeplearning4j_tpu.serving.fleet.worker",
           "--root", str(root), "--rid", str(int(rid)),
           "--builder", builder, "--ttl", str(float(ttl))]
    if role != "replica":
        cmd += ["--role", role]
    if pages != "off":
        cmd += ["--pages", pages]
    if throttle:
        cmd += ["--throttle", str(float(throttle))]
    if warmup:
        cmd.append("--warmup")
    return subprocess.Popen(cmd, **popen_kw)


def resolve_builder(spec: str):
    """Import ``module:function`` → the engine-builder callable."""
    mod_name, _, fn_name = spec.partition(":")
    if not mod_name or not fn_name:
        raise ValueError(
            f"--builder must be module:function, got {spec!r}")
    mod = importlib.import_module(mod_name)
    fn = getattr(mod, fn_name, None)
    if not callable(fn):
        raise ValueError(f"{spec!r} does not name a callable")
    return fn


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="fleet_worker",
        description="one serving-fleet replica agent process")
    p.add_argument("--root", required=True,
                   help="shared fleet root (leases/mail/journal/status)")
    p.add_argument("--rid", required=True, type=int,
                   help="replica id (lease rank, mailbox dir)")
    p.add_argument("--builder", required=True,
                   help="module:function returning a GenerationEngine "
                        "for a given replica id")
    p.add_argument("--ttl", type=float, default=2.0,
                   help="lease ttl seconds (death-detection horizon)")
    p.add_argument("--role", choices=("replica", "prefill"),
                   default="replica",
                   help="replica: decode-capable agent (default); "
                        "prefill: prefill-only agent publishing KV "
                        "pages to the fleet store")
    p.add_argument("--pages", choices=("off", "import", "publish",
                                       "full"), default="off",
                   help="replica page-store attachment: import shipped "
                        "pages on admission, publish prefix-cache "
                        "inserts, or both (prefill workers always "
                        "publish)")
    p.add_argument("--warmup", action="store_true",
                   help="pre-compile every serving bucket before "
                        "going live (zero retraces afterwards)")
    p.add_argument("--throttle", type=float, default=0.0,
                   help="sleep this long after each progressing "
                        "engine step (kill-mid-trace test pacing)")
    args = p.parse_args(argv)

    # import late so --help stays instant even with jax in the builder
    from deeplearning4j_tpu.serving.fleet.agent import ReplicaAgent
    from deeplearning4j_tpu.serving.fleet.pages import PageStore
    from deeplearning4j_tpu.serving.fleet.prefill import PrefillAgent

    builder = resolve_builder(args.builder)
    engine = builder(args.rid)
    if args.warmup:
        engine.warmup()
    if args.role == "prefill":
        store = PageStore(args.root)
        agent = PrefillAgent(engine, store, args.root, args.rid,
                             ttl=args.ttl)
        run = agent.run
    else:
        store = PageStore(args.root) if args.pages != "off" else None
        agent = ReplicaAgent(
            engine, args.root, args.rid, ttl=args.ttl,
            page_store=store,
            import_pages=args.pages in ("import", "full"),
            publish_pages=args.pages in ("publish", "full"))

        def run():
            agent.run(step_delay_s=args.throttle)
    if args.warmup:
        agent.mark_warm()
    agent.write_status()
    # SIGTERM = planned scale-in: drain (nack in-flight streams back
    # through the journal, withdraw the lease) and exit 0 — the signal
    # handler only flips a flag; the run loop does the actual work
    # outside async-signal context
    signal.signal(signal.SIGTERM,
                  lambda *_: agent.request_drain())
    try:
        run()
    except KeyboardInterrupt:
        agent.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
