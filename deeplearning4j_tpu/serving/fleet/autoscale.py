"""Signal-driven fleet autoscaling with hysteresis.

The autoscaler is pure host-side POLICY over signals the serving stack
already emits — aggregate queue depth (``AdmissionQueue.snapshot``),
arena occupancy and free-page fraction (``engine.health()``), and the
overload controller's brownout rung / breach evidence
(``health()["overload"]``). It never touches an engine: the router
collects a :class:`FleetSignals` snapshot per tick, the autoscaler
returns ``"out"`` / ``"in"`` / ``None``, and the router executes
(factory-spawn on scale-out, ledger migration + shutdown on scale-in).

Hysteresis is double: a decision needs the condition SUSTAINED for N
consecutive ticks (``out_ticks`` / ``in_ticks`` — one slow request
must not buy a replica), and any action opens a ``cooldown_s`` window
during which no further action fires (the replica just added needs
time to absorb load before the signals are believed again). An
oscillating load trace therefore produces zero actions unless one
phase outlasts the streak requirement — the no-flapping contract the
fleet parity suite pins.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

__all__ = ["AutoscaleConfig", "FleetAutoscaler", "FleetSignals"]


@dataclasses.dataclass(frozen=True)
class FleetSignals:
    """One tick's aggregate fleet observation (collected by the router
    from public engine accessors only)."""

    replicas: int
    slots: int                  # total arena slots across replicas
    active: int                 # occupied slots across replicas
    queued: int                 # aggregate admission-queue depth
    free_page_frac: Optional[float]  # min over replicas; None unpaged
    brownout_max: int           # worst brownout rung across replicas

    @property
    def queue_per_slot(self) -> float:
        return self.queued / max(1, self.slots)

    @property
    def active_frac(self) -> float:
        return self.active / max(1, self.slots)

    @classmethod
    def collect(cls, healths: List[dict],
                queue_depths: List[int]) -> "FleetSignals":
        """Aggregate per-replica ``engine.health()`` payloads + queue
        depths into one fleet observation."""
        free = None
        brownout = 0
        for h in healths:
            kv = h.get("kv_pages")
            if kv and kv.get("total"):
                f = kv["free"] / kv["total"]
                free = f if free is None else min(free, f)
            ov = h.get("overload")
            if ov:
                brownout = max(brownout, int(ov["brownout_level"]))
        return cls(replicas=len(healths),
                   slots=sum(h["slots"] for h in healths),
                   active=sum(h["active_slots"] for h in healths),
                   queued=sum(queue_depths),
                   free_page_frac=free, brownout_max=brownout)


@dataclasses.dataclass
class AutoscaleConfig:
    """Knobs for :class:`FleetAutoscaler`.

    Scale OUT when any pressure signal holds for ``out_ticks``
    consecutive ticks: aggregate queued work above
    ``out_queue_per_slot`` per slot, the worst free-page fraction under
    ``out_free_page_frac`` (the page-pressure signal the brownout
    ladder also reads — browning out masks the pressure, a new replica
    removes it), or a brownout rung at/above ``out_brownout_level``.

    Scale IN when the fleet is demonstrably idle for ``in_ticks``
    ticks: queue near-empty (below ``in_queue_per_slot``) AND mean slot
    occupancy under ``in_active_frac`` — and only down to
    ``min_replicas``. Scale-in is deliberately slower to earn than
    scale-out (longer streak): releasing a warm replica costs its
    prefix cache and a migration.

    ``cooldown_s`` gates BOTH directions after any action."""

    min_replicas: int = 1
    max_replicas: int = 4
    out_queue_per_slot: float = 1.0
    out_free_page_frac: float = 0.10
    out_brownout_level: int = 2
    in_queue_per_slot: float = 0.05
    in_active_frac: float = 0.35
    out_ticks: int = 3
    in_ticks: int = 6
    cooldown_s: float = 5.0

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, got "
                             f"{self.min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas ({self.max_replicas}) must be >= "
                f"min_replicas ({self.min_replicas})")
        if self.out_ticks < 1 or self.in_ticks < 1:
            raise ValueError("out_ticks/in_ticks must be >= 1")
        if self.cooldown_s < 0:
            raise ValueError(f"cooldown_s must be >= 0, got "
                             f"{self.cooldown_s}")


class FleetAutoscaler:
    """Streak + cooldown hysteresis over :class:`FleetSignals`."""

    def __init__(self, config: Optional[AutoscaleConfig] = None):
        self.config = config if config is not None else AutoscaleConfig()
        self._out_streak = 0
        self._in_streak = 0
        self._last_action_t: Optional[float] = None
        self.decisions = 0

    # -- the per-tick condition tests ----------------------------------
    def _pressure(self, s: FleetSignals) -> bool:
        c = self.config
        if s.queue_per_slot > c.out_queue_per_slot:
            return True
        if s.free_page_frac is not None \
                and s.free_page_frac < c.out_free_page_frac:
            return True
        return s.brownout_max >= c.out_brownout_level

    def _idle(self, s: FleetSignals) -> bool:
        c = self.config
        return (s.queue_per_slot <= c.in_queue_per_slot
                and s.active_frac < c.in_active_frac)

    def decide(self, signals: FleetSignals, now: float) -> Optional[str]:
        """One autoscale tick: ``"out"`` / ``"in"`` / ``None``. Streaks
        update every tick; a decision fires only once its streak
        reaches the threshold OUTSIDE the cooldown window, and firing
        resets both streaks (fresh post-action evidence required)."""
        c = self.config
        self._out_streak = self._out_streak + 1 \
            if self._pressure(signals) else 0
        self._in_streak = self._in_streak + 1 \
            if self._idle(signals) else 0
        if self._last_action_t is not None \
                and now - self._last_action_t < c.cooldown_s:
            return None
        if self._out_streak >= c.out_ticks \
                and signals.replicas < c.max_replicas:
            self._out_streak = self._in_streak = 0
            self._last_action_t = now
            self.decisions += 1
            return "out"
        if self._in_streak >= c.in_ticks \
                and signals.replicas > c.min_replicas:
            self._out_streak = self._in_streak = 0
            self._last_action_t = now
            self.decisions += 1
            return "in"
        return None
