"""FleetRouter: N GenerationEngine replicas behind one submit API.

The single-engine stack (PRs 5–10) serves one device; the
millions-of-users story needs N replicas behind a router — the L7
analog of DL4J's worker-pooled ParallelInference, built TPU-native
from parts the repo already proved out:

- **Placement** scores replicas by PREFIX-CACHE AFFINITY first: the
  fingerprint of a prompt's leading full block (the system-prompt
  block, sized to the replicas' KV page size) maps to the replica that
  last served it, so requests sharing a system prompt land where their
  prefix pages are warm and prime only their suffix. On an affinity
  miss (or an unavailable owner) placement falls back to least-loaded:
  ``score = (queue_depth + active_slots) / slots − w · free_page_frac``
  over the PUBLIC accessors only (``health()``, ``queue_snapshot()``)
  — the tpulint rule ``replica-local-state-in-router`` holds the fleet
  layer to that seam.
- **Live migration** (``serving/fleet/migration.py``) moves in-flight
  requests between replicas as request-ledger entries
  (``RequestLedgerEntry`` — the PR 9 rebuild payload made public), so
  every stream continues bit-identically on its new replica. Triggers:
  replica death (``is_healthy()`` down, or lease expiry through the
  replica-mode membership ledger), planned scale-in, and sustained
  overload (queued tail rebalanced to an idle replica).
- **Autoscaling** (``serving/fleet/autoscale.py``) turns the existing
  overload/page-pressure/queue signals into scale-out (factory-spawn a
  replica) and scale-in (migrate, then retire the emptiest replica)
  decisions with streak+cooldown hysteresis.

Replicas are assumed HOMOGENEOUS — the ``factory(rid)`` callable
returns engines over identically-parameterized nets (same checkpoint,
same config), which is what makes placement a pure performance choice:
any replica produces bit-identical tokens for any request, so routed
output == single-engine output == one-shot ``sample_stream``
(test-pinned, greedy and sampled, kill-a-replica included).

Drive it manually (``submit()`` + ``step()``/``run_until_idle()`` —
the deterministic test/bench shape; ``poll()`` runs detection/scaling
explicitly) or ``start()`` the replicas' background loops plus the
router's poll thread. See ARCHITECTURE.md "Serving fleet".
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
import uuid
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

from deeplearning4j_tpu.monitoring import flightrecorder
from deeplearning4j_tpu.monitoring.events import (
    emit as emit_event, global_event_log)
from deeplearning4j_tpu.monitoring.metrics import (
    MetricsRegistry, global_registry)
from deeplearning4j_tpu.serving.errors import (
    EngineShutdown, InferenceTimeout, NoReplicaAvailable,
    RequestCancelled, ServingOverloaded, ServingQueueFull)
from deeplearning4j_tpu.serving.fleet import migration as mig
from deeplearning4j_tpu.serving.fleet import transport
from deeplearning4j_tpu.serving.fleet.autoscale import (
    AutoscaleConfig, FleetAutoscaler, FleetSignals)
from deeplearning4j_tpu.serving.fleet.membership import (
    AGENT_ROLE, PREFILL_ROLE, FleetMembership)
from deeplearning4j_tpu.serving.health import (
    FLEET_AFFINITY_HITS, FLEET_AFFINITY_MISSES, FLEET_DEAD_REPLICAS,
    FLEET_GENERATION, FLEET_MIGRATED_REQUESTS, FLEET_MIGRATIONS,
    FLEET_RELAYED_TOKENS, FLEET_REPLACED_REQUESTS, FLEET_REPLICAS,
    FLEET_ROUTED, FLEET_SCALE_EVENTS, FLEET_TRANSPORT_CORRUPT_LINES,
    scrape_probe)
from deeplearning4j_tpu.serving.request import (
    GenerationRequest, RequestLedgerEntry)

log = logging.getLogger(__name__)

__all__ = ["FleetConfig", "FleetReplica", "FleetRouter",
           "ProcessFleetRouter"]


@dataclasses.dataclass
class FleetConfig:
    """Router knobs.

    ``affinity`` routes by the leading-block fingerprint;
    ``affinity_block`` is the fingerprint length in tokens (default:
    the replicas' KV page size, so the fingerprint is exactly one
    cacheable block; 16 when unpaged) and ``affinity_capacity`` bounds
    the fingerprint→replica map (LRU). ``free_weight`` is the
    free-page-fraction weight in the least-loaded score.

    ``rebalance_queue_wait_s`` arms overload rebalancing: when a
    replica's oldest queued request has waited at least this long AND
    another replica scores at least ``rebalance_load_margin`` lower,
    the queued tail migrates there (None disables). ``membership_root``
    + ``lease_ttl_s`` enable filesystem replica leases
    (``serving/fleet/membership.py``); ``poll_interval_s`` paces the
    started router's poll thread.

    ``disagg`` (ProcessFleetRouter only) enables DistServe-style
    prefill/decode separation: prompts holding at least
    ``disagg_min_prompt_blocks`` USABLE full KV blocks (a block the
    suffix-prime rule lets an admission actually reuse — i.e.
    ``(len(prompt) - 1) // page_size`` blocks) route to the
    ``role="prefill"`` lease pool first; the prefilled stream then
    lands on the decode replica whose advertised prefix digests cover
    the longest leading run of the prompt's chain (page locality).
    Short prompts, an empty prefill pool, and every prefill failure
    keep/return to the unified direct path."""

    affinity: bool = True
    affinity_block: Optional[int] = None
    affinity_capacity: int = 512
    free_weight: float = 0.5
    rebalance_queue_wait_s: Optional[float] = None
    rebalance_load_margin: float = 0.5
    membership_root: Optional[str] = None
    lease_ttl_s: float = 2.0
    poll_interval_s: float = 0.25
    disagg: bool = False
    disagg_min_prompt_blocks: int = 1

    def __post_init__(self):
        if self.affinity_block is not None and self.affinity_block < 1:
            raise ValueError(f"affinity_block must be >= 1, got "
                             f"{self.affinity_block}")
        if self.affinity_capacity < 1:
            raise ValueError(f"affinity_capacity must be >= 1, got "
                             f"{self.affinity_capacity}")
        if self.disagg_min_prompt_blocks < 1:
            raise ValueError(f"disagg_min_prompt_blocks must be >= 1, "
                             f"got {self.disagg_min_prompt_blocks}")


class FleetReplica:
    """One replica: a stable id + its engine. Public by design — the
    fleet layer reads engines through their public accessors only."""

    def __init__(self, rid: int, engine):
        self.rid = rid
        self.engine = engine

    def __repr__(self):
        return f"FleetReplica(rid={self.rid})"


class FleetRouter:
    """Prefix-affinity router over N homogeneous engine replicas."""

    def __init__(self, factory: Callable, replicas: int = 1,
                 config: Optional[FleetConfig] = None,
                 autoscale: Optional[AutoscaleConfig] = None,
                 registry: Optional[MetricsRegistry] = None,
                 name: str = "fleet"):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self._factory = factory
        self.config = config if config is not None else FleetConfig()
        self._autoscaler = (FleetAutoscaler(autoscale)
                            if autoscale is not None else None)
        if self._autoscaler is not None \
                and replicas < self._autoscaler.config.min_replicas:
            replicas = self._autoscaler.config.min_replicas
        self._label = name
        self.membership = FleetMembership(self.config.membership_root,
                                          ttl=self.config.lease_ttl_s)
        self._mu = threading.RLock()
        self._replicas: "OrderedDict[int, FleetReplica]" = OrderedDict()
        self._next_rid = 0
        #: leading-block fingerprint -> owning replica id (LRU-bounded)
        self._affinity: "OrderedDict[Tuple, int]" = OrderedDict()
        self._block: Optional[int] = self.config.affinity_block
        self._started = False
        self._stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None
        self.migrations = 0
        self.migrated_requests = 0
        self.scale_events = 0
        #: every replica trace identity ("label#rN") ever fronted,
        #: dead ones included — the timeline filter must keep showing
        #: a dead replica's serving events after the router dropped it
        self._engine_labels: set = set()
        self._register_metrics(registry)
        for _ in range(replicas):
            self._add_replica()

    # ------------------------------------------------------------------
    # telemetry
    # ------------------------------------------------------------------
    def _register_metrics(self, registry) -> None:
        r = registry or global_registry()
        lab = dict(fleet=self._label)
        r.gauge(FLEET_REPLICAS, "Live replicas behind the fleet router",
                ("fleet",)).set_function(
            scrape_probe(self, lambda s: len(s.replicas())), **lab)
        r.gauge(FLEET_GENERATION, "Fleet membership generation",
                ("fleet",)).set_function(
            scrape_probe(self, lambda s: s.membership.generation), **lab)
        self._routed = r.counter(
            FLEET_ROUTED, "Requests routed, by replica",
            ("fleet", "replica"))
        self._affinity_hits = r.counter(
            FLEET_AFFINITY_HITS, "Placements that followed a warm "
            "prefix-affinity mapping", ("fleet",)).labels(**lab)
        self._affinity_misses = r.counter(
            FLEET_AFFINITY_MISSES, "Placements that fell back to "
            "least-loaded scoring", ("fleet",)).labels(**lab)
        self._migrations_c = r.counter(
            FLEET_MIGRATIONS, "Live migrations, by cause",
            ("fleet", "cause"))
        for cause in (mig.CAUSE_DEATH, mig.CAUSE_SCALE_IN,
                      mig.CAUSE_OVERLOAD):
            self._migrations_c.labels(fleet=self._label, cause=cause)
        self._migrated_c = r.counter(
            FLEET_MIGRATED_REQUESTS, "Requests re-admitted on another "
            "replica by live migration", ("fleet",)).labels(**lab)
        self._dead_c = r.counter(
            FLEET_DEAD_REPLICAS, "Replicas declared dead (health down "
            "or lease expired)", ("fleet",)).labels(**lab)
        self._scale_c = r.counter(
            FLEET_SCALE_EVENTS, "Autoscaler actions, by direction",
            ("fleet", "direction"))
        for d in ("out", "in"):
            self._scale_c.labels(fleet=self._label, direction=d)

    # ------------------------------------------------------------------
    # replica lifecycle
    # ------------------------------------------------------------------
    def _add_replica(self, direction: Optional[str] = None
                     ) -> FleetReplica:
        with self._mu:
            rid = self._next_rid
            self._next_rid += 1
        engine = self._factory(rid)
        # factory-built replicas share the default model label: stamp
        # the rid so request traces name WHICH replica served them
        # (engine.trace_identity -> "label#rN")
        engine.replica_tag = rid
        rep = FleetReplica(rid, engine)
        with self._mu:
            self._replicas[rid] = rep
            members = list(self._replicas)
            self._engine_labels.add(engine.trace_identity)
        self.membership.join(rid)
        self.membership.publish(members, publisher=rid)
        if self._started:
            engine.start()
        if direction is not None:
            self.scale_events += 1
            self._scale_c.labels(fleet=self._label,
                                 direction=direction).inc()
            emit_event("fleet", "scale_out", fleet=self._label,
                       replica=rid)
        emit_event("fleet", "replica_join", fleet=self._label,
                   replica=rid, generation=self.membership.generation,
                   live=len(members))
        log.info("fleet %s: replica %d joined (generation %d, %d live)",
                 self._label, rid, self.membership.generation,
                 len(members))
        return rep

    def _drop_replica(self, rep: FleetReplica) -> None:
        with self._mu:
            self._replicas.pop(rep.rid, None)
            members = list(self._replicas)
            # drop the dead owner's affinity mappings: the next request
            # per fingerprint re-places (and re-warms) on a survivor
            stale = [fp for fp, rid in self._affinity.items()
                     if rid == rep.rid]
            for fp in stale:
                del self._affinity[fp]
        self.membership.leave(rep.rid)
        self.membership.publish(members)

    def replicas(self) -> List[FleetReplica]:
        with self._mu:
            return list(self._replicas.values())

    def replica(self, rid: int) -> Optional[FleetReplica]:
        with self._mu:
            return self._replicas.get(rid)

    # ------------------------------------------------------------------
    # placement
    # ------------------------------------------------------------------
    def _default_block(self) -> int:
        """Fingerprint block length: the replicas' KV page size (one
        cacheable block — affinity tracks exactly what the prefix cache
        can reuse), 16 tokens unpaged. Resolved once from the first
        replica's health payload."""
        if self._block is None:
            block = 16
            for rep in self.replicas():
                kv = rep.engine.health().get("kv_pages")
                if kv:
                    block = int(kv["page_size"])
                break
            self._block = block
        return self._block

    def _fingerprint(self, prompt) -> Optional[Tuple]:
        """The leading full block of the prompt, or None when it has no
        full block to share (too short to ever hit the prefix cache)."""
        if not self.config.affinity:
            return None
        bs = self._default_block()
        if len(prompt) <= bs:
            return None
        return tuple(prompt[:bs])

    def _score(self, rep: FleetReplica) -> float:
        """Least-loaded placement score (lower = better): occupancy +
        queue backlog per slot, discounted by free KV headroom. Reads
        the engine's narrow ``load_stats()`` payload — the hot submit
        path must not build the full health() dict per candidate."""
        s = rep.engine.load_stats()
        load = (s["queue_depth"] + s["active_slots"]) \
            / max(1, s["slots"])
        return load - self.config.free_weight * s["free_page_frac"]

    def _place(self, prompt, exclude=()) -> FleetReplica:
        """Pick the replica for `prompt`: the affinity owner when it is
        live and admitting, else the best-scoring live replica (and the
        fingerprint adopts it). Raises NoReplicaAvailable when nothing
        healthy remains."""
        with self._mu:
            cands = [r for r in self._replicas.values()
                     if r.rid not in exclude and r.engine.is_healthy()]
            if not cands:
                raise NoReplicaAvailable(
                    f"fleet {self._label}: no healthy replica "
                    f"(generation {self.membership.generation})")
            ready = [r for r in cands if r.engine.is_ready()] or cands
            fp = self._fingerprint(prompt)
            if fp is not None:
                rid = self._affinity.get(fp)
                if rid is not None:
                    rep = self._replicas.get(rid)
                    if rep is not None and rep in ready:
                        self._affinity.move_to_end(fp)
                        self._affinity_hits.inc()
                        return rep
            best = min(ready, key=self._score)
            if fp is not None:
                self._affinity[fp] = best.rid
                self._affinity.move_to_end(fp)
                while len(self._affinity) > self.config.affinity_capacity:
                    self._affinity.popitem(last=False)
                self._affinity_misses.inc()
            return best

    # ------------------------------------------------------------------
    # the submit/stream API (mirrors GenerationEngine.submit)
    # ------------------------------------------------------------------
    def submit(self, prompt, steps: int, **kw):
        """Route one prompt to a replica and submit it there; returns
        the replica engine's ``GenerationStream`` handle (same contract
        as ``GenerationEngine.submit``). A replica that refuses —
        drained/broken (``EngineShutdown``), queue-full, or
        overload-rejecting — is excluded and the request re-placed;
        only when EVERY live replica refuses does the last refusal
        propagate."""
        prompt = [int(t) for t in prompt]
        exclude: set = set()
        last: Optional[BaseException] = None
        while True:
            try:
                rep = self._place(prompt, exclude)
            except NoReplicaAvailable as e:
                flightrecorder.maybe_dump(
                    "no_replica", error=last if last is not None else e,
                    health=self.health(),
                    extra={"excluded": sorted(exclude)})
                if last is not None:
                    raise last
                raise
            try:
                handle = rep.engine.submit(prompt, steps, **kw)
            except (EngineShutdown, ServingQueueFull,
                    ServingOverloaded) as e:
                exclude.add(rep.rid)
                last = e
                continue
            self._routed.labels(fleet=self._label,
                                replica=str(rep.rid)).inc()
            return handle

    # ------------------------------------------------------------------
    # detection / rebalance / scaling (the poll cycle)
    # ------------------------------------------------------------------
    def poll(self, now: Optional[float] = None) -> dict:
        """One control-plane cycle: declare dead replicas (health down
        or lease expired) and migrate their ledgers to survivors;
        rebalance a sustained queue backlog onto an idle replica; run
        one autoscaler tick. Returns a summary dict (tests/bench
        introspection)."""
        now = time.monotonic() if now is None else now
        out = {"dead": [], "migrated": 0, "rebalanced": 0,
               "respawned": [], "scaled": None}
        reps = self.replicas()
        expired = set(self.membership.expired([r.rid for r in reps]))
        dead = [rep for rep in reps
                if not rep.engine.is_healthy() or rep.rid in expired]
        if dead and self._autoscaler is not None:
            # re-establish the autoscaler's floor BEFORE migrating, so
            # the dead replicas' ledgers have somewhere to land — else
            # losing the last replica would fail every in-flight stream
            # and brick the fleet (signals over zero replicas can never
            # read as pressure, so scale-out would never fire again)
            floor = self._autoscaler.config.min_replicas
            for _ in range(max(0, floor - (len(reps) - len(dead)))):
                out["respawned"].append(self._add_replica().rid)
        for rep in dead:
            out["dead"].append(rep.rid)
            self._dead_c.inc()
            emit_event("fleet", "replica_dead", fleet=self._label,
                       replica=rep.rid,
                       lease_expired=rep.rid in expired)
            report = self._migrate_from(rep, mig.CAUSE_DEATH)
            out["migrated"] += report.admitted
        if self.config.rebalance_queue_wait_s is not None:
            out["rebalanced"] = self._rebalance()
        if self._autoscaler is not None:
            out["scaled"] = self._autoscale_tick(now)
        return out

    def _migrate_from(self, rep: FleetReplica,
                      cause: str) -> mig.MigrationReport:
        """Export `rep`'s whole ledger, drop it from the fleet, and
        re-admit every entry through placement (affinity first — a
        migrated stream goes where its prefix is warm).

        The export waits on the replica's engine lock only BOUNDEDLY:
        a lease-expired replica may be hung INSIDE a dispatch with the
        lock held, and the poll thread is the whole control plane — it
        must not deadlock on one wedged engine. On timeout the replica
        is dropped from routing with nothing exported (a wedged
        in-process engine's streams cannot be reached from outside its
        lock; a multi-process deployment re-admits from persisted
        ledger payloads or client resubmission)."""
        try:
            entries = rep.engine.detach_ledger(lock_timeout=5.0)
        except TimeoutError:
            log.error(
                "fleet %s: replica %d is wedged (engine lock held "
                "through the detach timeout) — dropping it from "
                "routing with its ledger unexported", self._label,
                rep.rid)
            self._drop_replica(rep)
            self.migrations += 1
            self._migrations_c.labels(fleet=self._label,
                                      cause=cause).inc()
            emit_event("fleet", "migration", fleet=self._label,
                       source=rep.rid, cause=cause, wedged=True,
                       exported=0, admitted=0)
            return mig.MigrationReport(cause=cause, source=rep.rid)
        self._drop_replica(rep)
        report = mig.readmit_entries(entries, self._place, cause,
                                     source=rep.rid)
        self.migrations += 1
        self.migrated_requests += report.admitted
        self._migrations_c.labels(fleet=self._label, cause=cause).inc()
        self._migrated_c.inc(report.admitted)
        emit_event("fleet", "migration", fleet=self._label,
                   source=rep.rid, cause=cause,
                   exported=report.exported, admitted=report.admitted,
                   failed=report.failed,
                   targets={str(k): v
                            for k, v in report.per_target.items()})
        if report.failed:
            # in-flight work just died for want of a replica: the same
            # post-mortem trigger as a submit-side NoReplicaAvailable
            flightrecorder.maybe_dump(
                "no_replica", health=self.health(),
                traces=[e.request.trace for e in entries],
                extra={"cause": cause, "source": rep.rid,
                       "failed": report.failed})
        rep.engine.shutdown()     # nothing in flight: a clean stop
        return report

    def _rebalance(self) -> int:
        """Overload rebalance: a replica whose oldest queued request
        outwaited the threshold hands its queued tail to a replica
        scoring at least the margin lower. Actives never move here —
        their KV is warm where they sit. At most ONE source per poll
        cycle: moved requests keep their original submit times, so a
        same-cycle second pass would read the target as instantly
        overloaded and ping-pong the tail straight back."""
        moved = 0
        for rep in self.replicas():
            if not rep.engine.is_healthy():
                continue
            snap = rep.engine.queue_snapshot()
            if not snap.depth or snap.oldest_wait_s is None or \
                    snap.oldest_wait_s < self.config.rebalance_queue_wait_s:
                continue
            src_score = self._score(rep)
            # a target must be able to actually SEAT moved work (free
            # slots and an empty queue), and the move is CAPPED at its
            # free-slot count: migrated requests keep their original
            # submit times, so handing a target more than it can seat
            # would read as an over-threshold source on the NEXT poll
            # and bounce the tail straight back — cross-cycle ping-pong
            scored = []
            for r in self.replicas():
                if r.rid == rep.rid or not r.engine.is_healthy() \
                        or not r.engine.is_ready():
                    continue
                stats = r.engine.load_stats()
                if stats["queue_depth"] == 0 \
                        and stats["active_slots"] < stats["slots"]:
                    scored.append((self._score(r), r,
                                   stats["slots"]
                                   - stats["active_slots"]))
            if not scored:
                continue
            score_best, best, free_slots = min(scored,
                                               key=lambda t: t[0])
            if src_score - score_best \
                    < self.config.rebalance_load_margin:
                continue
            entries = rep.engine.detach_queued(max_n=free_slots)
            if not entries:
                continue
            # the detached tail goes to the VALIDATED target, not back
            # through affinity-first placement — a fingerprint mapping
            # to some third, loaded replica would force-requeue there
            # and re-create the ping-pong the cap exists to prevent
            # (placement is only the fallback if `best` dies mid-move)
            report = mig.readmit_entries(
                entries, lambda p, ex, _t=best, _skip=rep.rid:
                (_t if _t.rid not in ex and _t.engine.is_healthy()
                 else self._place(p, set(ex) | {_skip})),
                mig.CAUSE_OVERLOAD, source=rep.rid)
            self.migrations += 1
            self.migrated_requests += report.admitted
            self._migrations_c.labels(fleet=self._label,
                                      cause=mig.CAUSE_OVERLOAD).inc()
            self._migrated_c.inc(report.admitted)
            emit_event("fleet", "rebalance", fleet=self._label,
                       source=rep.rid, target=best.rid,
                       moved=report.admitted)
            moved += report.admitted
            break
        return moved

    def _signals(self) -> FleetSignals:
        reps = [r for r in self.replicas() if r.engine.is_healthy()]
        return FleetSignals.collect(
            [r.engine.health() for r in reps],
            [r.engine.queue_snapshot().depth for r in reps])

    def _autoscale_tick(self, now: float) -> Optional[str]:
        signals = self._signals()
        decision = self._autoscaler.decide(signals, now)
        if decision is not None:
            emit_event("fleet", "autoscale", fleet=self._label,
                       decision=decision, replicas=signals.replicas,
                       queued=signals.queued, active=signals.active)
        if decision == "out":
            self._add_replica(direction="out")
        elif decision == "in":
            self.scale_in()
        return decision

    # ------------------------------------------------------------------
    # explicit scaling (the autoscaler's executors, also public API)
    # ------------------------------------------------------------------
    def scale_out(self) -> FleetReplica:
        """Add one replica via the factory (counted as a scale event)."""
        return self._add_replica(direction="out")

    def scale_in(self, rid: Optional[int] = None
                 ) -> Optional[mig.MigrationReport]:
        """Retire one replica — by id, or the best-scoring (emptiest:
        cheapest migration, coldest cache to lose) — draining it
        through ledger migration onto the survivors. Refuses to retire
        the last replica."""
        with self._mu:
            live = [r for r in self._replicas.values()
                    if r.engine.is_healthy()]
            if rid is not None:
                rep = self._replicas.get(rid)
            else:
                rep = min(live, key=self._score) if live else None
            # the victim's ledger needs a HEALTHY survivor to land on:
            # counting registered replicas would let a scale-in retire
            # the only live replica while a dead one pads the count —
            # migration would then fail every in-flight stream
            if rep is None or not any(r.rid != rep.rid for r in live):
                return None
        report = self._migrate_from(rep, mig.CAUSE_SCALE_IN)
        self.scale_events += 1
        self._scale_c.labels(fleet=self._label, direction="in").inc()
        emit_event("fleet", "scale_in", fleet=self._label,
                   replica=rep.rid, moved=report.admitted)
        return report

    # ------------------------------------------------------------------
    # drive (manual mode) / lifecycle
    # ------------------------------------------------------------------
    def step(self) -> bool:
        """One manual cycle over every replica (the deterministic
        test/bench shape). Returns whether any replica made progress."""
        progress = False
        for rep in self.replicas():
            progress = rep.engine.step() or progress
        return progress

    def run_until_idle(self, max_steps: int = 1_000_000) -> int:
        """Drive ``step()`` until the whole fleet is idle, polling the
        control plane whenever progress stalls (so a dead replica's
        migration — or an autoscale action — can resume the trace)."""
        n = 0
        while True:
            if not self.step():
                self.poll()
                if not self.step():
                    return n
            n += 1
            if n >= max_steps:
                raise RuntimeError(f"fleet still busy after {n} steps")

    def warmup(self, **kw) -> "FleetRouter":
        """Warm every replica (manual mode only; see
        ``GenerationEngine.warmup``). Replicas added later by the
        autoscaler should be warmed by the factory instead."""
        for rep in self.replicas():
            rep.engine.warmup(**kw)
        return self

    def start(self) -> "FleetRouter":
        """Deployment shape: every replica's background loop plus the
        router's poll thread."""
        self._started = True
        self._stop.clear()
        for rep in self.replicas():
            rep.engine.start()
        if self._poll_thread is None or not self._poll_thread.is_alive():
            def _run():
                while not self._stop.wait(self.config.poll_interval_s):
                    try:
                        self.poll()
                    except Exception:   # noqa: BLE001 — keep polling
                        log.exception("fleet poll cycle failed")
            self._poll_thread = threading.Thread(
                target=_run, daemon=True, name=f"fleet-{self._label}")
            self._poll_thread.start()
        return self

    def shutdown(self) -> None:
        """Stop the poll thread, every replica, and the membership
        leases. Replica engines fail their in-flight work with
        ``EngineShutdown`` (the no-hung-callers contract)."""
        self._stop.set()
        t = self._poll_thread
        if t is not None and t.is_alive():
            t.join(timeout=2 * self.config.poll_interval_s + 1)
        for rep in self.replicas():
            rep.engine.shutdown()
        self.membership.stop()

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------
    def timeline(self, n: Optional[int] = 100) -> List:
        """This fleet's slice of the process-wide ops timeline, oldest
        first: the router's own ``fleet`` events plus the ``serving``
        lifecycle events of every replica it ever fronted (dead ones
        included — a post-mortem needs the victim's last brownout, not
        just the migration that buried it). Non-mutating snapshot of
        the bounded ring; no lock is held while filtering."""
        with self._mu:
            labels = set(self._engine_labels)
        out = []
        for e in global_event_log().tail(None):
            if e.category == "fleet" \
                    and e.attrs.get("fleet") == self._label:
                out.append(e)
            elif e.category == "serving" \
                    and e.attrs.get("engine") in labels:
                out.append(e)
        if n is not None:
            out = out[-n:]
        return out

    def health(self) -> dict:
        reps = self.replicas()
        return {
            "replicas": {r.rid: r.engine.health() for r in reps},
            "generation": self.membership.generation,
            "affinity_entries": len(self._affinity),
            "migrations": self.migrations,
            "migrated_requests": self.migrated_requests,
            "scale_events": self.scale_events,
            # bounded recent-timeline tail: a live probe sees the last
            # few control-plane actions without the JSONL sink
            "last_events": [
                {"category": e.category, "name": e.name, "wall": e.wall,
                 "attrs": dict(e.attrs)} for e in self.timeline(10)],
        }


# ----------------------------------------------------------------------
# the cross-process router
# ----------------------------------------------------------------------

class _RouteRecord:
    """Router-side bookkeeping for one outstanding cross-process
    request: the LOCAL ``GenerationRequest`` (its handle is the
    caller's stream, and every relayed token accumulates in it — which
    makes it the router's authoritative committed-ids record, usable
    for re-placement with NO cooperation from a dead replica), the
    serving replica + ``attempt`` fence, and the last journaled
    post-step rng state (the other half of the re-prime pair)."""

    __slots__ = ("request", "req_id", "rid", "attempt", "rng_state",
                 "excluded", "revoked", "phase")

    def __init__(self, request: GenerationRequest, req_id: str):
        self.request = request
        self.req_id = req_id
        self.rid: Optional[int] = None
        self.attempt = 0
        self.rng_state: Optional[dict] = None
        self.excluded: set = set()   # rids that NACKed this request
        self.revoked = False         # caller-cancel already forwarded
        #: routing phase (observability): "direct" unified placement,
        #: "prefill" awaiting EV_PREFILLED, "decode" handed off
        self.phase = "direct"


#: remote failure reconstruction: a journaled ``done`` event carries
#: ``repr(error)``; the relay rebuilds the matching serving error type
#: so a caller's except clauses work identically cross-process
_REMOTE_ERRORS = {cls.__name__: cls for cls in
                  (EngineShutdown, InferenceTimeout,
                   NoReplicaAvailable, RequestCancelled,
                   ServingOverloaded, ServingQueueFull)}


def _rebuild_error(text: Optional[str]) -> Optional[BaseException]:
    """``repr(exc)`` from a journal event -> a raisable exception of
    the same serving type (RuntimeError for anything unrecognized —
    the message still carries the original repr's payload)."""
    if text is None:
        return None
    name, _, rest = text.partition("(")
    msg = rest[:-1] if rest.endswith(")") else rest
    if len(msg) >= 2 and msg[0] in "'\"" and msg[-1] == msg[0]:
        msg = msg[1:-1]
    return _REMOTE_ERRORS.get(name, RuntimeError)(msg)


class ProcessFleetRouter:
    """Out-of-process fleet router: replicas are OS processes, reached
    only through the shared filesystem.

    The :class:`FleetRouter` holds engine references; this router holds
    NONE. Each replica is a ``serving/fleet/agent.ReplicaAgent`` in its
    own process (``serving/fleet/worker.py`` entrypoint), and the
    router's whole view of the fleet is

    - **discovery**: live lease ranks stamped ``role="replica"``
      (``membership.AGENT_ROLE``) in ``<root>/leases/`` — a replica
      that was ``kill -9``'d simply stops beating;
    - **placement**: the agents' atomic-rename status files (load,
      health, KV page size) score the same affinity-first /
      least-loaded formula as the in-process router;
    - **submit**: a LOCAL ``GenerationRequest`` is built (its handle is
      what the caller iterates), captured as a
      ``RequestLedgerEntry.payload()`` and written into the chosen
      agent's mailbox as an ``admit`` command (atomic rename;
      at-least-once — the agent dedupes by ``(request id, attempt)``);
    - **relay**: agent journals stream committed-token batches back;
      :meth:`relay` pushes them into the local handles
      (``relay_token`` — index-deduped, so a re-placed survivor
      re-emitting an overlap is harmless) and adopts each line's
      post-step rng state;
    - **death -> re-place**: an expired lease (or an unhealthy status)
      declares the replica dead; its outstanding requests are
      re-captured FROM THE LOCAL HANDLES (committed ids) + the last
      journaled rng state, fenced with ``attempt+1`` (a revoke goes to
      the old mailbox first, so a stalled-lease-but-ALIVE process
      cancels instead of double-serving), and re-admitted on survivors
      through the same PR 13 re-prime path — every stream completes
      bit-identically to an unperturbed single-engine run, with no
      cooperation from the corpse (test-pinned, ``kill -9`` included).

    Drive it manually (:meth:`relay` / :meth:`poll` — deterministic
    tests drive the agents in-process too) or :meth:`start` the poll
    thread against real worker processes."""

    def __init__(self, root: str, *,
                 config: Optional[FleetConfig] = None,
                 registry: Optional[MetricsRegistry] = None,
                 name: str = "procfleet",
                 chaos: Optional[object] = None):
        self.root = root
        self.config = config if config is not None else FleetConfig()
        self._label = name
        #: mailbox chaos seam, forwarded to every send-side Mailbox
        #: (resilience/chaos.py transport injectors)
        self.chaos = chaos
        paths = transport.fleet_paths(root)
        self.membership = FleetMembership(
            paths["leases"], ttl=self.config.lease_ttl_s,
            role=AGENT_ROLE)
        #: the prefill pool's discovery view (same lease dir, disjoint
        #: role stamp) — empty-pool reads make disagg degrade to
        #: unified placement instead of failing
        self.prefill_membership = FleetMembership(
            paths["leases"], ttl=self.config.lease_ttl_s,
            role=PREFILL_ROLE)
        self.status = transport.AgentStatus(root)
        self.journal = transport.JournalReader(root)
        self._mu = threading.RLock()
        self._mail: Dict[int, transport.Mailbox] = {}
        self._routes: Dict[str, _RouteRecord] = {}
        self._affinity: "OrderedDict[Tuple, int]" = OrderedDict()
        self._block: Optional[int] = self.config.affinity_block
        self._stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None
        self.replaced_requests = 0
        self.dead_replicas = 0
        self.prefill_routed = 0
        self.locality_hits = 0
        self._corrupt_seen = 0
        self._register_metrics(registry)

    # ------------------------------------------------------------------
    # telemetry
    # ------------------------------------------------------------------
    def _register_metrics(self, registry) -> None:
        r = registry or global_registry()
        lab = dict(fleet=self._label)
        r.gauge(FLEET_REPLICAS, "Live replicas behind the fleet router",
                ("fleet",)).set_function(
            scrape_probe(self, lambda s: len(s.live_replicas())), **lab)
        r.gauge(FLEET_GENERATION, "Fleet membership generation",
                ("fleet",)).set_function(
            scrape_probe(self, lambda s: s.membership.generation), **lab)
        self._routed = r.counter(
            FLEET_ROUTED, "Requests routed, by replica",
            ("fleet", "replica"))
        self._affinity_hits = r.counter(
            FLEET_AFFINITY_HITS, "Placements that followed a warm "
            "prefix-affinity mapping", ("fleet",)).labels(**lab)
        self._affinity_misses = r.counter(
            FLEET_AFFINITY_MISSES, "Placements that fell back to "
            "least-loaded scoring", ("fleet",)).labels(**lab)
        self._dead_c = r.counter(
            FLEET_DEAD_REPLICAS, "Replicas declared dead (health down "
            "or lease expired)", ("fleet",)).labels(**lab)
        self._relayed_c = r.counter(
            FLEET_RELAYED_TOKENS, "Committed tokens relayed from agent "
            "journals into local stream handles", ("fleet",)
        ).labels(**lab)
        self._replaced_c = r.counter(
            FLEET_REPLACED_REQUESTS, "In-flight requests re-placed "
            "onto a survivor after replica death or nack",
            ("fleet",)).labels(**lab)
        self._corrupt_c = r.counter(
            FLEET_TRANSPORT_CORRUPT_LINES, "Torn/undecodable journal "
            "lines skipped by the relay's reader",
            ("fleet",)).labels(**lab)

    # ------------------------------------------------------------------
    # discovery + placement (status files instead of engine accessors)
    # ------------------------------------------------------------------
    def _mailbox(self, rid: int) -> transport.Mailbox:
        with self._mu:
            box = self._mail.get(rid)
            if box is None:
                box = transport.Mailbox(self.root, rid,
                                        chaos=self.chaos)
                self._mail[rid] = box
            return box

    def live_replicas(self) -> List[int]:
        """Replica agents with a live lease — the discovery read (no
        engine references anywhere in this router)."""
        return sorted(self.membership.live_ranks())

    def _candidates(self, exclude) -> List[Tuple[int, dict]]:
        statuses = self.status.read_all()
        out = []
        for rid in self.live_replicas():
            if rid in exclude:
                continue
            st = statuses.get(rid)
            # no status yet = still booting; unhealthy = don't place
            if st is None or not st.get("healthy", False):
                continue
            # defensive: the rid namespace is shared across roles, so a
            # misconfigured deployment could leak a prefill agent's
            # status here — never decode on one
            if st.get("role") == "prefill":
                continue
            out.append((rid, st))
        return out

    def _default_block(self) -> int:
        """Affinity fingerprint length: the agents' advertised KV page
        size (16 when unpaged/unknown). Resolved once a status exists,
        like the in-process router resolves it from the first
        replica's health payload."""
        if self._block is None:
            statuses = sorted(self.status.read_all().items())
            if statuses:
                self._block = int(
                    statuses[0][1].get("kv_page_size", 16))
        return self._block if self._block is not None else 16

    def _fingerprint(self, prompt) -> Optional[Tuple]:
        if not self.config.affinity:
            return None
        bs = self._default_block()
        if len(prompt) <= bs:
            return None
        return tuple(prompt[:bs])

    def _score(self, st: dict) -> float:
        """The in-process router's least-loaded formula over a STATUS
        payload: occupancy + backlog per slot, discounted by free KV
        headroom (``load`` is the agent's ``load_stats()`` echo)."""
        load = st.get("load") or {}
        occ = (load.get("queue_depth", 0) + load.get("active_slots", 0)) \
            / max(1, load.get("slots", 1))
        return occ - self.config.free_weight \
            * load.get("free_page_frac", 0.0)

    def _place(self, prompt, exclude=()) -> int:
        """Pick the replica id for `prompt`: affinity owner when live
        and routable, else best status score (rid breaks score ties —
        the choice must be deterministic across router restarts).
        Raises NoReplicaAvailable when nothing routable remains."""
        with self._mu:
            cands = self._candidates(exclude)
            if not cands:
                raise NoReplicaAvailable(
                    f"fleet {self._label}: no routable replica agent "
                    f"(live {self.live_replicas()}, "
                    f"excluded {sorted(exclude)})")
            ready = [c for c in cands if c[1].get("ready")] or cands
            fp = self._fingerprint(prompt)
            if fp is not None:
                rid = self._affinity.get(fp)
                if rid is not None and any(r == rid for r, _ in ready):
                    self._affinity.move_to_end(fp)
                    self._affinity_hits.inc()
                    return rid
            best = min(ready,
                       key=lambda c: (self._score(c[1]), c[0]))[0]
            if fp is not None:
                self._affinity[fp] = best
                self._affinity.move_to_end(fp)
                while len(self._affinity) \
                        > self.config.affinity_capacity:
                    self._affinity.popitem(last=False)
                self._affinity_misses.inc()
            return best

    # ------------------------------------------------------------------
    # the submit/stream API (mirrors GenerationEngine.submit)
    # ------------------------------------------------------------------
    def submit(self, prompt, steps: int, *, temperature: float = 1.0,
               top_k: Optional[int] = None,
               top_p: Optional[float] = None,
               stop_tokens=(), rng=None,
               timeout: Optional[float] = None, priority: int = 0):
        """Route one prompt to a replica PROCESS; returns a local
        ``GenerationStream`` the relay feeds (same caller contract as
        ``GenerationEngine.submit`` — iterate it, ``result()`` it,
        ``cancel()`` it). The deadline stays anchored on THIS process's
        monotonic clock; the wire form carries remaining budget."""
        prompt = [int(t) for t in prompt]
        deadline = None if timeout is None else \
            time.monotonic() + float(timeout)
        req = GenerationRequest(
            prompt, steps, temperature=temperature, top_k=top_k,
            top_p=top_p, stop_tokens=stop_tokens, rng=rng,
            deadline=deadline, priority=priority)
        rec = _RouteRecord(req, uuid.uuid4().hex)
        with self._mu:
            self._routes[rec.req_id] = rec
        prefill_rid = self._place_prefill(prompt) \
            if self.config.disagg else None
        if prefill_rid is not None:
            self._send_prefill(rec, prefill_rid)
        else:
            self._send_to(rec, self._place(prompt))
        return req.handle

    # -- the disaggregated path (prefill pool first, then decode) ------
    def _prefill_candidates(self) -> List[Tuple[int, dict]]:
        """Routable prefill agents: live ``role="prefill"`` lease plus
        a healthy status file (the pool's analogue of
        :meth:`_candidates`)."""
        statuses = self.status.read_all()
        out = []
        for rid in sorted(self.prefill_membership.live_ranks()):
            st = statuses.get(rid)
            if st is None or not st.get("healthy", False):
                continue
            out.append((rid, st))
        return out

    def _place_prefill(self, prompt) -> Optional[int]:
        """Pick a prefill agent for `prompt`, or None when the request
        should go direct: short prompts (fewer USABLE full blocks than
        ``disagg_min_prompt_blocks`` — the last token is always primed
        by decode, hence ``(len - 1) // block``) ship nothing worth the
        hop, and an empty/unhealthy pool degrades to unified placement
        rather than queueing behind a ghost."""
        blocks = (len(prompt) - 1) // self._default_block()
        if blocks < self.config.disagg_min_prompt_blocks:
            return None
        with self._mu:
            cands = self._prefill_candidates()
            if not cands:
                return None
            ready = [c for c in cands if c[1].get("ready")] or cands
            return min(ready,
                       key=lambda c: (self._score(c[1]), c[0]))[0]

    def _send_prefill(self, rec: _RouteRecord, rid: int) -> None:
        """Mail the request to prefill agent `rid` as a
        ``CMD_PREFILL``; the stream stays parked on this record until
        the agent's ``EV_PREFILLED`` (first token + rng + page digests)
        hands it off to a decode replica."""
        rec.rid = rid
        rec.phase = "prefill"
        entry = RequestLedgerEntry.capture(rec.request, "queued")
        self._mailbox(rid).send({
            "kind": transport.CMD_PREFILL, "req": rec.req_id,
            "attempt": rec.attempt, "entry": entry.payload()})
        self.prefill_routed += 1
        self._routed.labels(fleet=self._label,
                            replica=str(rid)).inc()
        emit_event("transport", "route_prefill", fleet=self._label,
                   replica=rid, req=rec.req_id, attempt=rec.attempt)

    def _send_to(self, rec: _RouteRecord, rid: int) -> None:
        """Capture the LOCAL request as a ledger payload and mail it to
        `rid` under the record's current attempt fence."""
        rec.rid = rid
        rec.phase = "decode" if rec.request.streamed else "direct"
        phase = "active" if rec.request.streamed else "queued"
        entry = RequestLedgerEntry.capture(rec.request, phase)
        self._mailbox(rid).send({
            "kind": transport.CMD_ADMIT, "req": rec.req_id,
            "attempt": rec.attempt, "entry": entry.payload()})
        self._routed.labels(fleet=self._label,
                            replica=str(rid)).inc()
        emit_event("transport", "route", fleet=self._label,
                   replica=rid, req=rec.req_id, attempt=rec.attempt,
                   streamed=entry.streamed)

    # ------------------------------------------------------------------
    # the relay (journal -> local handles)
    # ------------------------------------------------------------------
    def relay(self) -> int:
        """Drain every agent journal and apply the events to the local
        stream handles; forward any caller-side cancels as revoke
        commands. Returns the number of events applied."""
        with self._mu:
            rids = {rec.rid for rec in self._routes.values()
                    if rec.rid is not None}
        rids.update(self.live_replicas())
        rids.update(self.prefill_membership.live_ranks())
        n = 0
        for rid in sorted(rids):
            for ev in self.journal.poll(rid):
                n += 1
                self._apply_event(rid, ev)
        # promote freshly detected torn/undecodable journal lines from
        # the reader's bare attribute into the metrics registry (the
        # health() field stays — dashboards scrape, probes poll)
        newc = self.journal.corrupt - self._corrupt_seen
        if newc > 0:
            self._corrupt_seen = self.journal.corrupt
            self._corrupt_c.inc(newc)
        self._propagate_cancels()
        return n

    def _apply_event(self, rid: int, ev: dict) -> None:
        req_id = str(ev.get("req"))
        attempt = int(ev.get("attempt", 0))
        with self._mu:
            rec = self._routes.get(req_id)
        if rec is None or rec.rid != rid or rec.attempt != attempt:
            return    # stale fence: a revoked attempt kept talking
        handle = rec.request.handle
        kind = ev.get("kind")
        if kind == transport.EV_TOK:
            start = int(ev.get("start", 0))
            toks = [int(t) for t in ev.get("toks", ())]
            for i, tok in enumerate(toks):
                # absolute-index dedupe: a survivor bit-identically
                # regenerating tokens the corpse already published
                # re-emits an overlap; only the tip extends the handle
                if start + i == len(handle.generated):
                    handle.relay_token(tok)
                    self._relayed_c.inc()
            if start + len(toks) == len(handle.generated):
                # this line's post-step rng matches OUR tip exactly:
                # adopt it as the re-prime state for a later death
                rec.rng_state = ev.get("rng")
        elif kind == transport.EV_DONE:
            handle.relay_finish(str(ev.get("reason") or "stop"),
                                error=_rebuild_error(ev.get("error")))
            with self._mu:
                self._routes.pop(req_id, None)
        elif kind == transport.EV_PREFILLED:
            self._apply_prefilled(rec, rid, ev)
        elif kind == transport.EV_NACK:
            # the target refused the admission (shutting down, or a
            # payload it could not decode): try the rest of the fleet,
            # excluding every nacker so a persistent refusal converges
            # on NoReplicaAvailable instead of ping-ponging
            rec.excluded.add(rid)
            emit_event("transport", "nack", fleet=self._label,
                       replica=rid, req=req_id, error=ev.get("error"))
            self._replace_record(rec, rec.excluded,
                                 cause=mig.CAUSE_DEATH, source=rid)

    def _apply_prefilled(self, rec: _RouteRecord, rid: int,
                         ev: dict) -> None:
        """Prefill handoff: relay the drawn first token, adopt the
        post-draw rng, then re-place the (now streamed) request on a
        decode replica scored by page locality. The decode admission
        re-primes ``ids[:-1]`` — exactly the prompt — against the
        shipped pages, so nothing is drawn twice and the stream stays
        bit-identical to unified serving."""
        handle = rec.request.handle
        tok = ev.get("tok")
        if tok is not None and not handle.generated:
            handle.relay_token(int(tok))
            self._relayed_c.inc()
        if ev.get("rng") is not None:
            rec.rng_state = ev.get("rng")
        if ev.get("done"):
            # the whole request finished inside prefill (stop token on
            # the first draw, or a one-step request)
            handle.relay_finish(str(ev.get("reason") or "stop"),
                                error=_rebuild_error(ev.get("error")))
            with self._mu:
                self._routes.pop(rec.req_id, None)
            return
        req = rec.request
        if rec.rng_state is not None:
            req.rng.bit_generator.state = rec.rng_state
        digests = [str(d) for d in ev.get("digests") or ()]
        try:
            target = self._place_by_locality(req.prompt, digests,
                                             rec.excluded)
        except NoReplicaAvailable as e:
            handle.relay_finish("error", e)
            with self._mu:
                self._routes.pop(rec.req_id, None)
            return
        # attempt bump fences out anything the prefill agent might
        # still journal under the old attempt
        rec.attempt += 1
        self._send_to(rec, target)
        emit_event("transport", "prefill_handoff", fleet=self._label,
                   req=rec.req_id, source=rid, target=target,
                   blocks=len(digests))

    def _place_by_locality(self, prompt, digests, exclude) -> int:
        """Decode placement for a prefilled stream: longest leading run
        of the shipped chain digests already sitting in a candidate's
        advertised prefix cache wins (those pages re-prime without a
        store read); score + rid break ties, so with no holder anywhere
        this degrades to plain least-loaded placement."""
        with self._mu:
            cands = self._candidates(exclude)
            if not cands:
                raise NoReplicaAvailable(
                    f"fleet {self._label}: no routable decode replica "
                    f"for prefilled stream (live "
                    f"{self.live_replicas()}, "
                    f"excluded {sorted(exclude)})")
            ready = [c for c in cands if c[1].get("ready")] or cands

            def key(c):
                advset = set(c[1].get("prefix_digests") or ())
                run = 0
                for d in digests:
                    if d not in advset:
                        break
                    run += 1
                return (-run, self._score(c[1]), c[0])

            best = min(ready, key=key)
            if digests and -key(best)[0] > 0:
                self.locality_hits += 1
            return best[0]

    def _propagate_cancels(self) -> None:
        with self._mu:
            recs = [r for r in self._routes.values()
                    if r.request.handle.cancelled and not r.revoked
                    and not r.request.handle.done
                    and r.rid is not None]
            for rec in recs:
                rec.revoked = True
        for rec in recs:
            self._mailbox(rec.rid).send({
                "kind": transport.CMD_REVOKE, "req": rec.req_id,
                "attempt": rec.attempt})

    # ------------------------------------------------------------------
    # death detection -> corpse-free re-placement
    # ------------------------------------------------------------------
    def poll(self) -> dict:
        """One control-plane cycle: relay pending journal events, then
        declare dead agents (lease expired, or status-unhealthy) and
        re-place their outstanding requests onto survivors. Returns a
        summary dict (tests/bench introspection)."""
        out = {"dead": [], "replaced": 0}
        self.relay()
        with self._mu:
            routed = sorted({rec.rid for rec in self._routes.values()
                             if rec.rid is not None})
        if not routed:
            return out
        live = set(self.membership.live_ranks())
        # a request parked on a prefill agent is routed to a rid the
        # decode membership view does NOT cover — union the pool's
        # live set or every healthy prefill agent reads as dead
        live |= set(self.prefill_membership.live_ranks())
        statuses = self.status.read_all()
        for rid in routed:
            st = statuses.get(rid)
            unhealthy = st is not None and not st.get("healthy", True)
            if rid in live and not unhealthy:
                continue
            out["dead"].append(rid)
            self.dead_replicas += 1
            self._dead_c.inc()
            emit_event("fleet", "replica_dead", fleet=self._label,
                       replica=rid, lease_expired=rid not in live)
            out["replaced"] += self._replace_from(rid)
        return out

    def _replace_from(self, rid: int) -> int:
        """Re-place every route on dead replica `rid` — using only
        state on THIS side of the transport (local handles + journaled
        rng), because the corpse cannot be asked for anything."""
        # drain the corpse's journal FIRST: every committed token it
        # managed to publish narrows the regeneration window, and the
        # last tok line's rng state is exactly the re-prime state
        for ev in self.journal.poll(rid):
            self._apply_event(rid, ev)
        with self._mu:
            victims = [rec for rec in self._routes.values()
                       if rec.rid == rid]
            # drop the dead owner's affinity mappings: the next request
            # per fingerprint re-places (and re-warms) on a survivor
            stale = [fp for fp, owner in self._affinity.items()
                     if owner == rid]
            for fp in stale:
                del self._affinity[fp]
        n = 0
        box = self._mailbox(rid)
        for rec in victims:
            # fence FIRST: a stalled-lease-but-ALIVE process must stop
            # serving the old attempt before a survivor starts the new
            # one — its engine cancels on the revoke, and the relay
            # ignores anything it still journals at the old attempt
            box.send({"kind": transport.CMD_REVOKE,
                      "req": rec.req_id, "attempt": rec.attempt})
            n += self._replace_record(rec, {rid} | rec.excluded,
                                      cause=mig.CAUSE_DEATH,
                                      source=rid)
        return n

    def _replace_record(self, rec: _RouteRecord, exclude,
                        cause: str, source) -> int:
        req = rec.request
        if req.handle.done:
            with self._mu:
                self._routes.pop(rec.req_id, None)
            return 0
        state = rec.rng_state
        if state is not None:
            # the LOCAL request's rng never advanced (the remote copy
            # did the drawing): restore the last journaled post-step
            # state so the capture below re-primes bit-identically —
            # (committed ids from the handle, rng from the journal)
            # is exactly the consistency unit one journal line carries
            req.rng.bit_generator.state = state
        try:
            rid = self._place(req.prompt, exclude)
        except NoReplicaAvailable as e:
            # nobody can take it: terminal event on the local handle —
            # every outstanding stream ends on SOME path
            req.handle.relay_finish("error", e)
            with self._mu:
                self._routes.pop(rec.req_id, None)
            return 0
        rec.attempt += 1
        self._send_to(rec, rid)
        mig.record_hop(req, source, rid, cause)
        self.replaced_requests += 1
        self._replaced_c.inc()
        emit_event("transport", "replace", fleet=self._label,
                   req=rec.req_id, source=source, target=rid,
                   cause=cause, attempt=rec.attempt)
        return 1

    # ------------------------------------------------------------------
    # drive / lifecycle
    # ------------------------------------------------------------------
    def step(self) -> bool:
        """One relay cycle (manual drive — the agents are stepped by
        their own processes, or by the test in-process); True while
        any relay event was applied."""
        return self.relay() > 0

    def outstanding(self) -> int:
        with self._mu:
            return len(self._routes)

    def assignments(self) -> Dict[str, Tuple[int, int]]:
        """Outstanding request id -> (replica, attempt) snapshot."""
        with self._mu:
            return {req_id: (rec.rid, rec.attempt)
                    for req_id, rec in self._routes.items()}

    def start(self) -> "ProcessFleetRouter":
        """Background drive: relay + death-check at poll cadence."""
        self._stop.clear()
        if self._poll_thread is None \
                or not self._poll_thread.is_alive():
            def _run():
                while not self._stop.wait(self.config.poll_interval_s):
                    try:
                        self.poll()
                    except Exception:   # noqa: BLE001 — keep polling
                        log.exception(
                            "process-fleet poll cycle failed")
            self._poll_thread = threading.Thread(
                target=_run, daemon=True,
                name=f"procfleet-{self._label}")
            self._poll_thread.start()
        return self

    def shutdown(self, stop_agents: bool = False) -> None:
        """Stop the poll thread and resolve every still-outstanding
        local handle with ``EngineShutdown`` (the no-hung-callers
        contract). With `stop_agents` the live agents are mailed a
        ``shutdown`` command too (the orderly whole-fleet stop — a
        ``kill -9`` test never gets this)."""
        self._stop.set()
        t = self._poll_thread
        if t is not None and t.is_alive():
            t.join(timeout=2 * self.config.poll_interval_s + 1)
        if stop_agents:
            stops = set(self.live_replicas())
            stops |= set(self.prefill_membership.live_ranks())
            for rid in sorted(stops):
                try:
                    self._mailbox(rid).send(
                        {"kind": transport.CMD_SHUTDOWN})
                except OSError:
                    pass
        try:
            self.relay()    # last drain: keep what already finished
        except OSError:
            pass
        with self._mu:
            recs, self._routes = list(self._routes.values()), {}
        for rec in recs:
            rec.request.handle.relay_finish(
                "error", EngineShutdown(
                    "fleet router shut down with the request still "
                    "in flight"))
        self.membership.stop()
        self.prefill_membership.stop()

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------
    def health(self) -> dict:
        with self._mu:
            affinity_entries = len(self._affinity)
        return {
            "live_replicas": self.live_replicas(),
            "prefill_replicas":
                sorted(self.prefill_membership.live_ranks()),
            "statuses": self.status.read_all(),
            "generation": self.membership.generation,
            "outstanding": self.outstanding(),
            "replaced_requests": self.replaced_requests,
            "dead_replicas": self.dead_replicas,
            "prefill_routed": self.prefill_routed,
            "locality_hits": self.locality_hits,
            "journal_corrupt_lines": self.journal.corrupt,
            "affinity_entries": affinity_entries,
        }
