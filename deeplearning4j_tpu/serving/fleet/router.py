"""FleetRouter: N GenerationEngine replicas behind one submit API.

The single-engine stack (PRs 5–10) serves one device; the
millions-of-users story needs N replicas behind a router — the L7
analog of DL4J's worker-pooled ParallelInference, built TPU-native
from parts the repo already proved out:

- **Placement** scores replicas by PREFIX-CACHE AFFINITY first: the
  fingerprint of a prompt's leading full block (the system-prompt
  block, sized to the replicas' KV page size) maps to the replica that
  last served it, so requests sharing a system prompt land where their
  prefix pages are warm and prime only their suffix. On an affinity
  miss (or an unavailable owner) placement falls back to least-loaded:
  ``score = (queue_depth + active_slots) / slots − w · free_page_frac``
  over the PUBLIC accessors only (``health()``, ``queue_snapshot()``)
  — the tpulint rule ``replica-local-state-in-router`` holds the fleet
  layer to that seam.
- **Live migration** (``serving/fleet/migration.py``) moves in-flight
  requests between replicas as request-ledger entries
  (``RequestLedgerEntry`` — the PR 9 rebuild payload made public), so
  every stream continues bit-identically on its new replica. Triggers:
  replica death (``is_healthy()`` down, or lease expiry through the
  replica-mode membership ledger), planned scale-in, and sustained
  overload (queued tail rebalanced to an idle replica).
- **Autoscaling** (``serving/fleet/autoscale.py``) turns the existing
  overload/page-pressure/queue signals into scale-out (factory-spawn a
  replica) and scale-in (migrate, then retire the emptiest replica)
  decisions with streak+cooldown hysteresis.

Replicas are assumed HOMOGENEOUS — the ``factory(rid)`` callable
returns engines over identically-parameterized nets (same checkpoint,
same config), which is what makes placement a pure performance choice:
any replica produces bit-identical tokens for any request, so routed
output == single-engine output == one-shot ``sample_stream``
(test-pinned, greedy and sampled, kill-a-replica included).

Drive it manually (``submit()`` + ``step()``/``run_until_idle()`` —
the deterministic test/bench shape; ``poll()`` runs detection/scaling
explicitly) or ``start()`` the replicas' background loops plus the
router's poll thread. See ARCHITECTURE.md "Serving fleet".
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

from deeplearning4j_tpu.monitoring import flightrecorder
from deeplearning4j_tpu.monitoring.events import (
    emit as emit_event, global_event_log)
from deeplearning4j_tpu.monitoring.metrics import (
    MetricsRegistry, global_registry)
from deeplearning4j_tpu.serving.errors import (
    EngineShutdown, NoReplicaAvailable, ServingOverloaded,
    ServingQueueFull)
from deeplearning4j_tpu.serving.fleet import migration as mig
from deeplearning4j_tpu.serving.fleet.autoscale import (
    AutoscaleConfig, FleetAutoscaler, FleetSignals)
from deeplearning4j_tpu.serving.fleet.membership import FleetMembership
from deeplearning4j_tpu.serving.health import (
    FLEET_AFFINITY_HITS, FLEET_AFFINITY_MISSES, FLEET_DEAD_REPLICAS,
    FLEET_GENERATION, FLEET_MIGRATED_REQUESTS, FLEET_MIGRATIONS,
    FLEET_REPLICAS, FLEET_ROUTED, FLEET_SCALE_EVENTS, scrape_probe)

log = logging.getLogger(__name__)

__all__ = ["FleetConfig", "FleetReplica", "FleetRouter"]


@dataclasses.dataclass
class FleetConfig:
    """Router knobs.

    ``affinity`` routes by the leading-block fingerprint;
    ``affinity_block`` is the fingerprint length in tokens (default:
    the replicas' KV page size, so the fingerprint is exactly one
    cacheable block; 16 when unpaged) and ``affinity_capacity`` bounds
    the fingerprint→replica map (LRU). ``free_weight`` is the
    free-page-fraction weight in the least-loaded score.

    ``rebalance_queue_wait_s`` arms overload rebalancing: when a
    replica's oldest queued request has waited at least this long AND
    another replica scores at least ``rebalance_load_margin`` lower,
    the queued tail migrates there (None disables). ``membership_root``
    + ``lease_ttl_s`` enable filesystem replica leases
    (``serving/fleet/membership.py``); ``poll_interval_s`` paces the
    started router's poll thread."""

    affinity: bool = True
    affinity_block: Optional[int] = None
    affinity_capacity: int = 512
    free_weight: float = 0.5
    rebalance_queue_wait_s: Optional[float] = None
    rebalance_load_margin: float = 0.5
    membership_root: Optional[str] = None
    lease_ttl_s: float = 2.0
    poll_interval_s: float = 0.25

    def __post_init__(self):
        if self.affinity_block is not None and self.affinity_block < 1:
            raise ValueError(f"affinity_block must be >= 1, got "
                             f"{self.affinity_block}")
        if self.affinity_capacity < 1:
            raise ValueError(f"affinity_capacity must be >= 1, got "
                             f"{self.affinity_capacity}")


class FleetReplica:
    """One replica: a stable id + its engine. Public by design — the
    fleet layer reads engines through their public accessors only."""

    def __init__(self, rid: int, engine):
        self.rid = rid
        self.engine = engine

    def __repr__(self):
        return f"FleetReplica(rid={self.rid})"


class FleetRouter:
    """Prefix-affinity router over N homogeneous engine replicas."""

    def __init__(self, factory: Callable, replicas: int = 1,
                 config: Optional[FleetConfig] = None,
                 autoscale: Optional[AutoscaleConfig] = None,
                 registry: Optional[MetricsRegistry] = None,
                 name: str = "fleet"):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self._factory = factory
        self.config = config if config is not None else FleetConfig()
        self._autoscaler = (FleetAutoscaler(autoscale)
                            if autoscale is not None else None)
        if self._autoscaler is not None \
                and replicas < self._autoscaler.config.min_replicas:
            replicas = self._autoscaler.config.min_replicas
        self._label = name
        self.membership = FleetMembership(self.config.membership_root,
                                          ttl=self.config.lease_ttl_s)
        self._mu = threading.RLock()
        self._replicas: "OrderedDict[int, FleetReplica]" = OrderedDict()
        self._next_rid = 0
        #: leading-block fingerprint -> owning replica id (LRU-bounded)
        self._affinity: "OrderedDict[Tuple, int]" = OrderedDict()
        self._block: Optional[int] = self.config.affinity_block
        self._started = False
        self._stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None
        self.migrations = 0
        self.migrated_requests = 0
        self.scale_events = 0
        #: every replica trace identity ("label#rN") ever fronted,
        #: dead ones included — the timeline filter must keep showing
        #: a dead replica's serving events after the router dropped it
        self._engine_labels: set = set()
        self._register_metrics(registry)
        for _ in range(replicas):
            self._add_replica()

    # ------------------------------------------------------------------
    # telemetry
    # ------------------------------------------------------------------
    def _register_metrics(self, registry) -> None:
        r = registry or global_registry()
        lab = dict(fleet=self._label)
        r.gauge(FLEET_REPLICAS, "Live replicas behind the fleet router",
                ("fleet",)).set_function(
            scrape_probe(self, lambda s: len(s.replicas())), **lab)
        r.gauge(FLEET_GENERATION, "Fleet membership generation",
                ("fleet",)).set_function(
            scrape_probe(self, lambda s: s.membership.generation), **lab)
        self._routed = r.counter(
            FLEET_ROUTED, "Requests routed, by replica",
            ("fleet", "replica"))
        self._affinity_hits = r.counter(
            FLEET_AFFINITY_HITS, "Placements that followed a warm "
            "prefix-affinity mapping", ("fleet",)).labels(**lab)
        self._affinity_misses = r.counter(
            FLEET_AFFINITY_MISSES, "Placements that fell back to "
            "least-loaded scoring", ("fleet",)).labels(**lab)
        self._migrations_c = r.counter(
            FLEET_MIGRATIONS, "Live migrations, by cause",
            ("fleet", "cause"))
        for cause in (mig.CAUSE_DEATH, mig.CAUSE_SCALE_IN,
                      mig.CAUSE_OVERLOAD):
            self._migrations_c.labels(fleet=self._label, cause=cause)
        self._migrated_c = r.counter(
            FLEET_MIGRATED_REQUESTS, "Requests re-admitted on another "
            "replica by live migration", ("fleet",)).labels(**lab)
        self._dead_c = r.counter(
            FLEET_DEAD_REPLICAS, "Replicas declared dead (health down "
            "or lease expired)", ("fleet",)).labels(**lab)
        self._scale_c = r.counter(
            FLEET_SCALE_EVENTS, "Autoscaler actions, by direction",
            ("fleet", "direction"))
        for d in ("out", "in"):
            self._scale_c.labels(fleet=self._label, direction=d)

    # ------------------------------------------------------------------
    # replica lifecycle
    # ------------------------------------------------------------------
    def _add_replica(self, direction: Optional[str] = None
                     ) -> FleetReplica:
        with self._mu:
            rid = self._next_rid
            self._next_rid += 1
        engine = self._factory(rid)
        # factory-built replicas share the default model label: stamp
        # the rid so request traces name WHICH replica served them
        # (engine.trace_identity -> "label#rN")
        engine.replica_tag = rid
        rep = FleetReplica(rid, engine)
        with self._mu:
            self._replicas[rid] = rep
            members = list(self._replicas)
            self._engine_labels.add(engine.trace_identity)
        self.membership.join(rid)
        self.membership.publish(members, publisher=rid)
        if self._started:
            engine.start()
        if direction is not None:
            self.scale_events += 1
            self._scale_c.labels(fleet=self._label,
                                 direction=direction).inc()
            emit_event("fleet", "scale_out", fleet=self._label,
                       replica=rid)
        emit_event("fleet", "replica_join", fleet=self._label,
                   replica=rid, generation=self.membership.generation,
                   live=len(members))
        log.info("fleet %s: replica %d joined (generation %d, %d live)",
                 self._label, rid, self.membership.generation,
                 len(members))
        return rep

    def _drop_replica(self, rep: FleetReplica) -> None:
        with self._mu:
            self._replicas.pop(rep.rid, None)
            members = list(self._replicas)
            # drop the dead owner's affinity mappings: the next request
            # per fingerprint re-places (and re-warms) on a survivor
            stale = [fp for fp, rid in self._affinity.items()
                     if rid == rep.rid]
            for fp in stale:
                del self._affinity[fp]
        self.membership.leave(rep.rid)
        self.membership.publish(members)

    def replicas(self) -> List[FleetReplica]:
        with self._mu:
            return list(self._replicas.values())

    def replica(self, rid: int) -> Optional[FleetReplica]:
        with self._mu:
            return self._replicas.get(rid)

    # ------------------------------------------------------------------
    # placement
    # ------------------------------------------------------------------
    def _default_block(self) -> int:
        """Fingerprint block length: the replicas' KV page size (one
        cacheable block — affinity tracks exactly what the prefix cache
        can reuse), 16 tokens unpaged. Resolved once from the first
        replica's health payload."""
        if self._block is None:
            block = 16
            for rep in self.replicas():
                kv = rep.engine.health().get("kv_pages")
                if kv:
                    block = int(kv["page_size"])
                break
            self._block = block
        return self._block

    def _fingerprint(self, prompt) -> Optional[Tuple]:
        """The leading full block of the prompt, or None when it has no
        full block to share (too short to ever hit the prefix cache)."""
        if not self.config.affinity:
            return None
        bs = self._default_block()
        if len(prompt) <= bs:
            return None
        return tuple(prompt[:bs])

    def _score(self, rep: FleetReplica) -> float:
        """Least-loaded placement score (lower = better): occupancy +
        queue backlog per slot, discounted by free KV headroom. Reads
        the engine's narrow ``load_stats()`` payload — the hot submit
        path must not build the full health() dict per candidate."""
        s = rep.engine.load_stats()
        load = (s["queue_depth"] + s["active_slots"]) \
            / max(1, s["slots"])
        return load - self.config.free_weight * s["free_page_frac"]

    def _place(self, prompt, exclude=()) -> FleetReplica:
        """Pick the replica for `prompt`: the affinity owner when it is
        live and admitting, else the best-scoring live replica (and the
        fingerprint adopts it). Raises NoReplicaAvailable when nothing
        healthy remains."""
        with self._mu:
            cands = [r for r in self._replicas.values()
                     if r.rid not in exclude and r.engine.is_healthy()]
            if not cands:
                raise NoReplicaAvailable(
                    f"fleet {self._label}: no healthy replica "
                    f"(generation {self.membership.generation})")
            ready = [r for r in cands if r.engine.is_ready()] or cands
            fp = self._fingerprint(prompt)
            if fp is not None:
                rid = self._affinity.get(fp)
                if rid is not None:
                    rep = self._replicas.get(rid)
                    if rep is not None and rep in ready:
                        self._affinity.move_to_end(fp)
                        self._affinity_hits.inc()
                        return rep
            best = min(ready, key=self._score)
            if fp is not None:
                self._affinity[fp] = best.rid
                self._affinity.move_to_end(fp)
                while len(self._affinity) > self.config.affinity_capacity:
                    self._affinity.popitem(last=False)
                self._affinity_misses.inc()
            return best

    # ------------------------------------------------------------------
    # the submit/stream API (mirrors GenerationEngine.submit)
    # ------------------------------------------------------------------
    def submit(self, prompt, steps: int, **kw):
        """Route one prompt to a replica and submit it there; returns
        the replica engine's ``GenerationStream`` handle (same contract
        as ``GenerationEngine.submit``). A replica that refuses —
        drained/broken (``EngineShutdown``), queue-full, or
        overload-rejecting — is excluded and the request re-placed;
        only when EVERY live replica refuses does the last refusal
        propagate."""
        prompt = [int(t) for t in prompt]
        exclude: set = set()
        last: Optional[BaseException] = None
        while True:
            try:
                rep = self._place(prompt, exclude)
            except NoReplicaAvailable as e:
                flightrecorder.maybe_dump(
                    "no_replica", error=last if last is not None else e,
                    health=self.health(),
                    extra={"excluded": sorted(exclude)})
                if last is not None:
                    raise last
                raise
            try:
                handle = rep.engine.submit(prompt, steps, **kw)
            except (EngineShutdown, ServingQueueFull,
                    ServingOverloaded) as e:
                exclude.add(rep.rid)
                last = e
                continue
            self._routed.labels(fleet=self._label,
                                replica=str(rep.rid)).inc()
            return handle

    # ------------------------------------------------------------------
    # detection / rebalance / scaling (the poll cycle)
    # ------------------------------------------------------------------
    def poll(self, now: Optional[float] = None) -> dict:
        """One control-plane cycle: declare dead replicas (health down
        or lease expired) and migrate their ledgers to survivors;
        rebalance a sustained queue backlog onto an idle replica; run
        one autoscaler tick. Returns a summary dict (tests/bench
        introspection)."""
        now = time.monotonic() if now is None else now
        out = {"dead": [], "migrated": 0, "rebalanced": 0,
               "respawned": [], "scaled": None}
        reps = self.replicas()
        expired = set(self.membership.expired([r.rid for r in reps]))
        dead = [rep for rep in reps
                if not rep.engine.is_healthy() or rep.rid in expired]
        if dead and self._autoscaler is not None:
            # re-establish the autoscaler's floor BEFORE migrating, so
            # the dead replicas' ledgers have somewhere to land — else
            # losing the last replica would fail every in-flight stream
            # and brick the fleet (signals over zero replicas can never
            # read as pressure, so scale-out would never fire again)
            floor = self._autoscaler.config.min_replicas
            for _ in range(max(0, floor - (len(reps) - len(dead)))):
                out["respawned"].append(self._add_replica().rid)
        for rep in dead:
            out["dead"].append(rep.rid)
            self._dead_c.inc()
            emit_event("fleet", "replica_dead", fleet=self._label,
                       replica=rep.rid,
                       lease_expired=rep.rid in expired)
            report = self._migrate_from(rep, mig.CAUSE_DEATH)
            out["migrated"] += report.admitted
        if self.config.rebalance_queue_wait_s is not None:
            out["rebalanced"] = self._rebalance()
        if self._autoscaler is not None:
            out["scaled"] = self._autoscale_tick(now)
        return out

    def _migrate_from(self, rep: FleetReplica,
                      cause: str) -> mig.MigrationReport:
        """Export `rep`'s whole ledger, drop it from the fleet, and
        re-admit every entry through placement (affinity first — a
        migrated stream goes where its prefix is warm).

        The export waits on the replica's engine lock only BOUNDEDLY:
        a lease-expired replica may be hung INSIDE a dispatch with the
        lock held, and the poll thread is the whole control plane — it
        must not deadlock on one wedged engine. On timeout the replica
        is dropped from routing with nothing exported (a wedged
        in-process engine's streams cannot be reached from outside its
        lock; a multi-process deployment re-admits from persisted
        ledger payloads or client resubmission)."""
        try:
            entries = rep.engine.detach_ledger(lock_timeout=5.0)
        except TimeoutError:
            log.error(
                "fleet %s: replica %d is wedged (engine lock held "
                "through the detach timeout) — dropping it from "
                "routing with its ledger unexported", self._label,
                rep.rid)
            self._drop_replica(rep)
            self.migrations += 1
            self._migrations_c.labels(fleet=self._label,
                                      cause=cause).inc()
            emit_event("fleet", "migration", fleet=self._label,
                       source=rep.rid, cause=cause, wedged=True,
                       exported=0, admitted=0)
            return mig.MigrationReport(cause=cause, source=rep.rid)
        self._drop_replica(rep)
        report = mig.readmit_entries(entries, self._place, cause,
                                     source=rep.rid)
        self.migrations += 1
        self.migrated_requests += report.admitted
        self._migrations_c.labels(fleet=self._label, cause=cause).inc()
        self._migrated_c.inc(report.admitted)
        emit_event("fleet", "migration", fleet=self._label,
                   source=rep.rid, cause=cause,
                   exported=report.exported, admitted=report.admitted,
                   failed=report.failed,
                   targets={str(k): v
                            for k, v in report.per_target.items()})
        if report.failed:
            # in-flight work just died for want of a replica: the same
            # post-mortem trigger as a submit-side NoReplicaAvailable
            flightrecorder.maybe_dump(
                "no_replica", health=self.health(),
                traces=[e.request.trace for e in entries],
                extra={"cause": cause, "source": rep.rid,
                       "failed": report.failed})
        rep.engine.shutdown()     # nothing in flight: a clean stop
        return report

    def _rebalance(self) -> int:
        """Overload rebalance: a replica whose oldest queued request
        outwaited the threshold hands its queued tail to a replica
        scoring at least the margin lower. Actives never move here —
        their KV is warm where they sit. At most ONE source per poll
        cycle: moved requests keep their original submit times, so a
        same-cycle second pass would read the target as instantly
        overloaded and ping-pong the tail straight back."""
        moved = 0
        for rep in self.replicas():
            if not rep.engine.is_healthy():
                continue
            snap = rep.engine.queue_snapshot()
            if not snap.depth or snap.oldest_wait_s is None or \
                    snap.oldest_wait_s < self.config.rebalance_queue_wait_s:
                continue
            src_score = self._score(rep)
            # a target must be able to actually SEAT moved work (free
            # slots and an empty queue), and the move is CAPPED at its
            # free-slot count: migrated requests keep their original
            # submit times, so handing a target more than it can seat
            # would read as an over-threshold source on the NEXT poll
            # and bounce the tail straight back — cross-cycle ping-pong
            scored = []
            for r in self.replicas():
                if r.rid == rep.rid or not r.engine.is_healthy() \
                        or not r.engine.is_ready():
                    continue
                stats = r.engine.load_stats()
                if stats["queue_depth"] == 0 \
                        and stats["active_slots"] < stats["slots"]:
                    scored.append((self._score(r), r,
                                   stats["slots"]
                                   - stats["active_slots"]))
            if not scored:
                continue
            score_best, best, free_slots = min(scored,
                                               key=lambda t: t[0])
            if src_score - score_best \
                    < self.config.rebalance_load_margin:
                continue
            entries = rep.engine.detach_queued(max_n=free_slots)
            if not entries:
                continue
            # the detached tail goes to the VALIDATED target, not back
            # through affinity-first placement — a fingerprint mapping
            # to some third, loaded replica would force-requeue there
            # and re-create the ping-pong the cap exists to prevent
            # (placement is only the fallback if `best` dies mid-move)
            report = mig.readmit_entries(
                entries, lambda p, ex, _t=best, _skip=rep.rid:
                (_t if _t.rid not in ex and _t.engine.is_healthy()
                 else self._place(p, set(ex) | {_skip})),
                mig.CAUSE_OVERLOAD, source=rep.rid)
            self.migrations += 1
            self.migrated_requests += report.admitted
            self._migrations_c.labels(fleet=self._label,
                                      cause=mig.CAUSE_OVERLOAD).inc()
            self._migrated_c.inc(report.admitted)
            emit_event("fleet", "rebalance", fleet=self._label,
                       source=rep.rid, target=best.rid,
                       moved=report.admitted)
            moved += report.admitted
            break
        return moved

    def _signals(self) -> FleetSignals:
        reps = [r for r in self.replicas() if r.engine.is_healthy()]
        return FleetSignals.collect(
            [r.engine.health() for r in reps],
            [r.engine.queue_snapshot().depth for r in reps])

    def _autoscale_tick(self, now: float) -> Optional[str]:
        signals = self._signals()
        decision = self._autoscaler.decide(signals, now)
        if decision is not None:
            emit_event("fleet", "autoscale", fleet=self._label,
                       decision=decision, replicas=signals.replicas,
                       queued=signals.queued, active=signals.active)
        if decision == "out":
            self._add_replica(direction="out")
        elif decision == "in":
            self.scale_in()
        return decision

    # ------------------------------------------------------------------
    # explicit scaling (the autoscaler's executors, also public API)
    # ------------------------------------------------------------------
    def scale_out(self) -> FleetReplica:
        """Add one replica via the factory (counted as a scale event)."""
        return self._add_replica(direction="out")

    def scale_in(self, rid: Optional[int] = None
                 ) -> Optional[mig.MigrationReport]:
        """Retire one replica — by id, or the best-scoring (emptiest:
        cheapest migration, coldest cache to lose) — draining it
        through ledger migration onto the survivors. Refuses to retire
        the last replica."""
        with self._mu:
            live = [r for r in self._replicas.values()
                    if r.engine.is_healthy()]
            if rid is not None:
                rep = self._replicas.get(rid)
            else:
                rep = min(live, key=self._score) if live else None
            # the victim's ledger needs a HEALTHY survivor to land on:
            # counting registered replicas would let a scale-in retire
            # the only live replica while a dead one pads the count —
            # migration would then fail every in-flight stream
            if rep is None or not any(r.rid != rep.rid for r in live):
                return None
        report = self._migrate_from(rep, mig.CAUSE_SCALE_IN)
        self.scale_events += 1
        self._scale_c.labels(fleet=self._label, direction="in").inc()
        emit_event("fleet", "scale_in", fleet=self._label,
                   replica=rep.rid, moved=report.admitted)
        return report

    # ------------------------------------------------------------------
    # drive (manual mode) / lifecycle
    # ------------------------------------------------------------------
    def step(self) -> bool:
        """One manual cycle over every replica (the deterministic
        test/bench shape). Returns whether any replica made progress."""
        progress = False
        for rep in self.replicas():
            progress = rep.engine.step() or progress
        return progress

    def run_until_idle(self, max_steps: int = 1_000_000) -> int:
        """Drive ``step()`` until the whole fleet is idle, polling the
        control plane whenever progress stalls (so a dead replica's
        migration — or an autoscale action — can resume the trace)."""
        n = 0
        while True:
            if not self.step():
                self.poll()
                if not self.step():
                    return n
            n += 1
            if n >= max_steps:
                raise RuntimeError(f"fleet still busy after {n} steps")

    def warmup(self, **kw) -> "FleetRouter":
        """Warm every replica (manual mode only; see
        ``GenerationEngine.warmup``). Replicas added later by the
        autoscaler should be warmed by the factory instead."""
        for rep in self.replicas():
            rep.engine.warmup(**kw)
        return self

    def start(self) -> "FleetRouter":
        """Deployment shape: every replica's background loop plus the
        router's poll thread."""
        self._started = True
        self._stop.clear()
        for rep in self.replicas():
            rep.engine.start()
        if self._poll_thread is None or not self._poll_thread.is_alive():
            def _run():
                while not self._stop.wait(self.config.poll_interval_s):
                    try:
                        self.poll()
                    except Exception:   # noqa: BLE001 — keep polling
                        log.exception("fleet poll cycle failed")
            self._poll_thread = threading.Thread(
                target=_run, daemon=True, name=f"fleet-{self._label}")
            self._poll_thread.start()
        return self

    def shutdown(self) -> None:
        """Stop the poll thread, every replica, and the membership
        leases. Replica engines fail their in-flight work with
        ``EngineShutdown`` (the no-hung-callers contract)."""
        self._stop.set()
        t = self._poll_thread
        if t is not None and t.is_alive():
            t.join(timeout=2 * self.config.poll_interval_s + 1)
        for rep in self.replicas():
            rep.engine.shutdown()
        self.membership.stop()

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------
    def timeline(self, n: Optional[int] = 100) -> List:
        """This fleet's slice of the process-wide ops timeline, oldest
        first: the router's own ``fleet`` events plus the ``serving``
        lifecycle events of every replica it ever fronted (dead ones
        included — a post-mortem needs the victim's last brownout, not
        just the migration that buried it). Non-mutating snapshot of
        the bounded ring; no lock is held while filtering."""
        with self._mu:
            labels = set(self._engine_labels)
        out = []
        for e in global_event_log().tail(None):
            if e.category == "fleet" \
                    and e.attrs.get("fleet") == self._label:
                out.append(e)
            elif e.category == "serving" \
                    and e.attrs.get("engine") in labels:
                out.append(e)
        if n is not None:
            out = out[-n:]
        return out

    def health(self) -> dict:
        reps = self.replicas()
        return {
            "replicas": {r.rid: r.engine.health() for r in reps},
            "generation": self.membership.generation,
            "affinity_entries": len(self._affinity),
            "migrations": self.migrations,
            "migrated_requests": self.migrated_requests,
            "scale_events": self.scale_events,
            # bounded recent-timeline tail: a live probe sees the last
            # few control-plane actions without the JSONL sink
            "last_events": [
                {"category": e.category, "name": e.name, "wall": e.wall,
                 "attrs": dict(e.attrs)} for e in self.timeline(10)],
        }
