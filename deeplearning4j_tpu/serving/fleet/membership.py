"""Fleet replica membership: replica-role leases + generation records.

The serving fleet tracks replicas the way PR 8's elastic trainer tracks
training ranks — and on the SAME primitives (``resilience/elastic.py``):
every replica heartbeats a lease under its replica id with
``role="serving"`` stamped into each beat (a training rank and a
serving replica can share one ledger directory without miscounting each
other), an expired lease is a dead replica, and every membership change
(join, death, scale-in) publishes an immutable, monotonically numbered
``GenerationRecord`` through the same fsynced exclusive-create path the
trainer's split-brain tiebreak uses. The generation number is the
router's fencing token: telemetry, migration reports, and a future
multi-router deployment all agree on "which fleet was that" by
generation, not by wall clock.

Filesystem membership is OPTIONAL (``root=None``): an in-process fleet
(tests, single-host serving) detects death through
``engine.is_healthy()`` alone and keeps a process-local generation
counter; pointing ``root`` at a shared directory adds the lease
machinery a multi-process deployment needs — including detection of a
replica whose PROCESS died (its engine object unreachable, its lease
simply stops beating).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Sequence

from deeplearning4j_tpu.monitoring.events import emit as emit_event
from deeplearning4j_tpu.resilience.elastic import (
    GenerationRecord, LeaseLedger)

log = logging.getLogger(__name__)

__all__ = ["AGENT_ROLE", "FleetMembership", "PREFILL_ROLE",
           "REPLICA_ROLE"]

#: the lease role serving replicas beat with (train ranks carry none
#: or their own role; live_ranks(role=REPLICA_ROLE) sees only replicas)
REPLICA_ROLE = "serving"

#: the lease role CROSS-PROCESS replica agents beat with
#: (``serving/fleet/agent.py``): one OS process per replica, discovered
#: by an out-of-process router purely through the lease ledger —
#: distinct from REPLICA_ROLE so an in-process fleet and a process
#: fleet can share one ledger directory without miscounting each other
AGENT_ROLE = "replica"

#: the lease role PREFILL-ONLY agents beat with
#: (``serving/fleet/prefill.py``): disaggregated serving's prefill
#: pool — same ledger, same transport, no decode slots. Replica ids
#: are a SINGLE namespace across roles (leases, mailboxes, journal
#: streams, and status files all key on rid alone), so a deployment
#: must assign prefill agents rids disjoint from decode replicas.
PREFILL_ROLE = "prefill"


class FleetMembership:
    """Replica lease + generation bookkeeping for one fleet router.

    ``join(rid)`` starts a heartbeating lease for a replica,
    ``leave(rid)`` withdraws it (orderly scale-in: peers see the
    replica gone at the next read instead of waiting out the ttl), and
    ``expired(rids)`` reports which tracked replicas' leases lapsed —
    the death signal for a replica whose process stopped beating even
    though the router cannot observe its engine. ``publish(members)``
    bumps the generation and (with a root) writes the generation
    record.

    Thread-safe: the router's poll loop and submit path may consult it
    concurrently.
    """

    def __init__(self, root: Optional[str] = None, ttl: float = 2.0,
                 role: str = REPLICA_ROLE,
                 extra: Optional[Dict] = None):
        self.root = root
        self.ttl = float(ttl)
        self.role = role
        #: advertisement merged into every joined lease's beats (a
        #: cross-process agent publishes its pid here)
        self.extra = dict(extra) if extra else None
        self._mu = threading.Lock()
        self._leases: Dict[int, LeaseLedger] = {}
        self._reader: Optional[LeaseLedger] = None
        self.generation = 0
        if root is not None:
            # a read/publish-only ledger: rank -1 never heartbeats, so
            # no lease file ever claims the router itself is a replica
            self._reader = LeaseLedger(root, rank=-1, ttl=self.ttl,
                                       role=role)
            latest = self._reader.latest_generation()
            if latest is not None:
                self.generation = latest.generation

    @property
    def enabled(self) -> bool:
        """Whether filesystem leases back this membership (False = the
        in-process mode: engine health is the only death signal)."""
        return self._reader is not None

    # -- replica lifecycle ---------------------------------------------
    def join(self, rid: int) -> None:
        """Start heartbeating a lease for replica `rid` (no-op without
        a root)."""
        if self._reader is None:
            return
        with self._mu:
            if rid in self._leases:
                return
            lease = LeaseLedger(self.root, rank=int(rid), ttl=self.ttl,
                                role=self.role, extra=self.extra)
            lease.start(self.generation)
            self._leases[rid] = lease

    def leave(self, rid: int) -> None:
        """Withdraw and stop replica `rid`'s lease (orderly leave)."""
        with self._mu:
            lease = self._leases.pop(rid, None)
        if lease is not None:
            lease.stop()
            lease.withdraw()

    def lease(self, rid: int) -> Optional[LeaseLedger]:
        """The heartbeating lease for `rid` (None without a root) —
        the chaos seam: ``lease.stall()`` simulates a hung replica."""
        with self._mu:
            return self._leases.get(rid)

    # -- discovery (the out-of-process router's membership read) -------
    def live_ranks(self) -> List[int]:
        """Ranks with a live lease in this membership's role (empty
        without a root) — how a router that holds NO engine references
        discovers which replica agents exist at all."""
        if self._reader is None:
            return []
        return self._reader.live_ranks(role=self.role)

    def live_leases(self) -> Dict[int, Dict]:
        """Live ranks with their latest beat payloads (advertised
        ``extra`` fields included; empty without a root)."""
        if self._reader is None:
            return {}
        return self._reader.live_leases(role=self.role)

    # -- death detection -----------------------------------------------
    def expired(self, rids: Sequence[int]) -> List[int]:
        """Tracked replicas among `rids` whose lease lapsed (empty
        without a root: lease expiry is then not a signal)."""
        if self._reader is None:
            return []
        live = set(self._reader.live_ranks(role=self.role))
        return [r for r in rids if r not in live]

    # -- generations ----------------------------------------------------
    def publish(self, members: Sequence[int], publisher: int = -1) -> int:
        """Advance the fleet generation over the given member set and
        (with a root) publish the record. An empty member set still
        bumps the local generation — the fleet-of-zero moment mid
        scale-from-death — but publishes nothing (generation records
        are non-empty by contract). Returns the new generation."""
        with self._mu:
            self.generation += 1
            gen = self.generation
        members = sorted(int(m) for m in members)
        if self._reader is not None and members:
            while True:
                rec = GenerationRecord(generation=gen, members=members,
                                       coordinator="",
                                       published_by=int(publisher))
                adopted = self._reader.publish_generation(rec)
                if adopted.to_dict() == rec.to_dict():
                    break
                # lost the exclusive-create race: the on-disk record at
                # this number is ANOTHER publisher's fleet view —
                # publish_generation returns it with the SAME number,
                # so converging means re-publishing OUR member set at
                # its successor, not adopting its membership
                gen = adopted.generation + 1
            with self._mu:
                self.generation = gen
            for lease in list(self._leases.values()):
                lease.heartbeat(gen)       # re-stamp the beat stream
        emit_event("fleet", "generation", generation=gen,
                   members=list(members), publisher=int(publisher))
        return gen

    def record(self) -> Optional[GenerationRecord]:
        """The latest on-disk generation record (None without a root
        or before the first publish)."""
        if self._reader is None:
            return None
        return self._reader.latest_generation()

    def stop(self) -> None:
        """Stop every lease thread (shutdown); leases are withdrawn so
        a later reader doesn't wait out the ttl."""
        with self._mu:
            leases, self._leases = dict(self._leases), {}
        for lease in leases.values():
            lease.stop()
            lease.withdraw()
