"""PrefillAgent: a prefill-only fleet process (DistServe-style role
split).

The disaggregated fleet's prefill half: same lease ledger, same
mailbox/journal/status transport as ``ReplicaAgent``, but a
``role="prefill"`` lease (``membership.PREFILL_ROLE``) and NO decode
loop — the agent consumes ``CMD_PREFILL`` commands, primes each
request through the engine's ordinary admission path
(``engine.prefill_publish``: prefix hits, the first-token draw, the
prefix-cache insert all included), publishes the prompt's full-block
KV pages to the fleet page store (``serving/fleet/pages.py``), detaches
the slot, and journals ONE ``EV_PREFILLED`` line carrying the drawn
first token, the post-draw rng state, and the published chain digests.
The router relays the token, adopts the rng, and re-places the stream
on a decode replica scored by page locality — whose admission imports
the shipped pages and primes only the suffix WITHOUT drawing (the
streamed-readmit path), so the disaggregated stream is bit-identical
to the unified one.

Prefill FLOPs therefore never run on a decode replica's dispatch
thread: long prompts stop stealing decode TPOT, which is the entire
point. A prefill failure nacks (the router degrades that request to
unified placement); a dead prefill process is just an expired lease
(the router routes around it). Replica ids share ONE namespace with
decode agents — deployments must keep them disjoint.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

from deeplearning4j_tpu.monitoring.events import emit as emit_event
from deeplearning4j_tpu.monitoring.metrics import (
    MetricsRegistry, global_registry)
from deeplearning4j_tpu.serving.fleet import transport
from deeplearning4j_tpu.serving.fleet.membership import (
    FleetMembership, PREFILL_ROLE)
from deeplearning4j_tpu.serving.health import (
    FLEET_PAGE_SHIP_BYTES, FLEET_PAGES_PUBLISHED, FLEET_PREFILLS,
    FLEET_TRANSPORT_COMMANDS, FLEET_TRANSPORT_DUPLICATES,
    FLEET_TRANSPORT_QUARANTINED)
from deeplearning4j_tpu.serving.request import RequestLedgerEntry

log = logging.getLogger(__name__)

__all__ = ["PrefillAgent"]


class PrefillAgent:
    """One prefill-only engine + lease + mailbox + journal process.

    Drive with :meth:`run` (worker entrypoint) or :meth:`poll_once`
    (the deterministic in-process test shape).
    """

    def __init__(self, engine, store, root: str, rid: int, *,
                 ttl: float = 2.0,
                 status_interval_s: float = 0.1,
                 registry: Optional[MetricsRegistry] = None,
                 label: str = "fleet"):
        self.engine = engine
        self.store = store
        self.rid = int(rid)
        self.root = root
        paths = transport.fleet_paths(root)
        engine.replica_tag = self.rid
        self.membership = FleetMembership(
            paths["leases"], ttl=ttl, role=PREFILL_ROLE,
            extra={"pid": os.getpid()})
        self.mailbox = transport.Mailbox(root, self.rid)
        self.journal = transport.JournalWriter(root, self.rid)
        self.status = transport.AgentStatus(root)
        self.status_interval_s = float(status_interval_s)
        self._last_status_t = 0.0
        self._label = label
        self._seen: set = set()          # (request id, attempt) dedupe
        self._shutdown = False
        self.commands = 0
        self.duplicates = 0
        self.prefills = 0
        self.published = 0
        self.publish_bytes = 0
        self._warm_compiles: Optional[float] = None
        r = registry or global_registry()
        lab = dict(fleet=self._label, replica=str(self.rid))
        self._cmd_c = r.counter(
            FLEET_TRANSPORT_COMMANDS, "Mailbox commands consumed, "
            "by kind", ("fleet", "replica", "kind"))
        self._dup_c = r.counter(
            FLEET_TRANSPORT_DUPLICATES, "Duplicate deliveries dropped "
            "by request-id dedupe", ("fleet", "replica")).labels(**lab)
        self._quar_c = r.counter(
            FLEET_TRANSPORT_QUARANTINED, "Torn/undecodable command "
            "files quarantined", ("fleet", "replica")).labels(**lab)
        self._prefill_c = r.counter(
            FLEET_PREFILLS, "CMD_PREFILL admissions served",
            ("fleet", "replica")).labels(**lab)
        self._pub_c = r.counter(
            FLEET_PAGES_PUBLISHED, "KV pages published to the fleet "
            "store", ("fleet", "replica")).labels(**lab)
        self._ship_c = r.counter(
            FLEET_PAGE_SHIP_BYTES, "Page bytes moved through the "
            "store, by direction", ("fleet", "replica", "direction"))
        self._quarantined_seen = 0
        self.membership.join(self.rid)
        self.write_status()

    # -- the zero-retrace bookkeeping ----------------------------------
    @staticmethod
    def _compile_total() -> float:
        from deeplearning4j_tpu.monitoring import runtime
        c = global_registry().get(runtime.COMPILE_COUNTER)
        return 0.0 if c is None else c.total()

    def mark_warm(self) -> None:
        self._warm_compiles = self._compile_total()

    # -- status advertisement ------------------------------------------
    def status_payload(self) -> dict:
        out = {"rid": self.rid, "pid": os.getpid(),
               "ts": time.time(),
               "role": "prefill",
               "healthy": self.engine.is_healthy(),
               "ready": self.engine.is_ready(),
               "load": self.engine.load_stats(),
               "inflight": 0,
               "commands": self.commands,
               "duplicates": self.duplicates,
               "prefills": self.prefills,
               "published": self.published,
               "publish_bytes": self.publish_bytes,
               "quarantined": len(self.mailbox.quarantined())}
        kv = self.engine.health().get("kv_pages")
        if kv:
            out["kv_page_size"] = kv["page_size"]
        if self._warm_compiles is not None:
            out["compiles_since_warm"] = \
                self._compile_total() - self._warm_compiles
        return out

    def write_status(self, force: bool = True) -> None:
        now = time.monotonic()
        if not force and now - self._last_status_t \
                < self.status_interval_s:
            return
        self._last_status_t = now
        self.status.write(self.rid, self.status_payload())

    # -- the command loop ----------------------------------------------
    def poll_once(self) -> int:
        before = len(self.mailbox.quarantined())
        cmds = self.mailbox.receive()
        newly_quarantined = len(self.mailbox.quarantined()) - before
        if newly_quarantined > 0:
            self._quar_c.inc(newly_quarantined)
            emit_event("transport", "quarantine", replica=self.rid,
                       count=newly_quarantined)
        for _, cmd in cmds:
            self.commands += 1
            kind = str(cmd.get("kind"))
            self._cmd_c.labels(fleet=self._label,
                               replica=str(self.rid), kind=kind).inc()
            if kind == transport.CMD_PREFILL:
                self._handle_prefill(cmd)
            elif kind == transport.CMD_SHUTDOWN:
                self._shutdown = True
            elif kind == transport.CMD_REVOKE:
                pass    # nothing decodes here; prefill is one-shot
            else:
                log.warning("prefill agent %d: unknown command kind "
                            "%r ignored", self.rid, kind)
        return len(cmds)

    def _handle_prefill(self, cmd: dict) -> None:
        req_id = str(cmd.get("req"))
        attempt = int(cmd.get("attempt", 0))
        key = (req_id, attempt)
        if key in self._seen:
            self.duplicates += 1
            self._dup_c.inc()
            emit_event("transport", "duplicate", replica=self.rid,
                       req=req_id, attempt=attempt)
            return
        self._seen.add(key)
        try:
            entry = RequestLedgerEntry.from_payload(cmd["entry"])
            rec = self.engine.prefill_publish(entry.request, self.store)
        except Exception as e:  # noqa: BLE001 — nack, never crash
            self.journal.append([{"kind": transport.EV_NACK,
                                  "req": req_id, "attempt": attempt,
                                  "error": repr(e)}])
            emit_event("transport", "nack", replica=self.rid,
                       req=req_id, error=repr(e))
            return
        self.prefills += 1
        self._prefill_c.inc()
        if rec["published"]:
            self.published += rec["published"]
            self.publish_bytes += rec["bytes"]
            self._pub_c.inc(rec["published"])
            self._ship_c.labels(fleet=self._label,
                                replica=str(self.rid),
                                direction="publish").inc(rec["bytes"])
        self.journal.append([{"kind": transport.EV_PREFILLED,
                              "req": req_id, "attempt": attempt,
                              "tok": rec["token"], "rng": rec["rng"],
                              "done": rec["done"],
                              "reason": rec["reason"],
                              "error": rec["error"],
                              "digests": rec["digests"],
                              "published": rec["published"],
                              "bytes": rec["bytes"]}])
        emit_event("transport", "prefilled", replica=self.rid,
                   req=req_id, attempt=attempt,
                   blocks=len(rec["digests"]), done=rec["done"])

    # -- driving -------------------------------------------------------
    def request_drain(self) -> None:
        """Signal-safe planned-stop request (the worker's SIGTERM
        handler): prefill is one-shot per command and holds no streams,
        so drain is just an orderly stop — finish the current poll,
        write a final status, withdraw the lease, exit."""
        self._shutdown = True

    def run(self, idle_sleep_s: float = 0.005) -> None:
        """Worker main loop: poll the mailbox until shutdown. No
        engine stepping — this role never decodes."""
        while not self._shutdown:
            handled = self.poll_once()
            self.write_status(force=False)
            if not handled:
                time.sleep(idle_sleep_s)
        self.close()

    def close(self) -> None:
        self._shutdown = True
        try:
            self.write_status()
        except OSError:
            pass
        self.membership.stop()
        self.journal.close()
        self.engine.shutdown()
