"""Ledger-based live request migration between replicas.

A replica's in-flight requests are fully described by its HOST-side
request ledger — the PR 9 rebuild payload, public since
``serving/request.RequestLedgerEntry``: prompt, committed ids (last =
the pending token), per-request rng at its exact draw position, and
sampling config. Migration is therefore the supervisor's quarantine
pointed at a DIFFERENT engine: export the source's ledger
(``detach_ledger`` — everything in flight, no terminal events, source
left empty and draining), place each entry with the router's own
placement function (so a migrated stream lands where its prefix is
warm), and ``admit_from_ledger`` on the target — streamed survivors
re-prime ``ids[:-1]`` with their pending token and untouched rng, so
every stream continues bit-identically to an unperturbed run
(test-pinned, greedy and sampled).

Three triggers, one mechanism:

- **planned** (scale-in / rollout): the full ``detach_ledger`` export —
  actives move instead of waiting out ``drain()``'s natural
  retirements;
- **death** (lease expiry or ``is_healthy()`` down): the same export
  runs post-mortem — the ledger is host memory and outlives the device
  arena; a replica that reached its terminal ``_break`` already failed
  its handles and exports empty (fail-all happened before the fleet
  could act);
- **overload rebalance**: only the QUEUED (never-prefilled) tail moves
  (``detach_queued``) — queued work migrates for free while actives
  keep their warm KV.

Entries that find no live target are failed with
:class:`~deeplearning4j_tpu.serving.errors.NoReplicaAvailable` — a
terminal event on every path, nobody blocks on a dead fleet.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Callable, Dict, List, Optional, Sequence

from deeplearning4j_tpu.serving.errors import (
    EngineShutdown, NoReplicaAvailable)
from deeplearning4j_tpu.serving.request import RequestLedgerEntry

log = logging.getLogger(__name__)

__all__ = ["MigrationReport", "readmit_entries", "record_hop"]

#: migration cause labels (the ``dl4jtpu_fleet_migrations_total`` label
#: vocabulary; also stamped into every report)
CAUSE_DEATH = "death"
CAUSE_SCALE_IN = "scale_in"
CAUSE_OVERLOAD = "overload"


def record_hop(request, source, target, cause: str) -> None:
    """Stamp one migration hop on the request's OWN trace: a migrated
    stream's post-mortem must name both replicas even after the source
    object (or source PROCESS) is gone. One helper shared by the
    in-process re-admission path and the cross-process router's
    re-placement, so the trace vocabulary cannot fork."""
    request.trace.record("migrate", source=source, target=target,
                         cause=cause)


@dataclasses.dataclass
class MigrationReport:
    """What one migration did: per-target re-admission counts, entries
    resolved dead on the way (cancel/deadline — they get their terminal
    event during re-admission, same as the supervisor's recovery), and
    entries failed because no replica could take them."""

    cause: str
    source: Optional[int] = None
    exported: int = 0
    admitted: int = 0
    resolved_dead: int = 0
    failed: int = 0
    per_target: Dict[int, int] = dataclasses.field(default_factory=dict)

    @property
    def moved(self) -> int:
        return self.admitted


def readmit_entries(entries: Sequence[RequestLedgerEntry],
                    place: Callable,
                    cause: str,
                    source: Optional[int] = None) -> MigrationReport:
    """Re-admit exported ledger entries across live replicas.

    ``place(prompt, exclude)`` is the router's placement function —
    it returns a replica (an object with ``rid`` and ``engine``) or
    raises :class:`NoReplicaAvailable`; affinity applies, so a stream
    whose system-prompt block is cached on a survivor goes home to it.
    A target that turns out shut down mid-migration is excluded and the
    entry re-placed; entries nobody can take are failed terminally."""
    report = MigrationReport(cause=cause, source=source,
                             exported=len(entries))
    for entry in entries:
        req = entry.request
        if req.handle.done:
            report.resolved_dead += 1
            continue
        exclude: set = set()
        while True:
            try:
                rep = place(req.prompt, exclude)
            except NoReplicaAvailable as e:
                entry.resolve(e)
                report.failed += 1
                break
            try:
                took = rep.engine.admit_from_ledger(
                    [entry], where=f"during {cause} migration")
            except EngineShutdown:
                # the target died/drained between placement and
                # admission: never hand it back the same entry
                exclude.add(rep.rid)
                continue
            except BaseException as e:  # noqa: BLE001 — strand nobody
                # a post-prime admission fault on the target (arena
                # build/merge — past _admit_one's per-request prefill
                # domain): resolve THIS entry terminally and keep
                # migrating the rest. The source is already empty, so
                # an aborted migration would leave every remaining
                # entry owned by no engine with no terminal event; the
                # target's own supervisor/step path owns its arena
                # health from here.
                entry.resolve(e)
                report.failed += 1
                break
            if took:
                report.admitted += took
                report.per_target[rep.rid] = \
                    report.per_target.get(rep.rid, 0) + took
                # recorded after the target accepted (a refused
                # target is not a hop)
                record_hop(req, source, rep.rid, cause)
            elif req.handle.done:
                report.resolved_dead += 1   # cancel/deadline resolved
            break
    if report.exported:
        log.info(
            "fleet migration (%s) from replica %s: %d exported, "
            "%d re-admitted %s, %d resolved dead, %d unplaceable",
            cause, source, report.exported, report.admitted,
            dict(report.per_target), report.resolved_dead, report.failed)
    return report
