"""Shared-filesystem transport for the cross-process serving fleet.

The in-process fleet (PR 13) moves requests between replicas as
``RequestLedgerEntry`` objects through direct engine references. This
module gives the ledger's versioned JSON wire form
(``RequestLedgerEntry.payload()``) a TRANSPORT, so a replica can be its
own OS process and the router can live in another one, with nothing
shared but a filesystem:

- **Mailbox** (``<root>/mail/<rid>/``): the router→agent command
  channel. One JSON file per command, written atomic-rename through
  the ``resilience/durable.py`` primitives, so an agent (or a reader
  that raced a ``kill -9``) never observes a torn command through the
  NORMAL write path. Delivery is at-least-once: a writer that dies
  between "wrote the file" and "recorded that it wrote the file" may
  re-send, so every command carries the request id (+ an ``attempt``
  fence) and the agent dedupes. A file that IS unreadable — a crashed
  copy tool, a chaos-injected torn write — is moved to
  ``quarantine/``, never crashing the poll loop and never re-read.
- **StreamJournal** (``<root>/journal/agent_<rid>.jsonl``): the
  agent→router event channel — an append-only JSONL stream of
  committed-token batches and retirements. Each ``tok`` line carries
  one request's NEW tokens for one engine step, their absolute indices
  among the generated tokens, and the request's post-step rng state:
  one line is one atomic consistency unit, so a line torn by
  ``kill -9`` mid-append loses a whole (ids, rng) pair — the previous
  line is still consistent, and a re-prime from it regenerates the
  lost tokens bit-identically (the router's index dedupe drops any
  overlap a survivor re-emits).
- **status files** (``<root>/status/agent_<rid>.json``): each agent's
  periodically refreshed load/health advertisement (atomic-rename),
  which is how an out-of-process router scores placement without
  ``load_stats()`` engine references.

Layout under one fleet root::

    <root>/leases/    lease_<rid>.json       (resilience/elastic.py)
    <root>/mail/<rid>/cmd_*.json             router -> agent commands
    <root>/mail/<rid>/quarantine/            torn/undecodable commands
    <root>/journal/agent_<rid>.jsonl         agent -> router events
    <root>/status/agent_<rid>.json           agent load advertisement

Command envelope (the mailbox payload)::

    {"kind": "admit",  "req": <id>, "attempt": <n>, "entry": <payload>}
    {"kind": "revoke", "req": <id>, "attempt": <n>}   # fence a stale serve
    {"kind": "shutdown"}

``entry`` is exactly ``RequestLedgerEntry.payload()`` — the versioned
wire form; nothing here re-encodes request state. Journal events::

    {"kind": "tok",  "req": r, "attempt": a, "start": i,
     "toks": [...], "rng": <bit-generator state>}
    {"kind": "done", "req": r, "attempt": a, "reason": <finish_reason>,
     "error": <repr or None>}
    {"kind": "nack", "req": r, "attempt": a, "error": <repr>}

See ARCHITECTURE.md "Cross-process fleet".
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.resilience.durable import atomic_write_json

__all__ = ["AgentStatus", "JournalReader", "JournalWriter", "Mailbox",
           "fleet_paths"]

#: command kinds the mailbox carries
CMD_ADMIT = "admit"
CMD_REVOKE = "revoke"
CMD_SHUTDOWN = "shutdown"
#: prefill-only admission (disaggregated mode): prime + publish KV
#: pages + the pending first token, do NOT decode (prefill.py)
CMD_PREFILL = "prefill"

#: journal event kinds
EV_TOK = "tok"
EV_DONE = "done"
EV_NACK = "nack"
#: a prefill replica finished priming: carries the first token, the
#: post-draw rng, and the published page digests (router hands the
#: stream to a decode replica scored by page locality)
EV_PREFILLED = "prefilled"

_CMD_PREFIX = "cmd_"
_QUARANTINE = "quarantine"


def fleet_paths(root: str) -> Dict[str, str]:
    """The shared-root layout, resolved in ONE place: every component
    (agent, router, worker entrypoint, tests) derives paths from here
    so the on-disk contract cannot drift per caller."""
    root = os.path.abspath(root)
    return {
        "root": root,
        "leases": os.path.join(root, "leases"),
        "mail": os.path.join(root, "mail"),
        "journal": os.path.join(root, "journal"),
        "status": os.path.join(root, "status"),
        "pages": os.path.join(root, "pages"),
    }


class Mailbox:
    """One replica agent's command directory.

    The ROUTER holds a send-side Mailbox per discovered agent; the
    AGENT holds the receive side for its own rid. Writers never touch
    files in place: every send is a tmp-write + ``os.replace`` through
    ``resilience/durable.atomic_write_json``, and names embed a
    (wall-ns, pid, per-process seq) triple so concurrent senders never
    collide and a sort-by-name read approximates send order. Order is a
    courtesy, not a contract — dedupe + the ``attempt`` fence carry
    correctness.
    """

    _seq_mu = threading.Lock()
    _seq = 0

    def __init__(self, root: str, rid: int,
                 chaos: Optional[object] = None):
        self.rid = int(rid)
        self.path = os.path.join(fleet_paths(root)["mail"], str(self.rid))
        self.quarantine_path = os.path.join(self.path, _QUARANTINE)
        #: transport chaos seam (resilience/chaos.py mailbox
        #: injectors): ``chaos.on_send(dirpath, name, data) -> bool``,
        #: True = the injector handled (or withheld) delivery
        self.chaos = chaos
        os.makedirs(self.quarantine_path, exist_ok=True)

    # -- send side (router) --------------------------------------------
    @classmethod
    def _next_name(cls) -> str:
        with cls._seq_mu:
            cls._seq += 1
            seq = cls._seq
        return (f"{_CMD_PREFIX}{time.time_ns():020d}_"
                f"{os.getpid()}_{seq:06d}.json")

    def send(self, cmd: dict) -> str:
        """Deliver one command (atomic rename); returns the file name.
        With a chaos injector attached the injector may take over the
        delivery (torn write, duplication, delay)."""
        name = self._next_name()
        if self.chaos is not None:
            data = (json.dumps(cmd, sort_keys=True) + "\n").encode()
            if self.chaos.on_send(self.path, name, data):
                return name
        atomic_write_json(os.path.join(self.path, name), cmd)
        return name

    # -- receive side (agent) ------------------------------------------
    def receive(self, max_n: Optional[int] = None
                ) -> List[Tuple[str, dict]]:
        """Consume pending commands in name order: parse, unlink,
        return ``(name, command)`` pairs. An unreadable/undecodable
        file is MOVED to ``quarantine/`` (counted by the agent's
        telemetry) — a torn command must never crash the poll loop,
        and must never be re-read as if it might heal."""
        try:
            names = sorted(n for n in os.listdir(self.path)
                           if n.startswith(_CMD_PREFIX)
                           and n.endswith(".json"))
        except OSError:
            return []
        out: List[Tuple[str, dict]] = []
        for name in names:
            if max_n is not None and len(out) >= max_n:
                break
            path = os.path.join(self.path, name)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    cmd = json.load(f)
                if not isinstance(cmd, dict) or "kind" not in cmd:
                    raise ValueError("command is not an envelope dict")
            except (OSError, ValueError) as e:
                self._quarantine(name, repr(e))
                continue
            try:
                os.unlink(path)
            except OSError:
                pass
            out.append((name, cmd))
        return out

    def _quarantine(self, name: str, why: str) -> None:
        try:
            os.replace(os.path.join(self.path, name),
                       os.path.join(self.quarantine_path, name))
        except OSError:
            try:
                os.unlink(os.path.join(self.path, name))
            except OSError:
                pass
        # a breadcrumb beside the quarantined file, for post-mortems
        try:
            atomic_write_json(
                os.path.join(self.quarantine_path, name + ".why"),
                {"name": name, "why": why})
        except OSError:
            pass

    def quarantined(self) -> List[str]:
        """Names of quarantined command files (oldest first)."""
        try:
            return sorted(n for n in os.listdir(self.quarantine_path)
                          if n.startswith(_CMD_PREFIX)
                          and n.endswith(".json"))
        except OSError:
            return []

    def pending(self) -> int:
        """Commands delivered but not yet consumed."""
        try:
            return sum(1 for n in os.listdir(self.path)
                       if n.startswith(_CMD_PREFIX)
                       and n.endswith(".json"))
        except OSError:
            return 0


def _journal_path(root: str, rid: int) -> str:
    return os.path.join(fleet_paths(root)["journal"],
                        f"agent_{int(rid)}.jsonl")


class JournalWriter:
    """The agent side of the stream journal: append-only JSONL.

    One ``append(events)`` call writes each event as one line and
    flushes once — a ``kill -9`` can tear at most the LAST line, which
    the reader simply never consumes (it only advances past complete
    lines). Deliberately not fsynced per line: the journal's loss
    bound is "whatever the page cache held", and the re-prime path
    regenerates anything lost bit-identically from the last line that
    did land.
    """

    def __init__(self, root: str, rid: int):
        self.path = _journal_path(root, rid)
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        self._f = open(self.path, "a", encoding="utf-8")

    def append(self, events: List[dict]) -> int:
        if not events:
            return 0
        buf = "".join(json.dumps(ev, sort_keys=True) + "\n"
                      for ev in events)
        self._f.write(buf)
        self._f.flush()
        return len(events)

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass


class JournalReader:
    """The router side: tail every agent's journal, complete lines
    only. Per-rid byte offsets advance past each consumed line's
    newline; a torn tail (no trailing newline yet — mid-append, or a
    ``kill -9`` artifact) stays unconsumed forever without blocking
    the lines before it. An undecodable COMPLETE line is skipped and
    counted (``corrupt``) — one bad record must not wedge the relay.
    """

    def __init__(self, root: str):
        self.root = root
        self._offsets: Dict[int, int] = {}
        self.corrupt = 0

    def poll(self, rid: int) -> List[dict]:
        """New complete events from agent `rid`'s journal since the
        last poll (empty when the file does not exist yet)."""
        path = _journal_path(self.root, rid)
        off = self._offsets.get(int(rid), 0)
        try:
            with open(path, "rb") as f:
                f.seek(off)
                chunk = f.read()
        except OSError:
            return []
        if not chunk:
            return []
        # consume only up to the last complete line
        end = chunk.rfind(b"\n")
        if end < 0:
            return []
        complete, consumed = chunk[:end + 1], end + 1
        self._offsets[int(rid)] = off + consumed
        out: List[dict] = []
        for line in complete.splitlines():
            if not line.strip():
                continue
            try:
                ev = json.loads(line)
                if not isinstance(ev, dict) or "kind" not in ev:
                    raise ValueError("journal line is not an event")
            except ValueError:
                self.corrupt += 1
                continue
            out.append(ev)
        return out


class AgentStatus:
    """Atomic-rename status advertisement, both directions.

    The agent calls :meth:`write` each poll cycle with its
    ``load_stats()``/health payload; the router calls :meth:`read` /
    :meth:`read_all` to score placement. Always a whole-file replace —
    a reader never sees a half-written status."""

    def __init__(self, root: str):
        self.path = fleet_paths(root)["status"]
        os.makedirs(self.path, exist_ok=True)

    def _status_path(self, rid: int) -> str:
        return os.path.join(self.path, f"agent_{int(rid)}.json")

    def write(self, rid: int, payload: dict) -> None:
        atomic_write_json(self._status_path(rid), payload)

    def read(self, rid: int) -> Optional[dict]:
        try:
            with open(self._status_path(rid), "r",
                      encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def read_all(self) -> Dict[int, dict]:
        out: Dict[int, dict] = {}
        try:
            names = os.listdir(self.path)
        except OSError:
            return out
        for name in names:
            if not (name.startswith("agent_") and
                    name.endswith(".json")):
                continue
            try:
                rid = int(name[len("agent_"):-len(".json")])
            except ValueError:
                continue
            payload = self.read(rid)
            if payload is not None:
                out[rid] = payload
        return out

    def clear(self, rid: int) -> None:
        try:
            os.unlink(self._status_path(rid))
        except OSError:
            pass
