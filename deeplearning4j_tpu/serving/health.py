"""Shared serving telemetry registration.

Every serving component publishes the same ``dl4jtpu_serving_*`` series
through ONE code path (this module) instead of per-component copies:
request/error/deadline/rejection counters with their handles resolved
once (the hot path must not re-enter the registry's get-or-create lock
per request), and scrape-time health gauges holding a WEAK reference —
a registry series must not pin a shut-down server (and its device
params) alive forever; a collected instance scrapes as down/empty.

``ParallelInference`` and ``GenerationEngine`` both register here; the
``model`` label value distinguishes their series (the engine prefixes
``engine:``).
"""

from __future__ import annotations

import weakref
from typing import Dict, Optional

from deeplearning4j_tpu.monitoring.metrics import (
    MetricsRegistry, global_registry)

SERVING_HEALTHY = "dl4jtpu_serving_healthy"
SERVING_READY = "dl4jtpu_serving_ready"
SERVING_QUEUE_DEPTH = "dl4jtpu_serving_queue_depth"
SERVING_REQUESTS = "dl4jtpu_serving_requests_total"
SERVING_ERRORS = "dl4jtpu_serving_errors_total"
SERVING_DEADLINE_EXCEEDED = "dl4jtpu_serving_deadline_exceeded_total"
SERVING_QUEUE_REJECTED = "dl4jtpu_serving_queue_rejected_total"

#: continuous-batching engine extras (engine.py registers these)
SERVING_ACTIVE_SLOTS = "dl4jtpu_serving_active_slots"
SERVING_TOKENS = "dl4jtpu_serving_tokens_total"
SERVING_TTFT = "dl4jtpu_serving_ttft_seconds"
SERVING_TPOT = "dl4jtpu_serving_tpot_seconds"
SERVING_QUEUE_WAIT = "dl4jtpu_serving_queue_wait_seconds"

#: block-paged KV arena + prefix cache + in-engine speculation (engine
#: registers these only in the matching mode)
SERVING_KV_PAGES_TOTAL = "dl4jtpu_serving_kv_pages_total"
SERVING_KV_PAGES_USED = "dl4jtpu_serving_kv_pages_used"
SERVING_PREFIX_HITS = "dl4jtpu_serving_prefix_cache_hits_total"
SERVING_PREFIX_MISSES = "dl4jtpu_serving_prefix_cache_misses_total"
SERVING_PREFIX_REUSED_TOKENS = \
    "dl4jtpu_serving_prefix_cache_reused_tokens_total"
SERVING_SPEC_ACCEPTANCE = "dl4jtpu_serving_spec_acceptance_ratio"

#: KV-traffic accounting for the paged decode paths (engine registers
#: these in paged mode): bytes the KV round trip MOVES per dispatch —
#: modeled host-side from the path in use (legacy round trip:
#: gather + scatter of the full dense view; direct-xla: one in-dispatch
#: gather + the one-token append; direct-pallas: live pages read + the
#: one-token append) — plus the per-step decode dispatch latency. The
#: round-trip elimination is a number here, not a claim.
SERVING_KV_BYTES_MOVED = "dl4jtpu_serving_kv_bytes_moved_total"
SERVING_DISPATCH_LATENCY = "dl4jtpu_serving_decode_dispatch_seconds"

#: fleet layer (serving/fleet/router.py registers these): multi-replica
#: routing, prefix-affinity placement, ledger migration, autoscaling.
#: ``fleet`` labels distinguish routers; ``replica`` / ``cause`` /
#: ``direction`` label the per-series dimensions.
FLEET_REPLICAS = "dl4jtpu_fleet_replicas"
FLEET_GENERATION = "dl4jtpu_fleet_generation"
FLEET_ROUTED = "dl4jtpu_fleet_routed_total"
FLEET_AFFINITY_HITS = "dl4jtpu_fleet_affinity_hits_total"
FLEET_AFFINITY_MISSES = "dl4jtpu_fleet_affinity_misses_total"
FLEET_MIGRATIONS = "dl4jtpu_fleet_migrations_total"
FLEET_MIGRATED_REQUESTS = "dl4jtpu_fleet_migrated_requests_total"
FLEET_DEAD_REPLICAS = "dl4jtpu_fleet_dead_replicas_total"
FLEET_SCALE_EVENTS = "dl4jtpu_fleet_scale_events_total"

#: cross-process fleet transport (serving/fleet/transport.py +
#: agent.py register these): shared-fs mailbox command traffic at the
#: agent (``kind`` labels admit/revoke/shutdown), at-least-once
#: duplicates dropped by request-id dedupe, torn command files moved
#: to quarantine instead of crashing the poll loop, and the journal
#: token events the router relayed into local stream handles.
FLEET_TRANSPORT_COMMANDS = "dl4jtpu_fleet_transport_commands_total"
FLEET_TRANSPORT_DUPLICATES = \
    "dl4jtpu_fleet_transport_duplicates_total"
FLEET_TRANSPORT_QUARANTINED = \
    "dl4jtpu_fleet_transport_quarantined_total"
FLEET_RELAYED_TOKENS = "dl4jtpu_fleet_relayed_tokens_total"
FLEET_REPLACED_REQUESTS = "dl4jtpu_fleet_replaced_requests_total"
#: journal lines that were complete (newline-terminated) yet
#: undecodable — real transport corruption, distinct from the torn
#: tail a crashed writer leaves (which is silently retried). The
#: router promotes ``JournalReader.corrupt`` through this counter so
#: /metrics and flight-recorder bundles see it, not just ``health()``.
FLEET_TRANSPORT_CORRUPT_LINES = \
    "dl4jtpu_fleet_transport_corrupt_lines_total"

#: disaggregated prefill/decode (serving/fleet/pages.py, prefill.py;
#: the agent and router register these): the content-addressed KV page
#: store on the fleet root. ``published``/``ship_bytes`` count store
#: writes, ``imported`` counts pages a decode replica mapped into its
#: pool instead of re-priming, hits/misses count store probes at
#: admission, ``quarantined`` counts torn/mismatched entries moved
#: aside, ``prefills`` counts CMD_PREFILL admissions a prefill replica
#: served.
FLEET_PAGES_PUBLISHED = "dl4jtpu_fleet_pages_published_total"
FLEET_PAGES_IMPORTED = "dl4jtpu_fleet_pages_imported_total"
FLEET_PAGE_STORE_HITS = "dl4jtpu_fleet_page_store_hits_total"
FLEET_PAGE_STORE_MISSES = "dl4jtpu_fleet_page_store_misses_total"
FLEET_PAGES_QUARANTINED = "dl4jtpu_fleet_pages_quarantined_total"
FLEET_PAGE_SHIP_BYTES = "dl4jtpu_fleet_page_ship_bytes_total"
FLEET_PREFILLS = "dl4jtpu_fleet_prefills_total"

#: survivability layer (supervisor.py / overload.py register these)
SERVING_ENGINE_REBUILDS = "dl4jtpu_serving_engine_rebuilds_total"
SERVING_ENGINE_ESCALATIONS = \
    "dl4jtpu_serving_engine_escalations_total"
SERVING_RECOVERED_REQUESTS = \
    "dl4jtpu_serving_recovered_requests_total"
SERVING_SHED = "dl4jtpu_serving_shed_total"
SERVING_EARLY_REJECTED = "dl4jtpu_serving_early_rejected_total"
SERVING_BROWNOUT_LEVEL = "dl4jtpu_serving_brownout_level"
SERVING_DRAINING = "dl4jtpu_serving_draining"

_COUNTERS = (
    (SERVING_REQUESTS, "Serving requests received"),
    (SERVING_ERRORS, "Serving requests failed by model errors"),
    (SERVING_DEADLINE_EXCEEDED, "Requests that outlived their deadline"),
    (SERVING_QUEUE_REJECTED, "Requests rejected by fail_fast admission"),
)


def scrape_probe(component, fn, default: float = 0.0):
    """Scrape-time gauge callback over a WEAK reference to `component`:
    reads ``fn(component)`` at collection time, `default` once the
    component is collected. The one probe shape every serving gauge
    uses — fix it here, every component's gauges follow."""
    ref = weakref.ref(component)

    def read():
        inst = ref()
        return default if inst is None else float(fn(inst))
    return read


def register_serving_metrics(component, model: str,
                             registry: Optional[MetricsRegistry] = None
                             ) -> Dict[str, object]:
    """Register the shared serving series for `component` and return its
    resolved counter handles ``{metric name: handle}``.

    `component` must expose ``is_healthy()`` / ``is_ready()`` /
    ``queue_depth()``; the healthy/ready/queue-depth gauges are
    scrape-time callbacks over a weakref to it, so a crashed worker
    flips them on the next scrape with no event having fired. One
    serving stack per `model` label value per registry; a newer
    instance takes over the series.
    """
    r = registry or global_registry()
    handles = {
        metric: r.counter(metric, help, ("model",)).labels(model=model)
        for metric, help in _COUNTERS}
    r.gauge(SERVING_HEALTHY, "Serving loop alive (1) or down (0)",
            ("model",)).set_function(
        scrape_probe(component,
                     lambda s: 1.0 if s.is_healthy() else 0.0),
        model=model)
    r.gauge(SERVING_READY, "Serving admitting requests (1) or not (0)",
            ("model",)).set_function(
        scrape_probe(component,
                     lambda s: 1.0 if s.is_ready() else 0.0),
        model=model)
    r.gauge(SERVING_QUEUE_DEPTH,
            "Requests waiting in the admission queue",
            ("model",)).set_function(
        scrape_probe(component, lambda s: s.queue_depth()), model=model)
    return handles
