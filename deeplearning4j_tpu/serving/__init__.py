"""Serving: continuous-batching generation behind admission control.

The generation counterpart of ``parallel.ParallelInference`` (which
coalesces fixed-shape classification batches): a ``GenerationEngine``
owns a fixed S-slot streaming-state arena, admits requests into free
slots mid-flight (prefill via the shared width-bucketed padded prime),
advances ALL active slots with one canonical jitted decode dispatch per
step, retires each request individually (stop token / length /
capacity / deadline / cancel), and streams tokens back through
per-request ``GenerationStream`` handles. Admission control (bounded
priority queue, ``block`` | ``fail_fast``), per-request deadlines, and
the shared ``dl4jtpu_serving_*`` telemetry ride around it.

See ARCHITECTURE.md "Serving engine".
"""

from deeplearning4j_tpu.serving.engine import GenerationEngine  # noqa: F401
from deeplearning4j_tpu.serving.errors import (  # noqa: F401
    EngineShutdown, InferenceTimeout, RequestCancelled, ServingQueueFull)
from deeplearning4j_tpu.serving.request import (  # noqa: F401
    GenerationRequest, GenerationStream)
from deeplearning4j_tpu.serving.scheduler import AdmissionQueue  # noqa: F401

__all__ = ["AdmissionQueue", "EngineShutdown", "GenerationEngine",
           "GenerationRequest", "GenerationStream", "InferenceTimeout",
           "RequestCancelled", "ServingQueueFull"]
