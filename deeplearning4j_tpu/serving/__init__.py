"""Serving: continuous-batching generation behind admission control.

The generation counterpart of ``parallel.ParallelInference`` (which
coalesces fixed-shape classification batches): a ``GenerationEngine``
owns a fixed S-slot streaming-state arena, admits requests into free
slots mid-flight (prefill via the shared width-bucketed padded prime),
advances ALL active slots with one canonical jitted decode dispatch per
step, retires each request individually (stop token / length /
capacity / deadline / cancel), and streams tokens back through
per-request ``GenerationStream`` handles. Admission control (bounded
priority queue, ``block`` | ``fail_fast``), per-request deadlines, and
the shared ``dl4jtpu_serving_*`` telemetry ride around it.

Serving engine v2 layers on top: a block-paged KV arena
(``PagedKVConfig`` — capacity as a token budget with per-slot page
tables over one refcounted pool), a full-block prompt ``PrefixCache``
(shared system prompts prime once), and in-engine speculative decoding
(``SpeculationConfig`` — a host draft + one widened verify dispatch per
step). ``PagedKVConfig(kv_dtype="int8")`` makes the pool's
authoritative KV storage quantized (``serving/quant.py`` — per-page
power-of-two amax scales, dequantize-on-read in both direct decode
impls, a pinned accuracy envelope vs bf16, ~2x pages under a
``total_bytes=`` budget; ``"auto"`` opts in only through a calibrated
crossover entry).

The survivability layer keeps all of it up under faults and load:
``EngineSupervisor`` (request-preserving arena rebuilds from the
host-side ledger, budgeted restarts, escalation to fail-all),
``OverloadConfig``/``OverloadController`` (SLO-breach shedding,
deadline-based early rejection, the page-pressure brownout ladder),
and ``GenerationEngine.drain()`` (the clean restart handoff).

The fleet layer (``serving/fleet``) composes N engine replicas behind
one ``FleetRouter``: prefix-affinity placement, ledger-based live
migration (``RequestLedgerEntry`` — the supervisor's rebuild payload
made public, so recovery and migration share one engine code path),
and signal-driven autoscaling with hysteresis.

See ARCHITECTURE.md "Serving engine", "Paged KV, prefix cache &
speculation", "Serving survivability", and "Serving fleet".
"""

from deeplearning4j_tpu.serving.engine import (  # noqa: F401
    GenerationEngine, SpeculationConfig)
from deeplearning4j_tpu.serving.errors import (  # noqa: F401
    EngineShutdown, InferenceTimeout, NoReplicaAvailable,
    RequestCancelled, ServingOverloaded, ServingQueueFull)
from deeplearning4j_tpu.serving.overload import (  # noqa: F401
    OverloadConfig, OverloadController)
from deeplearning4j_tpu.serving.paging import (  # noqa: F401
    PagedKVConfig, PageExhausted, PagePool)
from deeplearning4j_tpu.serving.prefix_cache import PrefixCache  # noqa: F401
from deeplearning4j_tpu.serving.request import (  # noqa: F401
    GenerationRequest, GenerationStream, LEDGER_VERSION,
    RequestLedgerEntry, RequestTrace, ttft_attribution)
from deeplearning4j_tpu.serving.scheduler import (  # noqa: F401
    AdmissionQueue, QueueSnapshot)
from deeplearning4j_tpu.serving.supervisor import (  # noqa: F401
    EngineSupervisor)
from deeplearning4j_tpu.serving.fleet import (  # noqa: F401
    AutoscaleConfig, FleetAutoscaler, FleetConfig, FleetMembership,
    FleetReplica, FleetRouter, FleetSignals, MigrationReport,
    PageStore, PrefillAgent, ProcessFleetRouter, ReplicaAgent)

__all__ = ["AdmissionQueue", "AutoscaleConfig", "EngineShutdown",
           "EngineSupervisor", "FleetAutoscaler", "FleetConfig",
           "FleetMembership", "FleetReplica", "FleetRouter",
           "FleetSignals", "GenerationEngine", "GenerationRequest",
           "GenerationStream", "InferenceTimeout", "LEDGER_VERSION",
           "MigrationReport", "NoReplicaAvailable", "OverloadConfig",
           "OverloadController", "PagedKVConfig", "PageExhausted",
           "PagePool", "PageStore", "PrefillAgent", "PrefixCache",
           "ProcessFleetRouter", "QueueSnapshot", "ReplicaAgent",
           "RequestCancelled", "RequestLedgerEntry", "RequestTrace",
           "ServingOverloaded", "ServingQueueFull", "SpeculationConfig",
           "ttft_attribution"]
