"""Prompt prefix cache over the block-paged KV pool.

Prompts sharing a leading token-block sequence (the canonical case: one
system prompt in front of every request) should prime once. Each cache
entry maps a *full-block prefix* to the pool page holding its last
block's K/V. The key is ``(parent entry id, block tokens)`` — parent
ids are unique forever (monotonic, never reused), so the key pins the
ENTIRE prefix exactly without storing it: the entry for blocks [0..k]
is only reachable through the chain of k matches before it, a lookup
walks block by block from the root (parent id 0) and stops at the
first miss, and a stale child whose parent was evicted can never be
re-reached (no later entry ever takes the old parent's id). Keys cost
O(page_size) per block instead of the O(prefix) cumulative-tuple
alternative, which goes quadratic on long system prompts.

On a hit the engine maps the matched pages straight into the new slot's
page table (refcount++ — physically shared, read-only by convention)
and prefills ONLY the suffix from the block boundary: TTFT drops from
full-prompt prefill to queue-wait + suffix prefill. The first partial
block past the match gets a fresh page the suffix prefill fills —
copy-on-extend: a slot never writes into a shared page, because writes
land at positions >= its prompt end and full prompt blocks end at or
before it. At least one suffix token is always re-primed (a lookup
never matches past ``prompt_len - 1``) so the admission draw always has
a freshly computed next-token distribution.

Exactness: a cached page holds exactly the K/V bytes a full prefill
would compute for those positions — causal attention makes prefix K/V
a function of the prefix tokens alone — so cache-on output is
bit-identical to cache-off (test-pinned). Recurrent (LSTM h/c) state is
a function of the whole prefix but lives OUTSIDE the pages, so the
engine refuses to enable the cache for nets carrying recurrent
streaming state.

Eviction: entries are LRU-ordered (a lookup touches every matched
level, parents before children, so a chain ages coherently); an entry
is evictable once no slot maps its page (pool refcount 1 — the cache's
own reference). Under page pressure the engine asks for the shortfall;
``evict`` walks oldest-first and frees what it can. Evicting a parent
strands its children unreachable — they simply age out next.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple

from deeplearning4j_tpu.serving.paging import PagePool

__all__ = ["PrefixCache", "ROOT_DIGEST", "block_digest", "chain_digests"]

#: the chain root every prefix digest descends from — a fixed tag, not
#: an empty string, so a digest can never collide with "no parent"
ROOT_DIGEST = hashlib.sha256(b"dl4jtpu/prefix-chain-root").hexdigest()


def block_digest(parent: str, tokens: Sequence[int]) -> str:
    """Content address of one full token block GIVEN its parent's
    digest: ``H(parent | token csv)``. Chaining the parent in makes the
    digest pin the ENTIRE prefix, exactly like the cache's
    ``(parent id, block)`` keys pin it — two prompts share a digest iff
    they share every token up to and including this block. This is the
    fleet-wide identity of a KV page (``serving/fleet/pages.py``): any
    replica of a homogeneous fleet computes the same digest for the
    same prefix, so a page primed anywhere names the bytes everywhere."""
    h = hashlib.sha256()
    h.update(parent.encode("ascii"))
    h.update(b"|")
    h.update(",".join(str(int(t)) for t in tokens).encode("ascii"))
    return h.hexdigest()


def chain_digests(prompt: Sequence[int], page_size: int) -> List[str]:
    """Digest chain for every FULL block of `prompt` (block i's entry
    is the digest of blocks [0..i]) — a pure function of the tokens,
    computable by a router that holds no pages at all (page-locality
    scoring) and by an importing agent before it touches the store."""
    out: List[str] = []
    parent = ROOT_DIGEST
    for i in range(len(prompt) // page_size):
        parent = block_digest(
            parent, prompt[i * page_size:(i + 1) * page_size])
        out.append(parent)
    return out


class PrefixCache:
    """Full-block prompt prefix cache over a :class:`PagePool`."""

    #: root parent id — entry ids start at 1 and are never reused
    _ROOT = 0

    def __init__(self, pool: PagePool):
        self._pool = pool
        self._ps = pool.page_size
        #: (parent entry id, block token tuple) ->
        #:     (page id, entry id, chain digest)
        #: the digest is the entry's fleet-wide content address
        #: (``block_digest`` chained from ``ROOT_DIGEST``) — carried so
        #: status files can advertise held prefixes without re-hashing
        self._entries: "OrderedDict[tuple, Tuple[int, int, str]]" = \
            OrderedDict()
        self._next_id = 1
        self.hits = 0          # requests that reused >= 1 block
        self.misses = 0        # requests that reused none
        self.reused_tokens = 0

    def __len__(self) -> int:
        return len(self._entries)

    def _block(self, prompt, i: int) -> tuple:
        return tuple(prompt[i * self._ps:(i + 1) * self._ps])

    # ------------------------------------------------------------------
    def lookup(self, prompt: Sequence[int]) -> Tuple[int, List[int]]:
        """Longest cached full-block prefix of `prompt`, capped so at
        least one prompt token remains for the suffix prefill. Returns
        ``(n_tokens_matched, page_ids)`` and counts a hit/miss; the
        caller owns retaining the returned pages."""
        limit = (len(prompt) - 1) // self._ps    # usable full blocks
        pages: List[int] = []
        parent = self._ROOT
        for i in range(limit):
            key = (parent, self._block(prompt, i))
            ent = self._entries.get(key)
            if ent is None:
                break
            self._entries.move_to_end(key)   # LRU touch, parent first
            pages.append(ent[0])
            parent = ent[1]
        if pages:
            self.hits += 1
            self.reused_tokens += len(pages) * self._ps
        else:
            self.misses += 1
        return len(pages) * self._ps, pages

    def insert(self, prompt: Sequence[int], table: Sequence[int]) -> None:
        """Register every full block of a just-prefilled prompt whose
        page the slot owns (`table` = the slot's block-ordered pages).
        Existing entries are touched, new ones take a cache reference on
        the slot's page — the page then outlives the request (refcount
        drops to the cache's 1 at retirement) and stays warm until
        evicted."""
        parent = self._ROOT
        parent_digest = ROOT_DIGEST
        for i in range(len(prompt) // self._ps):
            block = self._block(prompt, i)
            key = (parent, block)
            ent = self._entries.get(key)
            if ent is not None:
                self._entries.move_to_end(key)
                parent = ent[1]
                parent_digest = ent[2]
                continue
            page = table[i]
            self._pool.retain(page)
            ent_id = self._next_id
            self._next_id += 1
            parent_digest = block_digest(parent_digest, block)
            self._entries[key] = (page, ent_id, parent_digest)
            parent = ent_id

    # ------------------------------------------------------------------
    def held_blocks(self, prompt: Sequence[int]) -> int:
        """Leading full blocks of `prompt` already cached, WITHOUT
        touching LRU order or hit/miss stats — a pure probe for the
        fleet import path to decide which store blocks it still needs
        (capped like ``lookup`` so a full-prompt match never counts)."""
        limit = (len(prompt) - 1) // self._ps
        parent = self._ROOT
        held = 0
        for i in range(limit):
            ent = self._entries.get((parent, self._block(prompt, i)))
            if ent is None:
                break
            parent = ent[1]
            held += 1
        return held

    def digests(self, limit: Optional[int] = None) -> List[str]:
        """Chain digests of cached entries in LRU order (most recently
        used LAST), optionally capped to the `limit` most recent —
        what a replica advertises in its status file so the router can
        score page locality."""
        digs = [ent[2] for ent in self._entries.values()]
        if limit is not None and len(digs) > limit:
            digs = digs[-limit:]
        return digs

    # ------------------------------------------------------------------
    def evictable_pages(self) -> int:
        """Pages reclaimable right now (entries no slot maps)."""
        return sum(1 for ent in self._entries.values()
                   if self._pool.refcount(ent[0]) == 1)

    def evict(self, n_pages: int) -> int:
        """Free up to `n_pages` pages, oldest entries first, skipping
        entries still mapped by an active slot. Returns pages freed."""
        freed = 0
        for key in list(self._entries):
            if freed >= n_pages:
                break
            page = self._entries[key][0]
            if self._pool.refcount(page) != 1:
                continue                     # a slot still maps it
            del self._entries[key]
            self._pool.release(page)
            freed += 1
        return freed

    def clear(self) -> int:
        """Drop every unmapped entry (shutdown / tests)."""
        return self.evict(len(self._entries))
