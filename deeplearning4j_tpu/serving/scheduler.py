"""Admission control for the generation engine.

A bounded priority queue between ``submit()`` callers and the engine's
admission step, with the same two admission policies as
``ParallelInference``: ``block`` (callers wait for space, bounded by
their request deadline) and ``fail_fast`` (``ServingQueueFull``
immediately — the load-shedding mode a latency-SLO front end wants).
Within the bound, higher ``priority`` requests are admitted first;
arrival order breaks ties (stable FIFO per class).
"""

from __future__ import annotations

import dataclasses
import heapq
import threading
import time
from typing import Dict, List, Optional

from deeplearning4j_tpu.serving.errors import (
    EngineShutdown, InferenceTimeout, ServingQueueFull)
from deeplearning4j_tpu.serving.request import GenerationRequest


@dataclasses.dataclass(frozen=True)
class QueueSnapshot:
    """Non-mutating view of the admission queue for PLACEMENT scoring:
    total depth, per-priority depths, and the oldest enqueue's age. The
    fleet router reads this (via ``GenerationEngine.queue_snapshot``)
    instead of lock-probing queue internals — one immutable copy taken
    under the queue lock, safe to score against while the engine keeps
    admitting."""

    depth: int
    per_priority: Dict[int, int]
    oldest_wait_s: Optional[float]


class AdmissionQueue:
    """Bounded priority admission queue (``block`` | ``fail_fast``)."""

    def __init__(self, limit: int = 64, policy: str = "block"):
        if policy not in ("block", "fail_fast"):
            raise ValueError(f"queue_policy must be 'block' or "
                             f"'fail_fast', got {policy!r}")
        if limit < 1:
            raise ValueError(f"queue_limit must be >= 1, got {limit}")
        self.limit = limit
        self.policy = policy
        self._cond = threading.Condition()
        self._heap: List[tuple] = []     # (-priority, seq, request)
        self._seq = 0
        self._closed = False

    def depth(self) -> int:
        with self._cond:
            return len(self._heap)

    def full(self) -> bool:
        with self._cond:
            return len(self._heap) >= self.limit

    def snapshot(self, now: Optional[float] = None) -> QueueSnapshot:
        """One consistent, non-mutating placement view: total depth,
        per-priority class depths, and how long the oldest queued
        request has waited (None when empty). Reads only — no pop, no
        LRU touch, no notify."""
        now = time.monotonic() if now is None else now
        with self._cond:
            per: Dict[int, int] = {}
            oldest: Optional[float] = None
            for _, _, req in self._heap:
                per[req.priority] = per.get(req.priority, 0) + 1
                if oldest is None or req.submit_t < oldest:
                    oldest = req.submit_t
            return QueueSnapshot(
                depth=len(self._heap), per_priority=per,
                oldest_wait_s=None if oldest is None else now - oldest)

    def peek_all(self) -> List[GenerationRequest]:
        """Queued requests in admission order (priority desc, FIFO
        within a class) WITHOUT removing them — the ledger-export view."""
        with self._cond:
            return [req for _, _, req in
                    sorted(self._heap, key=lambda it: (it[0], it[1]))]

    def requeue(self, req: GenerationRequest) -> None:
        """Force-enqueue bypassing the limit and the closed flag: the
        re-admission path for ledger survivors (supervisor rebuild
        overflow, fleet migration). Survivors were already admitted
        once — dropping them at a full queue would turn a recovery into
        a failure — and the transient over-limit is bounded by the
        SOURCE's queue bound. Priority ordering is preserved; FIFO
        order within a class restarts at requeue order."""
        with self._cond:
            heapq.heappush(self._heap, (-req.priority, self._seq, req))
            self._seq += 1
            self._cond.notify_all()

    def depth_ahead(self, priority: int) -> int:
        """Queued requests that would be admitted BEFORE a new request
        of `priority`: every strictly-higher class plus the whole
        equal-priority class (admission is FIFO within a class, so an
        arriving request queues behind all of its peers). The overload
        controller's queue-position estimate for deadline-based early
        rejection."""
        with self._cond:
            return sum(1 for item in self._heap
                       if item[2].priority >= priority)

    def shed_lowest(self, keep: int) -> List[GenerationRequest]:
        """Remove (and return) queued requests until at most `keep`
        remain, victimizing the LOWEST priority class first and, within
        a class, the most recent arrival first (the request that would
        have waited longest sheds first — earlier arrivals have the
        most sunk queue-wait and the best chance of admission before
        their deadline). The engine fails the returned handles with
        ``ServingOverloaded``; the queue never touches handles
        itself."""
        with self._cond:
            n = len(self._heap) - max(0, int(keep))
            if n <= 0:
                return []
            # victims: ascending priority, then descending arrival seq
            order = sorted(self._heap,
                           key=lambda it: (-it[0], -it[1]))
            victims = order[:n]
            gone = {id(it[2]) for it in victims}
            self._heap = [it for it in self._heap
                          if id(it[2]) not in gone]
            heapq.heapify(self._heap)
            self._cond.notify_all()      # wake blocked submitters
            return [it[2] for it in victims]

    def submit(self, req: GenerationRequest) -> None:
        """Enqueue under the admission policy. ``block`` waits for space
        bounded by the request's deadline (forever with none — the
        legacy contract); expiry raises InferenceTimeout, shutdown
        raises EngineShutdown, and ``fail_fast`` at the limit raises
        ServingQueueFull."""
        with self._cond:
            if self._closed:
                raise EngineShutdown("admission queue closed")
            if self.policy == "fail_fast" and \
                    len(self._heap) >= self.limit:
                raise ServingQueueFull(
                    f"admission queue at limit ({self.limit} requests)")
            while len(self._heap) >= self.limit:
                budget = 0.2 if req.deadline is None else \
                    min(0.2, req.deadline - time.monotonic())
                if budget <= 0:
                    raise InferenceTimeout(
                        "deadline expired waiting for queue space")
                self._cond.wait(budget)
                if self._closed:
                    raise EngineShutdown("admission queue closed")
            heapq.heappush(self._heap, (-req.priority, self._seq, req))
            self._seq += 1
            self._cond.notify_all()

    def reap(self, now: float) -> List[GenerationRequest]:
        """Remove (and return) queued requests that are cancelled or
        past their deadline — called every engine step so a queued
        request's deadline fires on time even while the arena is full
        and nothing can be popped."""
        with self._cond:
            dead = [item[2] for item in self._heap
                    if item[2].handle.cancelled
                    or (item[2].deadline is not None
                        and now >= item[2].deadline)]
            if dead:
                gone = set(map(id, dead))
                self._heap = [item for item in self._heap
                              if id(item[2]) not in gone]
                heapq.heapify(self._heap)
                self._cond.notify_all()
            return dead

    def pop(self, admissible=None) -> Optional[GenerationRequest]:
        """Highest-priority queued request, or None (non-blocking).
        Deadline/cancellation checks belong to the engine's admission
        step, which fails the popped request's handle itself.

        `admissible(req)` (optional) is consulted on the HEAD request
        only: False leaves it queued and returns None — the paged
        engine's head-of-line block when the head needs more free pages
        than exist, so admission order stays FIFO-per-priority instead
        of starving big requests behind a stream of small ones (pages
        free as active requests retire, so the head always eventually
        fits; requests that can NEVER fit are rejected at submit)."""
        with self._cond:
            if not self._heap:
                return None
            if admissible is not None and \
                    not admissible(self._heap[0][2]):
                return None
            _, _, req = heapq.heappop(self._heap)
            self._cond.notify_all()      # wake blocked submitters
            return req

    def wait(self, timeout: float) -> None:
        """Park until work arrives (or `timeout` seconds — the engine's
        deadline-polling tick when idle)."""
        with self._cond:
            if not self._heap and not self._closed:
                self._cond.wait(timeout)

    def close(self) -> List[GenerationRequest]:
        """Refuse new submissions and drain everything queued, in
        admission order (priority desc, FIFO within a class — the
        ledger-export path re-admits the drained list head-first on
        another replica, so heap-internal order would invert
        priorities there; the fail-everything callers don't care)."""
        with self._cond:
            self._closed = True
            drained = [req for _, _, req in
                       sorted(self._heap, key=lambda it: (it[0], it[1]))]
            self._heap.clear()
            self._cond.notify_all()
            return drained
