"""SLO-aware overload control: shed, reject early, brown out.

An overloaded serving engine that admits everything serves nobody: the
queue grows, every request's time-to-first-token blows through the SLO,
and prefill work is wasted on requests that will be dead on delivery.
This module is the admission-side counterweight, three independent
levers in escalating order of reach (the µ-cuDNN instinct applied to
serving: under pressure degrade FEATURES, never availability):

1. **Shedding** — the engine feeds observed queue-wait / TTFT samples
   to the controller; when a configured SLO is in *sustained* breach
   (a breach fraction over a sample window, not one slow request), the
   lowest-priority most-recent queued work is shed with a typed
   :class:`~.errors.ServingOverloaded` until the queue is back to a
   servable depth. Shedding queued (never-prefilled) work costs zero
   device cycles and immediately shortens every survivor's wait.
2. **Early rejection** — a request submitted with a deadline that
   provably cannot be met given the queue estimate (position-ahead ÷
   observed admission rate, or an injected estimator) is refused AT
   SUBMIT with ``ServingOverloaded``: failing in O(1) at the front
   door beats spending a prefill dispatch on a corpse and beats making
   the caller discover the timeout themselves `deadline` seconds later.
3. **Brownout** — under KV-page pressure the engine degrades features
   in a fixed ladder: drop the speculation gamma → disable speculation
   → stop prefix-cache inserts; each rung restores automatically (with
   hysteresis) when pressure clears. Every rung keeps the dispatch
   shapes canonical — a reduced gamma pads the SAME widened verify
   dispatch with fewer real proposals — so brownout transitions cause
   zero retraces.

The controller is pure host-side policy: the engine owns all device
work and all handle failures; the controller only decides. Sampling
state is lock-guarded because ``reject_at_submit`` runs on caller
threads while observations arrive from the engine's step loop.

See ARCHITECTURE.md "Serving survivability".
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

__all__ = ["OverloadConfig", "OverloadController"]

#: brownout rungs (the ladder order is part of the contract)
BROWNOUT_OFF = 0
BROWNOUT_REDUCED_GAMMA = 1
BROWNOUT_NO_SPECULATION = 2
BROWNOUT_NO_PREFIX_INSERTS = 3


@dataclass
class OverloadConfig:
    """Knobs for :class:`OverloadController`.

    ``ttft_slo_s`` / ``queue_wait_slo_s``: the latency objectives; a
    sustained breach of EITHER (at least ``breach_fraction`` of the
    last ``breach_window`` admissions over the objective, with at least
    ``min_samples`` observed) triggers shedding down to
    ``shed_to_depth`` queued requests (default: the engine's slot
    count — one ready successor per slot is servable depth; deeper is
    speculation about the future).

    ``early_reject``: refuse deadline-carrying submits whose deadline
    cannot be met given ``queue_eta`` (an injectable
    ``(engine, request, now) -> seconds`` estimator; default: queue
    position ahead ÷ the observed admission rate over the sample
    window, never rejecting before ``min_samples`` admissions have
    calibrated the rate).

    ``brownout_enter_fracs``: free-page fractions at which rungs 1..3
    of the brownout ladder engage; a rung releases when the free
    fraction recovers past its threshold + ``brownout_clear_margin``
    (hysteresis — a pool oscillating at a threshold must not flap;
    the release point is capped at 1.0 so a fully free pool always
    releases even when threshold + margin exceeds it).
    ``brownout_gamma`` is the reduced speculation gamma at rung 1
    (default: half the configured gamma, at least 1)."""

    ttft_slo_s: Optional[float] = None
    queue_wait_slo_s: Optional[float] = None
    breach_window: int = 16
    breach_fraction: float = 0.5
    min_samples: int = 4
    shed_to_depth: Optional[int] = None
    early_reject: bool = True
    queue_eta: Optional[Callable] = None
    #: admission-rate samples older than this never inform eta(): after
    #: a traffic lull the stale span would read as a dismal rate and
    #: spuriously reject meetable deadlines at the next burst's start
    rate_horizon_s: float = 60.0
    brownout_enter_fracs: Tuple[float, float, float] = (0.15, 0.08, 0.03)
    brownout_clear_margin: float = 0.10
    brownout_gamma: Optional[int] = None

    def __post_init__(self):
        if not 0.0 < self.breach_fraction <= 1.0:
            raise ValueError(f"breach_fraction must be in (0, 1], got "
                             f"{self.breach_fraction}")
        if self.breach_window < 1:
            raise ValueError(f"breach_window must be >= 1, got "
                             f"{self.breach_window}")
        if self.min_samples < 1:
            raise ValueError(f"min_samples must be >= 1, got "
                             f"{self.min_samples}")
        fr = self.brownout_enter_fracs
        if len(fr) != 3 or not all(
                0.0 <= b <= a <= 1.0
                for a, b in zip(fr, fr[1:])) or not 0 <= fr[0] <= 1:
            raise ValueError(
                "brownout_enter_fracs must be 3 non-increasing "
                f"fractions in [0, 1], got {fr!r}")
        if self.brownout_clear_margin < 0:
            raise ValueError(f"brownout_clear_margin must be >= 0, got "
                             f"{self.brownout_clear_margin}")
        if self.brownout_gamma is not None and self.brownout_gamma < 1:
            raise ValueError(f"brownout_gamma must be >= 1, got "
                             f"{self.brownout_gamma}")


class OverloadController:
    """Decides shedding, early rejection, and the brownout rung for one
    engine. All inputs are observations the engine pushes; all outputs
    are decisions the engine executes."""

    def __init__(self, config: Optional[OverloadConfig] = None):
        self.config = config if config is not None else OverloadConfig()
        w = self.config.breach_window
        self._mu = threading.Lock()
        self._engine = None
        self._ttft = deque(maxlen=w)
        self._queue_wait = deque(maxlen=w)
        self._admit_t = deque(maxlen=max(2, w))
        self.level = BROWNOUT_OFF
        self.shed_total = 0
        self.early_rejected_total = 0

    def _bind(self, engine) -> None:
        """One controller per engine: the sample windows are SLO
        evidence for a SINGLE engine's traffic — shared across two
        engines, one engine's slow TTFTs would shed the other's queue
        and skew its admission-rate estimate. (The same contract as
        ``EngineSupervisor._bind``.)"""
        if self._engine is not None and self._engine is not engine:
            raise ValueError(
                "one OverloadController controls one engine — construct "
                "a fresh controller (or pass OverloadConfig) per "
                "GenerationEngine")
        self._engine = engine

    # -- observations (engine step loop) -------------------------------
    def observe_queue_wait(self, seconds: float) -> None:
        with self._mu:
            self._queue_wait.append(float(seconds))

    def observe_ttft(self, seconds: float, now: float) -> None:
        """One admission completed prefill: record its TTFT and the
        admission instant (the rate base for the queue estimate)."""
        with self._mu:
            self._ttft.append(float(seconds))
            self._admit_t.append(float(now))

    def reset_observations(self) -> None:
        """Drop the sample windows (breach evidence + admission-rate
        base). The engine calls this after ``warmup()``: synthetic
        warmup admissions carry COMPILE time in their TTFT and would
        otherwise read as a sustained breach (and a dismal admission
        rate) the moment real traffic arrives."""
        with self._mu:
            self._ttft.clear()
            self._queue_wait.clear()
            self._admit_t.clear()

    # -- shedding -------------------------------------------------------
    def _breached(self, samples, slo: Optional[float]) -> bool:
        if slo is None or len(samples) < self.config.min_samples:
            return False
        over = sum(1 for s in samples if s > slo)
        return over >= self.config.breach_fraction * len(samples)

    def sustained_breach(self) -> bool:
        with self._mu:
            return (self._breached(self._ttft, self.config.ttft_slo_s)
                    or self._breached(self._queue_wait,
                                      self.config.queue_wait_slo_s))

    def shed(self, engine) -> List:
        """Victims to fail with ``ServingOverloaded`` this step: under a
        sustained breach, the queue's lowest-priority tail beyond the
        servable depth. The breach window resets after a shed so the
        next round needs fresh post-shed evidence (one burst of slow
        admissions must not bleed the queue dry for `window` more
        steps)."""
        if not self.sustained_breach():
            return []
        keep = self.config.shed_to_depth
        if keep is None:
            keep = engine.slots
        victims = engine._pending.shed_lowest(keep)
        if victims:
            with self._mu:
                self._ttft.clear()
                self._queue_wait.clear()
            self.shed_total += len(victims)
        return victims

    # -- early rejection ------------------------------------------------
    def eta(self, engine, req, now: float) -> Optional[float]:
        """Estimated seconds until `req` would be admitted, or None when
        no estimate is available yet (never reject on ignorance)."""
        if self.config.queue_eta is not None:
            return self.config.queue_eta(engine, req, now)
        with self._mu:
            # age out lull-stale samples: a 10-minute-old admission
            # must not stretch the span into a near-zero rate
            cut = now - self.config.rate_horizon_s
            while self._admit_t and self._admit_t[0] < cut:
                self._admit_t.popleft()
            if len(self._admit_t) < max(2, self.config.min_samples):
                return None
            span = self._admit_t[-1] - self._admit_t[0]
            if span <= 0:
                return None
            rate = (len(self._admit_t) - 1) / span
        ahead = engine._pending.depth_ahead(req.priority)
        return ahead / rate

    def reject_at_submit(self, engine, req,
                         now: float) -> Optional[str]:
        """A reason string when `req`'s deadline provably cannot be met
        given the queue estimate (the engine raises ServingOverloaded
        with it); None admits."""
        if not self.config.early_reject or req.deadline is None:
            return None
        est = self.eta(engine, req, now)
        if est is None:
            return None
        if now + est >= req.deadline:
            with self._mu:       # submit runs on caller threads
                self.early_rejected_total += 1
            return (f"deadline cannot be met: ~{est:.3f}s queue ahead "
                    f"vs {req.deadline - now:.3f}s of deadline budget "
                    f"(early rejection beats wasted prefill)")
        return None

    # -- brownout -------------------------------------------------------
    def brownout_gamma(self, gamma: int) -> int:
        g = self.config.brownout_gamma
        return max(1, gamma // 2) if g is None else min(g, gamma)

    def brownout_level(self, engine) -> int:
        """Current rung of the brownout ladder for `engine`, with
        hysteresis: rungs engage at ``brownout_enter_fracs`` free-page
        fractions and release ``brownout_clear_margin`` above them.
        Engines without a paged pool never brown out (no page-pressure
        signal)."""
        pool = engine.page_pool
        if pool is None or pool.usable <= 0:
            return BROWNOUT_OFF
        free_frac = pool.free_count() / pool.usable
        fracs = self.config.brownout_enter_fracs
        desired = BROWNOUT_OFF
        for rung, frac in enumerate(fracs, start=1):
            if free_frac < frac:
                desired = rung
        if desired > self.level:
            self.level = desired
        else:
            margin = self.config.brownout_clear_margin
            while self.level > BROWNOUT_OFF and free_frac >= min(
                    1.0, fracs[self.level - 1] + margin):
                self.level -= 1
        return self.level
