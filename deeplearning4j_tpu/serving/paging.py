"""Block-paged KV storage for the generation engine (vLLM-style).

PR 5's arena sized every slot for the worst-case sequence, so admitted
concurrency was capped at S and a short request stranded the HBM of the
positions it never used. Here the authoritative KV storage is a **page
pool**: per attention leaf, a ``[P, Hkv, page_size, D]`` array of
fixed-size token pages, plus one per-slot **page table** mapping the
slot's token blocks to pool pages. Capacity becomes a *token* budget
(the µ-cuDNN memory-budget decomposition applied to serving state):

- admission checks ``prompt_len + max_new_tokens`` against **free
  pages**, not free slots — short requests hold few pages, so a pool
  sized like the old S-slot arena admits far more short requests;
- retirement returns the slot's pages to the pool immediately (host
  list ops — no device work);
- pages are refcounted, so the prefix cache can map one physical page
  into many slots' tables read-only (``serving/prefix_cache.py``).

The per-step dispatch is DIRECT by default (PR 10, ``direct=True``):
the attention step reads K/V straight through the page table (XLA
fallback folds the ``pool[table]`` gather into the dispatch; the
``serving/paged_kernel.py`` Pallas kernel reads only live pages via
scalar-prefetched tables) and the new token's K/V appends with an
O(one-token) in-dispatch write — one fixed-shape dispatch per step,
nothing materialized densely, zero retraces after warmup (see
ARCHITECTURE.md "Paged decode fast path"). ``direct=False`` keeps the
legacy round trip this module's ``gather_pages``/``scatter_pages``
implement — a jitted gather materializes the active slots' dense
``[S, Hkv, L, D]`` view, the ONE decode (or widened verify) dispatch
runs over it unchanged, and a jitted donated scatter commits the
updated view back — the bench A/B baseline, bit-identical math either
way since valid positions carry the exact bytes the slot arena would
hold. (``gather_pages`` also still serves the prefix cache's one-row
prefill installs.)

Page 0 is the reserved **null page**: table entries beyond a slot's
allocation point at it, so gathers read garbage that position-validity
masks (``kv_pos``) keep invisible, and colliding scatter writes land
harmlessly where nothing is ever read.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp

__all__ = ["PagePool", "PageExhausted", "PagedKVConfig", "gather_pages",
           "pages_needed", "scatter_pages", "set_page"]


class PageExhausted(RuntimeError):
    """The pool cannot satisfy an allocation (admission should have
    head-blocked — reaching this mid-admission is an engine bug, except
    under chaos-seized pools)."""


@dataclass
class PagedKVConfig:
    """Knobs for the block-paged arena.

    ``page_size`` tokens per page; capacity comes from ``total_pages``,
    ``total_tokens`` or ``total_bytes`` (whichever is given —
    ``total_tokens`` rounds down to whole pages; ``total_bytes`` is a
    BYTE budget the engine divides by the per-page cost of the net's kv
    leaves incl. any int8 scale sidecar, so the same budget admits ~2x
    the pages under ``kv_dtype="int8"``), defaulting to the old slot
    arena's worst case (slots × ceil(L / page_size)) so switching
    paging on never shrinks capacity. ``prefix_cache`` enables
    shared-prompt page reuse.

    ``kv_dtype`` selects the pool's authoritative storage precision:
    ``"bf16"`` (default) keeps the net's native leaf dtype — the name
    of the unquantized path, not a cast; ``"int8"`` stores symmetric
    per-(page, kv-head) int8 with a ``[P, Hkv]`` amax-scale sidecar
    per leaf (``serving/quant.py`` — quantize-once on write,
    dequantize-on-read in both decode impls; requires ``direct=True``:
    the legacy dense round trip has no quantized read path);
    ``"auto"`` consults the measured ``paged_decode_quant`` crossover
    entry for this engine's shape (tuning/plan.resolve_kv_dtype) —
    uncalibrated runs stay bf16.

    ``direct`` (default) makes decode operate DIRECTLY on the page
    pool: the attention step reads K/V through the page table and the
    new token appends with an O(one-token) in-dispatch write — no
    per-step gather/scatter round trip (ARCHITECTURE.md "Paged decode
    fast path"). ``direct=False`` keeps the legacy round trip (the
    bench A/B baseline). ``decode_impl`` selects the direct read path:
    ``"xla"`` (any backend — the gather folds into the dispatch),
    ``"pallas"`` (the serving/paged_kernel.py TPU paged-attention
    kernel; ``kernel_interpret=True`` emulates it on CPU for exactness
    tests), or ``"auto"`` (eligibility: pallas needs TPU + shapes that
    pass the kernel gate, xla otherwise; among eligible impls the
    measured kernel-crossover store makes the choice when a calibrated
    entry exists for this shape — tuning/crossover.py — with the
    kernel as the uncalibrated default)."""

    page_size: int = 8
    total_pages: Optional[int] = None
    total_tokens: Optional[int] = None
    total_bytes: Optional[int] = None
    prefix_cache: bool = True
    direct: bool = True
    decode_impl: str = "auto"
    kernel_interpret: bool = False
    kv_dtype: str = "bf16"

    def __post_init__(self):
        if self.page_size < 1:
            raise ValueError(f"page_size must be >= 1, got "
                             f"{self.page_size}")
        if self.decode_impl not in ("auto", "xla", "pallas"):
            raise ValueError(
                f"decode_impl must be 'auto', 'xla' or 'pallas', got "
                f"{self.decode_impl!r}")
        if self.kv_dtype not in ("bf16", "int8", "auto"):
            raise ValueError(
                f"kv_dtype must be 'bf16', 'int8' or 'auto', got "
                f"{self.kv_dtype!r}")
        if self.kv_dtype != "bf16" and not self.direct:
            raise ValueError(
                "kv_dtype='int8'/'auto' needs direct=True: the legacy "
                "gather/scatter round trip materializes the dense view "
                "in the net dtype and has no quantized read path")
        given = [k for k in ("total_pages", "total_tokens",
                             "total_bytes")
                 if getattr(self, k) is not None]
        if len(given) > 1:
            raise ValueError(
                f"give at most one capacity knob, got {given}")
        if self.total_pages is not None and self.total_pages < 1:
            raise ValueError(f"total_pages must be >= 1, got "
                             f"{self.total_pages}")
        if self.total_tokens is not None and \
                self.total_tokens < self.page_size:
            raise ValueError(
                f"total_tokens {self.total_tokens} is less than one "
                f"page ({self.page_size} tokens)")
        if self.total_bytes is not None and self.total_bytes < 1:
            raise ValueError(f"total_bytes must be >= 1, got "
                             f"{self.total_bytes}")

    def resolve_pages_bytes(self, page_bytes: int) -> int:
        """Pages the ``total_bytes`` budget buys at ``page_bytes`` per
        page (the engine computes page_bytes from the net's kv leaves
        via quant.kv_page_bytes — scale sidecars included)."""
        n = int(self.total_bytes) // max(1, int(page_bytes))
        if n < 1:
            raise ValueError(
                f"total_bytes {self.total_bytes} buys no page "
                f"({page_bytes} bytes/page)")
        return n

    def resolve_pages(self, slots: int, n_max: int) -> int:
        if self.total_pages is not None:
            return int(self.total_pages)
        if self.total_tokens is not None:
            return int(self.total_tokens) // self.page_size
        return int(slots) * int(n_max)


def pages_needed(total_tokens: int, page_size: int) -> int:
    """Pages a request holding `total_tokens` KV positions needs. The
    final drawn token is never fed back (the request retires on it), so
    a request of want = prompt + steps ids stores want - 1 positions —
    callers pass that."""
    return max(1, -(-int(total_tokens) // int(page_size)))


class PagePool:
    """Host-side page accounting: free list, per-page refcounts, and the
    chaos seize/restore seam. Deterministic: pages allocate in LIFO
    order, so a replayed trace maps the same physical pages.

    Refcount protocol: ``alloc`` hands out pages at refcount 1 (the
    allocating slot's reference); ``retain``/``release`` adjust for
    additional holders (the prefix cache, other slots mapping a shared
    page); a page returns to the free list when its count hits 0."""

    def __init__(self, total_pages: int, page_size: int):
        if total_pages < 2:
            raise ValueError(
                f"need >= 2 pages (page 0 is the reserved null page), "
                f"got {total_pages}")
        self.page_size = int(page_size)
        self.total_pages = int(total_pages)
        #: allocatable pages (page 0 reserved)
        self.usable = self.total_pages - 1
        self._free: List[int] = list(range(self.total_pages - 1, 0, -1))
        self._ref = [0] * self.total_pages
        self._seized: List[int] = []

    # -- accounting ----------------------------------------------------
    def free_count(self) -> int:
        return len(self._free)

    def used_count(self) -> int:
        return self.usable - len(self._free) - len(self._seized)

    def refcount(self, page: int) -> int:
        return self._ref[page]

    # -- allocation ----------------------------------------------------
    def alloc(self, n: int) -> List[int]:
        if n > len(self._free):
            raise PageExhausted(
                f"need {n} pages, {len(self._free)} free "
                f"(pool of {self.usable})")
        out = [self._free.pop() for _ in range(n)]
        for p in out:
            self._ref[p] = 1
        return out

    def retain(self, page: int) -> None:
        if self._ref[page] < 1:
            raise ValueError(f"retain of unallocated page {page}")
        self._ref[page] += 1

    def release(self, page: int) -> None:
        if self._ref[page] < 1:
            raise ValueError(f"release of unallocated page {page}")
        self._ref[page] -= 1
        if self._ref[page] == 0:
            self._free.append(page)

    # -- chaos seam (resilience.chaos.PageExhaustionInjector) ----------
    def seize(self, n: int) -> List[int]:
        """Remove `n` free pages from circulation (fault injection: a
        neighbouring tenant / fragmentation eating the pool). Seized
        pages are not 'used' — they are simply gone until restore()."""
        n = max(0, min(int(n), len(self._free)))
        taken = [self._free.pop() for _ in range(n)]
        self._seized.extend(taken)
        return taken

    def restore(self, pages=None) -> None:
        """Return seized pages (default: all of them) to the free list."""
        back = list(self._seized) if pages is None else list(pages)
        for p in back:
            self._seized.remove(p)
            self._free.append(p)


# ---------------------------------------------------------------------------
# the jitted pool <-> dense-view round trip
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("length",))
def gather_pages(pools, table, *, length: int):
    """Materialize the dense per-slot view from the pool: for each leaf
    ``[P, Hkv, ps, D]``, gather ``table`` ([S, n_max] page ids, 0 =
    null) into ``[S, Hkv, n_max*ps, D]`` and slice to the layer cache
    length. Unmapped blocks read the null page — garbage the kv_pos
    validity masks keep invisible."""
    out = []
    for pool in pools:
        _, h, _, d = pool.shape
        g = pool[table]                      # [S, n, Hkv, ps, D]
        g = jnp.moveaxis(g, 2, 1)            # [S, Hkv, n, ps, D]
        out.append(g.reshape(g.shape[0], h, -1, d)[:, :, :length, :])
    return out


@partial(jax.jit, donate_argnums=(0,))
def set_page(pool, idx, leaf):
    """Write ONE page's block into `pool` at dynamic index `idx`
    (donated: updated in place). The fleet page-import write: a shipped
    ``[Hkv, ps, D]`` KV block (or ``[Hkv]`` scale row) lands in the
    local pool without a dense round trip. `idx` is a traced scalar so
    every page of a pool shares one compiled scatter — warmup primes it
    by writing zeros to the null page."""
    return pool.at[idx].set(leaf.astype(pool.dtype))


@partial(jax.jit, donate_argnums=(0,))
def scatter_pages(pools, dense, table):
    """Commit the updated dense views back to their mapped pages
    (donated: the pool buffer is updated in place). Only pages in
    `table` are written; free pages and unmapped cache entries keep
    their bytes. Duplicate page ids (prefix-shared blocks) collide with
    bit-identical values — the dense view was gathered from the same
    page and decode never rewrites old positions — so write order is
    immaterial. Blocks past a slot's allocation write the null page."""
    out = []
    for pool, d in zip(pools, dense):
        _, h, ps, dd = pool.shape
        s, n = table.shape
        pad = n * ps - d.shape[2]
        dp = jnp.pad(d, ((0, 0), (0, 0), (0, pad), (0, 0)))
        dp = dp.reshape(s, h, n, ps, dd)
        dp = jnp.moveaxis(dp, 2, 1)          # [S, n, Hkv, ps, D]
        out.append(pool.at[table].set(dp.astype(pool.dtype)))
    return out
