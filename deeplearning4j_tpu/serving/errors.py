"""Shared serving error types.

One vocabulary of serving failures for every serving component —
``ParallelInference`` (coalesced fixed-shape classification batches) and
``GenerationEngine`` (continuous-batching generation) raise the SAME
exceptions for the same conditions, so a front end's error handling is
written once. ``parallel.inference`` re-exports these names for
back-compat with pre-serving/ imports.
"""

from __future__ import annotations

__all__ = ["EngineShutdown", "InferenceTimeout", "NoReplicaAvailable",
           "RequestCancelled", "ServingOverloaded", "ServingQueueFull"]


class InferenceTimeout(TimeoutError):
    """A per-request deadline expired before a result was ready."""


class ServingQueueFull(RuntimeError):
    """fail_fast admission control rejected a request (queue at limit)."""


class RequestCancelled(RuntimeError):
    """The caller cancelled a request before it finished."""


class EngineShutdown(RuntimeError):
    """The serving component stopped before this request finished."""


class NoReplicaAvailable(EngineShutdown):
    """The fleet router found no healthy replica to take a request (or
    to re-admit a migrated one). Subclasses :class:`EngineShutdown` so
    single-engine error handling written against the engine contract
    sees the same failure class behind a router."""


class ServingOverloaded(RuntimeError):
    """SLO-aware overload control refused this request: either shed from
    the queue under a sustained latency-SLO breach, or rejected at
    submit because its deadline provably cannot be met given the current
    queue estimate (early rejection beats wasted prefill). Retryable
    against a less-loaded replica, or later with backoff."""
